"""Model registry: named, versioned serving models with atomic hot swap.

The v1 server binds ONE model at construction; replacing it means a new
process and a cold bucket-warmup window — downtime. The registry makes
the model a named, versioned slot:

* ``register(name, source)`` / ``swap(name, source)`` fully LOAD,
  VALIDATE and (via the engine's ``prepare`` hook) STAGE + WARM the
  incoming version before anything observable changes; only then does
  the name flip to the new :class:`LoadedModel` under the lock. A
  corrupted or truncated npz (driver killed mid-write, partial copy)
  raises :class:`ModelLoadError` and the prior version keeps serving —
  the failure mode the validation exists for.
* Readers (the engine's submit path) resolve ``name -> LoadedModel``
  once per request and carry the reference: requests admitted before a
  swap finish on the version they were admitted against (the old
  union stays staged until its queue drains); requests admitted after
  the flip see the new version. There is no intermediate state — the
  flip is one dict assignment under the lock.

Versions are monotonic per name (1, 2, ...), exported as the
``serving_model_version`` gauge so a scrape can tell which version is
live without parsing logs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import threading
from typing import Callable, Optional, Union

import numpy as np

from dpsvm_tpu.models.multiclass import (CompactedEnsemble, MulticlassSVM,
                                         compact_models, ovo_vote_fold)
from dpsvm_tpu.models.svm_model import SVMModel


class ModelLoadError(ValueError):
    """A model file failed to load or validate. Raised BEFORE any
    registry state changes, so the live version is never disturbed."""


def _union_fingerprint(ens: CompactedEnsemble) -> str:
    """Content hash of the SV union rows — the coalescing identity.
    Two models whose unions are byte-identical (and share kernel and
    feature width) can have their queries answered by ONE kernel
    matmul with their coefficient columns stacked side by side, so
    this hash keys the scheduler's union groups. Computed once per
    registration (a few ms at MNIST-OvO scale), never on the request
    path."""
    sv = np.ascontiguousarray(ens.sv_union, np.float32)
    return hashlib.sha256(sv.tobytes()).hexdigest()[:16]


@dataclasses.dataclass(eq=False)  # identity semantics: sets/dicts key
class LoadedModel:                # on THE staged version, not its bytes
    """One registered model version: the loaded model object plus every
    derived fact the request path needs (so submit/dispatch never
    re-derive anything). Immutable after construction — a swap builds a
    NEW LoadedModel; it never mutates the live one."""

    name: str
    version: int
    source: str  # path or "<object>"
    model: Union[MulticlassSVM, SVMModel]
    ens: CompactedEnsemble
    strategy: str  # "binary" | "ovr" | "ovo"
    classes: Optional[np.ndarray]
    union_fp: str
    f64_cols: np.ndarray

    @property
    def kp(self):
        return self.ens.kernel

    @property
    def d(self) -> int:
        return int(self.ens.sv_union.shape[1])

    @property
    def k(self) -> int:
        return self.ens.n_models

    def group_key(self, dtype: str) -> tuple:
        """The coalescing family: models sharing (union bytes, kernel,
        feature width, storage dtype) answer from one staged union."""
        return (self.union_fp, int(self.ens.sv_union.shape[0]), self.d,
                self.kp, dtype)

    def labels(self, dec: np.ndarray) -> np.ndarray:
        """Decision columns -> predicted labels (strategy-aware; the
        PredictServer.labels semantics)."""
        if self.strategy == "binary":
            return np.where(dec[:, 0] >= 0, 1, -1).astype(np.int32)
        if self.strategy == "ovr":
            return self.classes[np.argmax(dec, axis=1)]
        return self.classes[np.argmax(
            ovo_vote_fold(dec, len(self.classes)), axis=1)]


def _validate_compacted(ens: CompactedEnsemble) -> None:
    """Structural consistency of the compacted arrays — a partial write
    can produce a loadable npz whose arrays disagree (e.g. a truncated
    coef matrix); serving it would crash mid-dispatch or, worse, gather
    wrong columns. Checked before the model is ever visible."""
    s = int(ens.sv_union.shape[0])
    k = ens.n_models
    if ens.coef.shape != (s, k):
        raise ModelLoadError(
            f"compacted coef shape {ens.coef.shape} disagrees with "
            f"sv_union rows {s} x {k} models")
    if ens.b.shape != (k,):
        raise ModelLoadError(
            f"compacted b shape {ens.b.shape} != ({k},)")
    if ens.idx.shape[0] != k or ens.coef_pad.shape != ens.idx.shape:
        raise ModelLoadError(
            f"compacted idx/coef_pad shapes {ens.idx.shape}/"
            f"{ens.coef_pad.shape} disagree ({k} models)")
    if s and (int(ens.idx.min()) < 0 or int(ens.idx.max()) >= s):
        raise ModelLoadError(
            f"compacted idx points outside the union "
            f"[{int(ens.idx.min())}, {int(ens.idx.max())}] vs {s} rows")
    if not (np.isfinite(ens.coef).all() and np.isfinite(ens.b).all()
            and np.isfinite(ens.sv_union).all()):
        raise ModelLoadError("compacted arrays hold non-finite values")


def load_model_file(path: str) -> Union[MulticlassSVM, SVMModel]:
    """Load a servable classifier model (.npz multiclass bundle or
    binary model, .txt binary) with the loud-failure contract: ANY
    loading problem — truncated zip, missing keys, zlib corruption in a
    member, wrong model_type — raises :class:`ModelLoadError` so the
    registry can refuse the file without disturbing the live version."""
    from dpsvm_tpu.testing import faults

    # swap_corrupt fault seam: when armed, this load reads a
    # deterministically corrupted copy of the file, so the REAL
    # validate/reject path below is what the chaos legs exercise —
    # never a mocked error. Identity when disarmed.
    path = faults.maybe_corrupt_model(path)
    try:
        if path.endswith(".npz"):
            z = np.load(path, allow_pickle=False)
            mt = str(z.get("model_type", ""))
            if mt in ("svr", "oneclass", "precomputed_svc"):
                raise ModelLoadError(
                    f"cannot serve a {mt} model (the serving engine is "
                    "the classifier decision path)")
            if mt == "multiclass" or ("n_models" in z and "strategy" in z):
                # Force every member array through the decompressor NOW:
                # np.load is lazy per member, so a file truncated inside
                # a compressed member would otherwise pass load and
                # crash at first dispatch.
                return MulticlassSVM.load(path)
            return SVMModel.load(path)
        return SVMModel.load(path)
    except ModelLoadError:
        raise
    except Exception as e:  # BadZipFile, zlib.error, KeyError, ...
        # Deliberately broad: the contract is "reject, keep serving" —
        # whatever shape the corruption takes, it must surface as the
        # one refusal type the registry handles, never escape and take
        # the engine down.
        raise ModelLoadError(f"cannot load model {path!r}: "
                             f"{type(e).__name__}: {e}") from e


def build_loaded(name: str, source, version: int) -> LoadedModel:
    """LoadedModel from a path or an in-memory model object (the
    object form is the test/bench convenience; files are the
    production path)."""
    from dpsvm_tpu.predict import AUTO_F64_RISK, decision_risk_columns

    if isinstance(source, str):
        model = load_model_file(source)
        src = source
    else:
        model, src = source, "<object>"
    if isinstance(model, MulticlassSVM):
        ens = model.ensure_compacted()
        if ens is None:
            raise ModelLoadError(
                f"model {name!r}: submodels do not share one kernel "
                "(mixed-kernel ensembles have no SV union to share)")
        strategy, classes = model.strategy, np.asarray(model.classes)
    elif isinstance(model, SVMModel):
        ens = compact_models([model])
        strategy, classes = "binary", None
    else:
        raise ModelLoadError(
            f"cannot serve a {type(model).__name__}; expected "
            "MulticlassSVM or SVMModel")
    _validate_compacted(ens)
    risks = decision_risk_columns(ens.coef)
    f64_cols = np.nonzero(risks >= AUTO_F64_RISK)[0]
    return LoadedModel(name=name, version=version, source=src,
                       model=model, ens=ens, strategy=strategy,
                       classes=classes,
                       union_fp=_union_fingerprint(ens),
                       f64_cols=f64_cols)


class RegistryJournal:
    """Durable record of the live model set (ISSUE 13 crash recovery).

    The registry itself is process memory — a crashed or restarted
    engine comes back EMPTY, which a millions-of-users front door
    cannot afford. The journal closes that gap with the minimum
    durable state: a JSON file holding {name -> model path + version},
    ATOMICALLY REWRITTEN (tmp + rename, the checkpoint discipline) on
    every register/swap/unregister, so it is always a complete,
    parseable snapshot of the live set — a kill at any instant leaves
    either the old snapshot or the new one, never a torn file.

    Replay (:meth:`ServingEngine` construction) re-registers each
    journaled (name, path) through the NORMAL validate-stage-warm
    path, seeding version counters so the rehydrated engine serves the
    exact pre-crash versions. Only file-backed models journal:
    in-memory model objects cannot be replayed, so they are recorded
    nowhere (the registry's entries() still serves them live)."""

    FORMAT_VERSION = 1

    def __init__(self, path: str):
        self.path = path

    def write(self, models: dict) -> None:
        """Atomically AND durably persist {name: {"source": path,
        "version": v}}: tmp + fsync + rename + directory fsync — the
        checkpoint.py discipline. Without the fsyncs the PR 13
        crash-recovery guarantee held against killed processes but
        not power loss (the rename could reach disk before the tmp
        file's data blocks)."""
        from dpsvm_tpu.utils.checkpoint import fsync_dir

        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".journal.tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump({"format_version": self.FORMAT_VERSION,
                           "models": models}, fh, indent=1)
                fh.flush()
                os.fsync(fh.fileno())  # data durable BEFORE the rename
            os.replace(tmp, self.path)
            fsync_dir(d)  # …and the rename itself durable after
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def load(self) -> dict:
        """The journaled {name: {"source", "version"}} map; {} when the
        journal does not exist yet. A corrupt/unreadable journal fails
        LOUDLY — silently serving an empty model set after a crash is
        exactly the failure mode the journal exists to prevent."""
        if not os.path.exists(self.path):
            return {}
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            raise ValueError(
                f"registry journal {self.path!r} is unreadable "
                f"({type(e).__name__}: {e}); refusing to start with a "
                "silently empty model set — repair or remove the "
                "journal explicitly") from e
        if int(doc.get("format_version", -1)) != self.FORMAT_VERSION:
            raise ValueError(
                f"registry journal {self.path!r} has format_version "
                f"{doc.get('format_version')!r}; this build writes "
                f"{self.FORMAT_VERSION}")
        return dict(doc.get("models", {}))


class ModelRegistry:
    """name -> live LoadedModel, with atomic replacement.

    ``prepare`` (the engine's hook) runs on the fully built incoming
    LoadedModel BEFORE it becomes visible: device staging and bucket
    warm-up happen there, so the first post-swap request pays neither
    an upload nor a compile (zero-downtime). If prepare raises, the
    registry is untouched."""

    def __init__(self, prepare: Optional[Callable] = None,
                 on_swap: Optional[Callable] = None,
                 journal: Optional[RegistryJournal] = None):
        self._lock = threading.Lock()
        self._live: dict = {}
        self._versions: dict = {}
        self._prepare = prepare
        self._on_swap = on_swap
        self._journal = journal
        # Journal writes run OUTSIDE self._lock (disk I/O must never
        # stall request routing, which takes self._lock on every
        # submit via get()). Publish order is preserved by snapshotting
        # under self._lock with a sequence number and skipping any
        # snapshot older than the last one written.
        self._journal_lock = threading.Lock()
        self._journal_seq = 0
        self._journal_written_seq = 0

    def attach_journal(self, journal: RegistryJournal) -> None:
        """Attach (and immediately snapshot to) a journal. The engine
        attaches AFTER replay — a journal attached during replay would
        be rewritten with each partially replayed subset, and a crash
        mid-replay would then SHRINK the durable record. An unwritable
        journal raises HERE (engine construction, no traffic yet):
        discovering it at the post-crash rehydrate would be too late."""
        with self._lock:
            self._journal = journal
            snap = self._journal_snapshot_locked()
        self._journal_publish(snap, strict=True)

    def _journal_snapshot_locked(self):
        """Snapshot the live set for the journal (caller holds
        self._lock; cheap — pure dict work, no I/O). Only file-backed
        entries are recorded: an in-memory object registration cannot
        be replayed, so journaling it would turn the next rehydrate
        into a hard error for state that was never durable to begin
        with. Returns (seq, payload, journal) or None."""
        if self._journal is None:
            return None
        self._journal_seq += 1
        return (self._journal_seq, {
            e.name: {"source": e.source, "version": e.version}
            for e in self._live.values() if e.source != "<object>"},
            self._journal)

    def _journal_publish(self, snap, strict: bool = False) -> None:
        """Write a snapshot taken by _journal_snapshot_locked to disk,
        outside the registry lock. A snapshot that lost the race to a
        newer one is dropped (the journal is a whole-set snapshot, so
        the newest write is always the full current truth). A write
        failure must NOT fail the registration that produced it — the
        in-memory registry is the serving truth and the flip has
        already happened — so it warns LOUDLY instead (a rotting
        journal means a post-crash rehydrate serves a stale set);
        ``strict`` (attach time) re-raises."""
        if snap is None:
            return
        seq, payload, journal = snap
        with self._journal_lock:
            if seq <= self._journal_written_seq:
                return
            try:
                journal.write(payload)
                self._journal_written_seq = seq
            except Exception as e:
                if strict:
                    raise
                import warnings

                warnings.warn(
                    f"registry journal write to {journal.path!r} "
                    f"FAILED ({type(e).__name__}: {e}); the live "
                    "model set is SERVING but NOT DURABLE — a crash "
                    "now rehydrates the previous journaled set. Fix "
                    "the journal path/disk and trigger any "
                    "register/swap to re-snapshot.", stacklevel=3)

    def register(self, name: str, source) -> LoadedModel:
        """Load + validate + prepare `source`, then atomically publish
        it as `name` (version = previous + 1). The load/validate/
        prepare work runs OUTSIDE the lock — a slow or failing load
        never blocks concurrent readers of other names, and a failure
        leaves the previous version serving (and burns no version
        number). The FINAL version is assigned under the lock at
        publish time, so concurrent swaps of one name get distinct,
        monotonic versions (last publish wins the slot)."""
        entry = build_loaded(name, source,
                             self._versions.get(name, 0) + 1)
        if self._prepare is not None:
            self._prepare(entry)
        with self._lock:
            version = self._versions.get(name, 0) + 1
            entry.version = version  # provisional -> final
            prev = self._live.get(name)
            self._live[name] = entry
            self._versions[name] = version
            snap = self._journal_snapshot_locked()
        self._journal_publish(snap)
        if prev is not None and self._on_swap is not None:
            self._on_swap(prev, entry)
        return entry

    def restore(self, name: str, source: str, version: int) -> LoadedModel:
        """Journal-replay registration: register `source` as `name`
        pinned at exactly `version` (the pre-crash version), through
        the same load/validate/prepare path as a live register. The
        version counter is seeded so monotonicity continues from the
        journaled history, not from 1."""
        with self._lock:
            self._versions[name] = max(self._versions.get(name, 0),
                                       int(version) - 1)
        return self.register(name, source)

    def swap(self, name: str, source) -> LoadedModel:
        """Hot-swap an EXISTING name to a new version (register with a
        must-exist check — a typo'd name must not silently create a
        second model)."""
        if name not in self._live:
            raise KeyError(f"no model {name!r} registered "
                           f"(have {sorted(self._live)})")
        return self.register(name, source)

    def get(self, name: Optional[str] = None) -> LoadedModel:
        from dpsvm_tpu.testing import faults

        with self._lock:
            # Seeded lock-contention probe: an armed lock_stall holds
            # THIS lock for a bounded interval (tools/faults_smoke.py
            # proves the serving path survives it).
            faults.lock_stall()
            if name is None:
                if len(self._live) != 1:
                    raise KeyError(
                        "model name required when "
                        f"{len(self._live)} models are registered "
                        f"(have {sorted(self._live)})")
                return next(iter(self._live.values()))
            try:
                return self._live[name]
            except KeyError:
                raise KeyError(f"no model {name!r} registered "
                               f"(have {sorted(self._live)})") from None

    def unregister(self, name: str) -> LoadedModel:
        with self._lock:
            try:
                entry = self._live.pop(name)
            except KeyError:
                raise KeyError(f"no model {name!r} registered") from None
            snap = self._journal_snapshot_locked()
        self._journal_publish(snap)
        return entry

    def names(self) -> list:
        with self._lock:
            return sorted(self._live)

    def entries(self) -> list:
        with self._lock:
            return list(self._live.values())

    def __len__(self) -> int:
        return len(self._live)

    def __contains__(self, name: str) -> bool:
        return name in self._live
