"""dpsvm_tpu.serving — serving engine v2 (ISSUE 10).

The PredictServer (serve.py) proved the serving shape: a compacted SV
union resident on device, power-of-two bucket executors, micro-batch
merging. This package grows that into a multi-model engine:

* :mod:`dpsvm_tpu.serving.registry`  — named, versioned models loaded
  from format_version-2 npz with atomic zero-downtime hot swap: the
  incoming version is fully validated, staged and warmed BEFORE the
  routing pointer flips; a corrupted/partial file never disturbs the
  live version.
* :mod:`dpsvm_tpu.serving.scheduler` — deadline-aware continuous
  batching: per-request deadlines, EDF-ordered batch forming that
  coalesces requests across models sharing one compacted union /
  kernel family into a single bucket dispatch, and backpressure that
  sheds expired work with an explicit deadline-miss verdict.
* :mod:`dpsvm_tpu.serving.dispatch`  — union-group device staging and
  the double-buffered async dispatcher (host-side batch forming for
  batch t+1 overlaps device compute for batch t — the ops/ooc.py
  double-buffer discipline applied to serving), plus the
  :class:`ServingEngine` frontend that ties the three together and
  exports the whole thing on /metrics and the serve run log.

The network front door (ISSUE 15) rides on top:

* :mod:`dpsvm_tpu.serving.wire`    — the length-prefixed binary frame
  protocol (clock-skew-safe deadline budgets, five-verdict contract).
* :mod:`dpsvm_tpu.serving.server`  — :class:`ServeServer`, the
  persistent-connection TCP endpoint: admission control, per-
  connection read/write bounds, protocol-error containment, graceful
  drain, exact verdict accounting.
* :mod:`dpsvm_tpu.serving.client`  — :class:`ServeClient`, bounded
  retry with backoff + jitter on connect/``rejected`` only (never on
  ``failed``/``expired`` — no duplicated compute).
* :mod:`dpsvm_tpu.serving.replicas` — :class:`ReplicaFleet` (ISSUE
  16), N engines behind one front door: lockstep model admin over a
  shared registry journal, rolling restarts, fleet /metrics. The
  engine core itself (union staging, bucket executors, async
  dispatch) lives in :mod:`dpsvm_tpu.serving.engine_core`, including
  the mesh-sharded union-group variant.

The closed-loop load generator driving this engine through the bench
regression gate is ``tools/loadgen.py`` (``--net`` drives it through
the socket path with connection-fault injection).
"""

from dpsvm_tpu.serving.client import ServeClient
from dpsvm_tpu.serving.dispatch import ServeResult, ServingEngine
from dpsvm_tpu.serving.registry import (LoadedModel, ModelLoadError,
                                        ModelRegistry, RegistryJournal,
                                        load_model_file)
from dpsvm_tpu.serving.replicas import ReplicaFleet
from dpsvm_tpu.serving.scheduler import Request, Scheduler
from dpsvm_tpu.serving.server import ServeServer

__all__ = [
    "ServingEngine", "ServeResult", "ModelRegistry", "RegistryJournal",
    "LoadedModel", "ModelLoadError", "load_model_file", "Scheduler",
    "Request", "ServeServer", "ServeClient", "ReplicaFleet",
]
