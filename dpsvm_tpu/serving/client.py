"""Client library for the network front door (ISSUE 15).

A thin wrapper over :mod:`dpsvm_tpu.serving.wire` (numpy + stdlib
sockets; no jax import of its own and never any device work — the
package ``__init__`` it rides in may import jax, an import-time cost
only): one persistent connection, synchronous request/verdict round
trips, and the ONLY retry policy that cannot duplicate compute:

* CONNECT failures (refused, reset before a full send, accept-dropped)
  retry with exponential backoff + seeded jitter up to
  ``connect_retries`` — the server never saw the request, so a retry
  is free.
* ``rejected`` verdicts retry up to ``reject_retries``, sleeping the
  server's ``retry_after_ms`` hint (never less than the local
  backoff) — the server explicitly promised it did no work.
* ``failed`` and ``expired`` verdicts are returned to the caller
  verbatim and NEVER retried: the server may have spent real compute
  on them, and the failure classes they represent (bad request, blown
  deadline) would not be cured by resending.
* A connection that dies AFTER a full send raises
  :class:`ConnectionDropped` — the request may be mid-flight on the
  server, so the library refuses to guess (the caller owns
  idempotency decisions).

DEADLINES cross the wire as remaining budget: the caller's
``deadline_ms`` is anchored once at the first attempt, and every
retry ships the budget MINUS the time already burned — a request that
exhausts its budget in backoff arrives with ~0 budget and is
explicitly expired by the server, never silently late.

The ``net_conn_drop`` / ``net_partial_write`` / ``net_read_stall``
fault seams (dpsvm_tpu/testing/faults.py) fire HERE, in the client:
the behaviors they model are things the wire does TO the server, so
arming them in the client drives the server's real read/write/
accounting paths.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Optional

import numpy as np

from dpsvm_tpu.serving import wire
from dpsvm_tpu.testing import faults


class ServeClientError(Exception):
    """Base class for front-door client failures."""


class ConnectError(ServeClientError, ConnectionError):
    """Could not establish a connection within the retry budget."""


class SendAborted(ServeClientError, ConnectionError):
    """The request frame was NOT fully sent (the server never accepted
    it) — safe to retry, but counted separately by chaos legs."""


class ConnectionDropped(ServeClientError, ConnectionError):
    """The connection died AFTER a full send, before the verdict: the
    request may be mid-flight server-side. NEVER retried by the
    library (duplicate compute)."""


class ServerDraining(ServeClientError):
    """A GOODBYE frame arrived: the server is draining. Anything still
    outstanding past the GOODBYE was never admitted — safe to retry
    against a live server."""


class ProtocolError(ServeClientError):
    """The server answered with an ERROR frame (we sent something it
    considers malformed) or sent bytes we cannot parse."""


class ServeClient:
    """One persistent front-door connection.

    ``request()`` returns the :class:`dpsvm_tpu.serving.wire.Verdict`
    the server produced (``served``/``late`` carry labels or decision
    columns; ``expired``/``rejected``/``failed`` carry accounting
    only). ``last_attempts`` exposes how many wire attempts the most
    recent request used (the reject-retry tests pin it)."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 30.0, connect_retries: int = 4,
                 reject_retries: int = 4, backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0, seed: int = 0):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.connect_retries = int(connect_retries)
        self.reject_retries = int(reject_retries)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._rng = random.Random(seed)
        self._sock: Optional[socket.socket] = None
        self._next_id = 1
        self.last_attempts = 0
        # Client-side accounting for the chaos legs' reconciliation:
        # frames FULLY sent (the server-side frames_accepted mirror)
        # and every verdict actually observed — including rejected
        # verdicts the retry loop swallows. Exactness contract (the
        # loadgen --net assert): per client,
        #   sum(verdicts_observed) + dropped + goodbyed == frames_sent
        # and across clients frames_sent totals the server's
        # frames_accepted.
        self.frames_sent = 0
        self.verdicts_observed = {v: 0 for v in wire.VERDICTS}

    # ---------------------------------------------------------- transport
    def _backoff(self, attempt: int) -> float:
        base = min(self.backoff_s * (2 ** attempt), self.max_backoff_s)
        return base + self._rng.uniform(0.0, base)

    def connect(self) -> None:
        """Establish (or re-establish) the connection, with bounded
        exponential backoff + jitter. Raises ConnectError when the
        budget is exhausted."""
        self.close()
        last = None
        for attempt in range(self.connect_retries + 1):
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout_s)
                sock.settimeout(self.timeout_s)
                # Wait for the server's HELLO banner: the TCP
                # handshake alone proves nothing (it completes in the
                # listen backlog) — EOF here means the server dropped
                # us AT ACCEPT, the one drop that is always safe to
                # retry.
                head = wire.recv_exact(sock, wire.HEADER_BYTES)
                ftype, length = wire.parse_header(head, 1 << 20)
                wire.recv_exact(sock, length)
                if ftype != wire.T_HELLO:
                    raise wire.WireError(
                        f"expected HELLO banner, got frame type "
                        f"{ftype}")
                self._sock = sock
                return
            except (OSError, wire.WireError) as e:
                last = e
                try:
                    sock.close()
                except (OSError, UnboundLocalError):
                    pass
                if attempt < self.connect_retries:
                    time.sleep(self._backoff(attempt))
        raise ConnectError(
            f"cannot connect to {self.host}:{self.port} after "
            f"{self.connect_retries + 1} attempts: {last}") from last

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------ request
    def request(self, rows, model: Optional[str] = None,
                deadline_ms: Optional[float] = None,
                want_decision: bool = False) -> wire.Verdict:
        """One request -> one verdict. Retries connect failures and
        ``rejected`` verdicts only (see module docstring); the
        remaining deadline budget shrinks across retries."""
        q = np.asarray(rows, np.float32)
        t0 = time.monotonic()
        attempts = 0
        while True:
            attempts += 1
            self.last_attempts = attempts
            if self._sock is None:
                self.connect()
            budget = deadline_ms
            if budget is not None:
                budget = max(
                    0.0, budget - (time.monotonic() - t0) * 1e3)
            req_id = self._next_id
            self._next_id += 1
            frame = wire.pack_request(req_id, q, model, budget,
                                      want_decision=want_decision)
            try:
                self._send_frame(frame)
            except SendAborted:
                self.close()
                raise
            except OSError as e:
                # A drain can close the socket mid-send with the
                # GOODBYE already sitting in our receive buffer —
                # surface THAT (an explicit, retry-safe signal), not a
                # drop.
                if self._goodbye_buffered():
                    self.close()
                    raise ServerDraining(
                        "server drained during send") from e
                # Otherwise sendall's failure point is unknowable —
                # part of the frame may have reached the server — so
                # treat it like a post-send drop, never a silent retry.
                self.close()
                raise ConnectionDropped(
                    f"connection died during send: {e}") from e
            self.frames_sent += 1
            # net_conn_drop fault seam: the frame is fully sent, then
            # the connection dies before the verdict is read — the
            # server's verdict becomes undeliverable; accounting must
            # still close (the loadgen chaos leg's contract).
            if faults.net_conn_drop():
                self.close()
                raise ConnectionDropped(
                    "injected fault at seam 'net_conn_drop' (socket "
                    "closed after send, before the verdict)")
            faults.net_read_stall()  # slow-reader seam: stall, then read
            verdict = self._read_verdict(req_id)
            if verdict.verdict == "rejected" \
                    and attempts <= self.reject_retries:
                hint_s = verdict.retry_after_ms / 1e3
                time.sleep(max(hint_s, self._backoff(attempts - 1)))
                continue
            return verdict

    def _send_frame(self, frame: bytes) -> None:
        # net_partial_write fault seam: HALF the frame goes out, then
        # the socket closes — the server must account a truncated
        # frame and kill only this connection.
        if faults.net_partial_write():
            try:
                self._sock.sendall(frame[:len(frame) // 2])
            except OSError:
                pass
            raise SendAborted(
                "injected fault at seam 'net_partial_write' "
                f"({len(frame) // 2}/{len(frame)} bytes sent)")
        self._sock.sendall(frame)

    def _goodbye_buffered(self) -> bool:
        """After a send failure: scan whatever frames are already in
        the receive buffer for a GOODBYE (drain closed the socket
        under us). Never blocks meaningfully; never counts verdicts
        (nothing is outstanding at send time)."""
        sock = self._sock
        if sock is None:
            return False
        try:
            sock.settimeout(0.05)
            while True:
                head = wire.recv_exact(sock, wire.HEADER_BYTES)
                ftype, length = wire.parse_header(head, 1 << 30)
                wire.recv_exact(sock, length)
                if ftype == wire.T_GOODBYE:
                    return True
        except Exception:
            return False

    def _read_verdict(self, req_id: int) -> wire.Verdict:
        while True:
            try:
                head = wire.recv_exact(self._sock, wire.HEADER_BYTES)
                ftype, length = wire.parse_header(
                    head, max_payload=1 << 30)
                payload = wire.recv_exact(self._sock, length)
            except (wire.ConnectionClosed, socket.timeout, OSError) as e:
                self.close()
                raise ConnectionDropped(
                    f"connection died awaiting verdict: {e}") from e
            except wire.WireError as e:
                self.close()
                raise ProtocolError(f"unparseable server frame: {e}") \
                    from e
            if ftype == wire.T_VERDICT:
                try:
                    v = wire.parse_verdict(payload)
                except wire.WireError as e:
                    self.close()
                    raise ProtocolError(
                        f"malformed verdict frame: {e}") from e
                if v.req_id == req_id:
                    self.verdicts_observed[v.verdict] += 1
                    return v
                continue  # a stale verdict (e.g. pre-drop pipelining)
            if ftype == wire.T_GOODBYE:
                self.close()
                raise ServerDraining(wire.parse_goodbye(payload)
                                     or "server draining")
            if ftype == wire.T_ERROR:
                self.close()
                _, msg = wire.parse_error(payload)
                raise ProtocolError(f"server refused the stream: {msg}")
            self.close()
            raise ProtocolError(f"unexpected frame type {ftype}")

    # ------------------------------------------------------- conveniences
    def predict(self, rows, model: Optional[str] = None,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        """Labels for `rows`, raising on any non-served verdict."""
        v = self.request(rows, model=model, deadline_ms=deadline_ms)
        if v.labels is None:
            raise ServeClientError(
                f"request ended {v.verdict!r}: {v.message}")
        return v.labels

    def decision(self, rows, model: Optional[str] = None,
                 deadline_ms: Optional[float] = None) -> np.ndarray:
        """Decision columns for `rows` (the bitwise rehydrate-proof
        path), raising on any non-served verdict."""
        v = self.request(rows, model=model, deadline_ms=deadline_ms,
                         want_decision=True)
        if v.decision is None:
            raise ServeClientError(
                f"request ended {v.verdict!r}: {v.message}")
        return v.decision
