"""Engine-core for the v2 serving engine: union staging, bucket
executors and decision contraction — the device half of
serving/dispatch.py, split from the engine-host half (submit/pump/
drain, registry, metrics) so each can scale on its own axis.

* :class:`UnionGroup` — the staged device operands for one coalescing
  family (registry.LoadedModel.group_key): ONE resident SV union +
  sv_sq, and the member models' dual-coefficient matrices stacked
  side by side into one (S, K_total) operand. A bucket dispatch then
  answers requests for EVERY member model with a single kernel matmul
  — the kernel work (the dominant term, serve.py's own motivation) is
  shared; each request slices its model's columns from the result.
  With ``ServeConfig.num_devices > 1`` the group stages MESH-sharded:
  union rows (and the matching stacked-coefficient rows) shard over
  the data mesh via parallel/mesh.py shard_padded_rows and one psum
  combines the partial decision columns — the PredictServer mesh
  machinery (serve._mesh_serve_executor, the SAME cached executor)
  promoted into the v2 engine, so covtype-scale unions stop being
  single-chip-bound. Zero pad rows carry zero coefficient rows, so
  the sharded contraction is exact; the tpulint ``serve_mesh_group``
  budget pins the dispatch to one psum + one kernel matmul and zero
  host callbacks. Groups restage only on registry mutations, never on
  the request path; the single-chip branch keeps reusing
  serve._dense_batch_factory, so those compiled bucket executors are
  the SAME programs tpulint budgets
  (serve_bucket/serve_coalesced_bucket).
* :class:`AsyncDispatcher` — at most one device batch in flight; the
  next batch is FORMED AND DISPATCHED before the previous batch's
  result is materialized, so host-side batch forming for batch t+1
  overlaps device compute for batch t (jax dispatch is asynchronous;
  ``np.asarray`` is the only blocking point — the ops/ooc.py
  double-buffer discipline applied to serving). An optional SERIAL
  device-time floor (ServeConfig.device_floor_us_per_row) emulates an
  accelerator-bound dispatch timeline on host-bound CI hardware — the
  replica-scaling benchmark's measurement regime.
* :func:`suggest_buckets` — the occupancy-driven report-only bucket
  advice (pure host function).
* :func:`_overwrite_f64` — exact host float64 evaluation of
  risk-routed columns (decision contraction's host tail).

serving/dispatch.py (the engine-host) re-exports all of these under
their historical names.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from dpsvm_tpu.config import ServeConfig
from dpsvm_tpu.obs import compilelog
from dpsvm_tpu.obs.trace import span
from dpsvm_tpu.serve import (_dense_batch_factory,
                             _dense_batch_int8_factory,
                             _mesh_serve_executor, effective_buckets,
                             resolve_union_storage, stage_union_host,
                             union_nbytes)
from dpsvm_tpu.serving.registry import LoadedModel
from dpsvm_tpu.testing import faults


class UnionGroup:
    """Staged device operands for one coalescing family.

    ``members`` is ordered; ``slices[entry]`` is entry's column range in
    the stacked coefficient operand. Built OFF the request path (at
    registration / swap prepare, before the routing flip) and warmed so
    post-build traffic never traces or uploads.

    ``mesh_devices`` is the number of devices the union rows shard
    over: 1 for the single-chip staging, ``config.num_devices`` for
    the mesh variant (whose decision columns the bitwise pin in
    tests/test_serve_replicas.py holds to the single-chip group).

    ``storage`` is the RESOLVED union storage token ('f32'|'bf16'|
    'int8') — dispatch.py resolves it per entry through the shared
    guard (serve.resolve_union_storage) and bakes it into the group
    key, so every member of a group staged here already accepted this
    storage; None (direct construction, tests) resolves here from the
    config request against the base member. int8 groups stage the
    per-row dequant scales alongside the rows — mesh-sharded WITH
    their row blocks (same P(DATA_AXIS) placement; the psum combine
    is unchanged)."""

    def __init__(self, key, members, config: ServeConfig,
                 storage: str = None):
        import jax.numpy as jnp

        self.key = key
        self.members = list(members)
        base = self.members[0].ens
        self.kp = base.kernel
        self.d = int(base.sv_union.shape[1])
        self.s_rows = int(base.sv_union.shape[0])
        buckets = config.buckets
        if buckets is None:
            from dpsvm_tpu.serve import resolve_buckets
            buckets, _ = resolve_buckets(config)
        self.buckets = effective_buckets(buckets, self.s_rows)
        self.mesh_devices = 1
        if storage is None:
            storage, _ = resolve_union_storage(
                base, self.kp, config.effective_union_storage())
        self.union_storage = storage
        self.union_bytes = union_nbytes(storage, self.s_rows, self.d)
        self.slices: dict = {}
        lo = 0
        coefs, bs = [], []
        for m in self.members:
            self.slices[m] = slice(lo, lo + m.k)
            coefs.append(np.ascontiguousarray(m.ens.coef, np.float32))
            bs.append(np.ascontiguousarray(m.ens.b, np.float32))
            lo += m.k
        self.k_total = lo
        self.b_host = np.concatenate(bs)
        if self.s_rows == 0:
            # Degenerate all-empty union: the decision is exactly -b;
            # no device operands, no executor.
            self._call = None
            return
        sv = np.ascontiguousarray(base.sv_union, np.float32)
        # Norms from the ROUNDED/DEQUANTIZED rows — the dot operands'
        # values (the serve.py _stage discipline, shared helper).
        sv_store, sv_scale, sv_sq = stage_union_host(sv, storage)
        if config.num_devices > 1:
            from dpsvm_tpu.parallel.mesh import (replicate_array,
                                                 shard_padded_rows)

            mesh, mapped = _mesh_serve_executor(
                config.num_devices, self.kp, storage)
            self.mesh_devices = int(mesh.size)
            # Pad rows are zeros with ZERO coefficient rows — inert in
            # the psum'd contraction (the shard_padded_rows contract),
            # so the sharded decision equals the single-chip one.
            sv_d = shard_padded_rows(mesh, sv_store)
            sv_sq_d = shard_padded_rows(mesh, sv_sq)
            coef_d = shard_padded_rows(mesh, np.hstack(coefs))
            b_d = replicate_array(mesh, self.b_host)
            if storage == "int8":
                scale_d = shard_padded_rows(mesh, sv_scale)

                def call(qb, _m=mapped, _mesh=mesh):
                    return _m(replicate_array(_mesh, qb), sv_d,
                              scale_d, sv_sq_d, coef_d, b_d)
            else:
                def call(qb, _m=mapped, _mesh=mesh):
                    return _m(replicate_array(_mesh, qb),
                              sv_d, sv_sq_d, coef_d, b_d)
        else:
            sv_d = jnp.asarray(sv_store)
            sv_sq_d = jnp.asarray(sv_sq)
            coef_d = jnp.asarray(np.hstack(coefs))
            b_d = jnp.asarray(self.b_host)
            if storage == "int8":
                batch = _dense_batch_int8_factory()
                scale_d = jnp.asarray(sv_scale)

                def call(qb, _kp=self.kp):
                    return batch(jnp.asarray(qb), sv_d, scale_d,
                                 sv_sq_d, coef_d, b_d, _kp)
            else:
                batch = _dense_batch_factory()

                def call(qb, _kp=self.kp):
                    return batch(jnp.asarray(qb), sv_d, sv_sq_d,
                                 coef_d, b_d, _kp)

        self._call = call

    def member_set(self) -> set:
        return set(self.members)

    def warm(self) -> None:
        """Compile + touch every bucket executor on zero queries so the
        first live request after a (re)stage pays neither."""
        for bucket in self.buckets:
            np.asarray(self.dispatch(
                np.zeros((bucket, self.d), np.float32), bucket))

    def dispatch(self, qb: np.ndarray, bucket: int):
        """One async bucket dispatch of a (bucket, d) padded batch ->
        (bucket, K_total) decision columns (device array — NOT yet
        materialized; np.asarray is the caller's blocking point)."""
        if self._call is None:
            return np.broadcast_to(
                -self.b_host, (qb.shape[0], self.k_total)).astype(
                np.float32)
        with compilelog.label(f"serve/bucket{bucket}",
                              f"({bucket},{self.d})"), \
                span(f"serve/bucket{bucket}"):
            return self._call(qb)


class AsyncDispatcher:
    """At most one in-flight device batch; issuing the next collects
    the previous. The issue->collect interval spans the NEXT batch's
    host-side forming — that overlap is the point — so the honest
    per-dispatch cost recorded is the time actually spent BLOCKING on
    materialization (``wait_s``), not the interval.

    Completed items are 5-tuples ``(meta, rows, wait_s, window_s,
    error)``: ``error`` is None on success, else a human-readable
    reason and ``rows`` is None — the engine fails that batch with
    explicit 'failed' verdicts and keeps serving (ISSUE 13). With
    ``timeout_s`` set (ServeConfig.dispatch_timeout_ms), the blocking
    materialization runs on a watchdog thread and a batch not
    materialized within the bound is failed the same way — a wedged
    device dispatch costs one batch, never the pump thread.

    ``floor_us_per_row`` (ServeConfig.device_floor_us_per_row) imposes
    a SERIAL emulated device timeline: each successful dispatch
    completes no earlier than the previous one's emulated completion
    plus ``padded_rows * floor`` — a sleep (GIL released), not spin —
    so on host-bound CI hardware the dispatcher behaves like one
    serial accelerator per engine and the replica frontier measures
    front-door scale-out rather than host-CPU contention. The floor is
    charged per PADDED row: on the emulated device, padding costs
    device time exactly as it does on a real one."""

    def __init__(self, timeout_s: Optional[float] = None,
                 floor_us_per_row: Optional[float] = None):
        self._inflight = None  # (device result, meta, t_issue, padded)
        self._timeout = timeout_s
        self._floor = (None if floor_us_per_row is None
                       else floor_us_per_row / 1e6)
        self._dev_free_t = 0.0  # emulated device's serial-free time

    @property
    def busy(self) -> bool:
        return self._inflight is not None

    def issue(self, group: UnionGroup, qb: np.ndarray, bucket: int,
              meta) -> list:
        """Dispatch (async), then materialize the PREVIOUS in-flight
        batch. Returns the completed 5-tuples (0, 1 or — when this
        batch's dispatch itself raises — 2 items)."""
        prev = self._inflight
        try:
            # serve_dispatch fault seam: an injected dispatch
            # exception at batch K (deliberately NOT armed inside
            # UnionGroup.dispatch — warm-up calls must never fault).
            if faults.arrive("serve_dispatch"):
                raise RuntimeError(
                    "injected fault at seam 'serve_dispatch'")
            dev = group.dispatch(qb, bucket)
        except Exception as e:
            self._inflight = None
            out = self._materialize(prev)
            out.append((meta, None, 0.0, 0.0,
                        f"dispatch raised {type(e).__name__}: {e}"))
            return out
        self._inflight = (dev, meta, time.perf_counter(), qb.shape[0])
        return self._materialize(prev)

    def drain(self) -> list:
        out = self._materialize(self._inflight)
        self._inflight = None
        return out

    def _materialize(self, item) -> list:
        if item is None:
            return []
        dev, meta, t_issue, padded_rows = item
        t0 = time.perf_counter()
        if self._timeout is None:
            try:
                rows, err = np.asarray(dev), None
            except Exception as e:
                rows, err = None, (f"materialization raised "
                                   f"{type(e).__name__}: {e}")
        else:
            # Bounded wait: the blocking np.asarray runs on a daemon
            # watchdog thread. On timeout the batch is FAILED and the
            # pump moves on; the orphaned thread finishes (or never
            # does — a truly wedged runtime) without holding anything
            # the engine needs. The serve_stall fault seam fires in
            # the waiting thread, modeling exactly that wedge.
            box: dict = {}

            def _pull():
                try:
                    faults.serve_stall()
                    box["rows"] = np.asarray(dev)
                except Exception as e:  # pragma: no cover - rare path
                    box["err"] = (f"materialization raised "
                                  f"{type(e).__name__}: {e}")

            th = threading.Thread(target=_pull, daemon=True,
                                  name="dpsvm-dispatch-watchdog")
            th.start()
            th.join(self._timeout)
            if th.is_alive():
                rows, err = None, (
                    f"dispatch watchdog: batch not materialized within "
                    f"{self._timeout * 1e3:.0f} ms; failing the batch "
                    "and serving on")
            elif "err" in box:
                rows, err = None, box["err"]
            else:
                rows, err = box["rows"], None
        if self._floor is not None and err is None:
            # Serial emulated device: this dispatch starts when the
            # device went free (or when it was issued, if later) and
            # takes floor * padded_rows of device time.
            done_t = (max(t_issue, self._dev_free_t)
                      + self._floor * padded_rows)
            self._dev_free_t = done_t
            now = time.perf_counter()
            if done_t > now:
                time.sleep(done_t - now)
        t1 = time.perf_counter()
        return [(meta, rows, t1 - t0, t1 - t_issue, err)]


def suggest_buckets(row_samples, current_buckets) -> dict:
    """Occupancy-driven ``ServeConfig.buckets`` suggestion (ISSUE 14
    satellite — the ROADMAP item 2 stub closed, report-only).

    `row_samples` are observed LIVE rows per dispatch (the engine's
    batch_rows histogram window); `current_buckets` the configured
    power-of-two ladder. The suggestion is the smallest ladder whose
    rungs sit at the next power of two above the traffic's p25/p50/
    p75/p95 marks (top bucket always kept — it caps segment size), and
    the record carries the PROJECTED mean occupancy under both ladders
    so the advice is adjudicable before anyone applies it.

    Pure function of host-held values — unit-testable, zero device
    work. Applying a suggestion stays behind the profile discipline:
    the autotune ``serve_buckets`` probe measures whether dispatch
    cost even tracks the bucket on this device (a latency-floored
    device makes padding free, and then FEWER buckets win on compile
    count)."""
    rows = np.asarray(list(row_samples), np.float64)
    rows = rows[rows > 0]
    current = tuple(int(b) for b in current_buckets)
    if rows.size == 0:
        return {"current_buckets": list(current),
                "suggested_buckets": None,
                "note": "no dispatches observed"}
    top = current[-1]

    def pow2_at_least(v):
        return 1 << max(0, int(np.ceil(np.log2(max(float(v), 1.0)))))

    marks = {f"p{q}": float(np.percentile(rows, q))
             for q in (25, 50, 75, 95)}
    ladder = sorted({min(pow2_at_least(v), top)
                     for v in marks.values()} | {top})

    def projected_occupancy(buckets):
        b = np.asarray(buckets, np.float64)
        # First bucket that fits each dispatch (observed rows never
        # exceed the top bucket: oversized requests are segmented).
        idx = np.minimum(np.searchsorted(b, rows), len(b) - 1)
        return round(float(np.mean(rows / b[idx])), 4)

    return {
        "current_buckets": list(current),
        "suggested_buckets": [int(b) for b in ladder],
        "observed_rows": {**{k: round(v, 1) for k, v in marks.items()},
                          "max": int(rows.max()),
                          "dispatches": int(rows.size)},
        "projected_occupancy": {
            "current": projected_occupancy(current),
            "suggested": projected_occupancy(ladder)},
        "note": ("applied automatically between legs only when "
                 "buckets=None and the autotune serve_buckets probe "
                 "says right-sizing pays on this device; otherwise "
                 "report-only"),
    }


def _overwrite_f64(entry: LoadedModel, q, dec: np.ndarray) -> None:
    """Exact host float64 evaluation of an entry's risk-routed columns
    (the serve.py _overwrite_f64 algebra via the one shared f64 kernel
    definition). ``q`` is the CALLER'S rows — float64 requests stay
    exact (unquantized) on these columns."""
    from dpsvm_tpu.solver.reconstruct import gram_matvec_f64

    q64 = np.asarray(q, np.float64)
    for j in entry.f64_cols:
        dec[:, j] = (gram_matvec_f64(entry.ens.sv_union,
                                     entry.ens.coef[:, j], entry.kp,
                                     queries=q64)
                     - float(entry.ens.b[j])).astype(np.float32)
