"""Engine-host for the v2 serving engine: the ServingEngine frontend —
registry + scheduler + dispatcher behind a submit/pump/drain API, with
always-on instruments (queue depth, deadline misses, hot swaps, batch
occupancy), the serve run log (one chunk record per dispatch), and the
/metrics endpoint.

The device half — union staging, bucket executors, decision
contraction (:class:`UnionGroup`, :class:`AsyncDispatcher`,
:func:`suggest_buckets`, :func:`_overwrite_f64`) — lives in
serving/engine_core.py and is re-exported here under its historical
names. The split is the two scaling axes made explicit: engine-core
scales DOWN into the mesh (union rows sharded over devices,
ServeConfig.num_devices), engine-host scales OUT into the replica
fleet (N engines behind one front door, serving/replicas.py)."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

import numpy as np

from dpsvm_tpu.config import ServeConfig
from dpsvm_tpu.obs import compilelog, run_obs
from dpsvm_tpu.obs import export as openmetrics
from dpsvm_tpu.obs.metrics import Registry
from dpsvm_tpu.serve import (resolve_buckets, resolve_union_storage,
                             union_nbytes)
from dpsvm_tpu.serving.engine_core import (AsyncDispatcher,  # noqa: F401
                                           UnionGroup, _overwrite_f64,
                                           suggest_buckets)
from dpsvm_tpu.serving.registry import (LoadedModel, ModelRegistry,
                                        RegistryJournal)
from dpsvm_tpu.serving.scheduler import Request, Scheduler


@dataclasses.dataclass
class ServeResult:
    """One completed request. ``verdict``:
      "ok"      — served, completed within its deadline (or none set);
      "late"    — served, but completed PAST its deadline: the decision
                  rows are real, and the request counts as a deadline
                  miss (admitted-past-deadline work is counted, never
                  silently served late);
      "expired" — shed at batch-forming time (deadline already passed
                  before any device work): no decision rows, counted.
      "failed"  — the batch's device dispatch raised or tripped the
                  dispatch watchdog (ServeConfig.dispatch_timeout_ms):
                  no decision rows, counted per model
                  (serve_dispatch_failures); the engine keeps serving
                  subsequent batches — an explicit verdict, never a
                  hung pump thread (ISSUE 13).

    ``entry`` is the LoadedModel THAT SERVED the request (the version
    resolved at submit) — label folding must use it, not a fresh
    registry lookup: after a hot swap the live entry may have a
    different class set/strategy than the one whose columns these are.
    """

    ticket: int
    model: str
    version: int
    decision: Optional[np.ndarray]
    verdict: str
    latency_s: float
    entry: object = dataclasses.field(default=None, repr=False)

    def labels(self) -> Optional[np.ndarray]:
        """Predicted labels via the SERVING version's fold (None for
        expired requests)."""
        if self.decision is None:
            return None
        return self.entry.labels(self.decision)

    @property
    def ok(self) -> bool:
        return self.verdict == "ok"

    @property
    def deadline_missed(self) -> bool:
        return self.verdict in ("late", "expired")

    @property
    def failed(self) -> bool:
        return self.verdict == "failed"


class ServingEngine:
    """Multi-model serving engine v2: model registry with zero-downtime
    hot swap, deadline-aware continuous batching, async dispatch.

    Request path: ``submit(rows, model=..., deadline_ms=...) ->
    ticket``; ``pump()`` runs one scheduling step (form the earliest-
    deadline batch, dispatch it async, complete whatever finished);
    ``drain()`` pumps until idle and returns every completed
    {ticket: ServeResult}; ``results()`` pops completions without
    blocking. With ``config.num_devices > 1`` the union groups stage
    MESH-sharded (engine_core.UnionGroup's mesh variant — the
    PredictServer machinery promoted into this engine); ``replica`` is
    the engine's index inside a serving/replicas.py ReplicaFleet
    (stamped into the run-log manifest so ``obs report`` can tell the
    replicas apart), None for a standalone engine."""

    def __init__(self, config: ServeConfig = ServeConfig(),
                 replica: Optional[int] = None):
        self.config = config
        self.replica = None if replica is None else int(replica)
        # Bucket-ladder resolution (ISSUE 17 second axis): explicit
        # config wins; buckets=None resolves through the DeviceProfile
        # serve_buckets gate. The provenance records the source, and —
        # with an authoritative pays verdict — arms the occupancy
        # auto-apply (maybe_apply_bucket_suggestion, run between
        # serving legs by drain()).
        ladder, self.bucket_provenance = resolve_buckets(config)
        self._bucket_ladder = tuple(ladder)
        self.scheduler = Scheduler()
        self.registry = ModelRegistry(prepare=self._prepare_entry,
                                      on_swap=self._on_swap)
        self._groups: dict = {}
        self._dispatcher = AsyncDispatcher(
            timeout_s=(None if config.dispatch_timeout_ms is None
                       else config.dispatch_timeout_ms / 1e3),
            floor_us_per_row=config.device_floor_us_per_row)
        self._done: dict = {}
        self._next_ticket = 0
        self._dispatches = 0
        self._rows_total = 0
        self._closing = False
        self._closed = False
        # Lifecycle lock (ISSUE 15 satellite): drain() and close()
        # serialize on it, so close() during an active drain() waits
        # for the drain to finish and then tears down exactly once —
        # double-shutdown is idempotent from any interleaving, and a
        # scrape during a drain reads live instruments (never torn:
        # only close() flips _closing, under this lock). RLock because
        # close() itself drains.
        self._lifecycle = threading.RLock()
        # The attached network front door (serving/server.py), if any:
        # its counters join snapshot() and the /metrics exposition so
        # one scrape carries one truth.
        self._front = None

        # Always-on instruments (the PredictServer discipline): one
        # Registry per engine; percentiles everywhere come from THESE
        # histograms — loadgen, /metrics and the run log cannot
        # disagree.
        self.metrics = Registry(enabled=True)
        self.request_seconds = self.metrics.histogram(
            "serve.request_seconds")
        self.dispatch_seconds = self.metrics.histogram(
            "serve.dispatch_seconds")
        self.batch_occupancy = self.metrics.histogram(
            "serve.batch_occupancy")
        # Absolute live rows per dispatch (the occupancy histogram's
        # numerator): what the report-only bucket_suggestion() reads —
        # occupancy alone cannot recover WHICH bucket sizes the
        # traffic actually needs (ISSUE 14 satellite, ROADMAP item 2).
        self.batch_rows = self.metrics.histogram("serve.batch_rows")
        self.deadline_misses = self.metrics.counter(
            "serve.deadline_misses_total")
        self.expired = self.metrics.counter("serve.expired_total")
        self.hot_swaps = self.metrics.counter("serve.hot_swaps_total")
        self.coalesced = self.metrics.counter(
            "serve.coalesced_dispatches_total")
        self.compiles = self.metrics.counter("serve.compiles_total")
        self.dispatch_failures = self.metrics.counter(
            "serve.dispatch_failures_total")
        self.watchdog_trips = self.metrics.counter(
            "serve.watchdog_trips_total")
        self._per_model: dict = {}

        # Compile accounting, scoped to THIS engine's own dispatches
        # (the serve.py weakref-sink pattern: close() was never
        # mandatory, so the sink must not pin the engine). The scope
        # flag is THREAD-LOCAL: an admin thread warming a swap's group
        # runs concurrently with the serving thread's dispatches, and
        # compiles fire synchronously on the compiling thread — a
        # shared bool would let one thread's finally-reset hide the
        # other thread's compile from the counter.
        import weakref

        self._tl = threading.local()
        self._prep_lock = threading.Lock()
        self._preparing = 0  # in-flight swap preparations (admin thread)
        ref = weakref.ref(self)

        def _compile_sink(name, shape, secs, _ref=ref):
            eng = _ref()
            if eng is None:
                compilelog.remove_sink(_compile_sink)
                return
            if getattr(eng._tl, "in_dispatch", False) \
                    and name.startswith("serve/"):
                eng.compiles.add(1)

        self._compile_sink = _compile_sink
        compilelog.add_sink(self._compile_sink)

        self._obs = run_obs("serve", config,
                            meta={"engine": "serving_v2",
                                  "buckets": list(self._bucket_ladder),
                                  "bucket_source":
                                      self.bucket_provenance["source"],
                                  "dtype": config.dtype,
                                  "union_storage":
                                      config.effective_union_storage(),
                                  "deadline_ms": config.deadline_ms,
                                  **({"replica": self.replica}
                                     if self.replica is not None
                                     else {})})
        self.exporter = None
        if config.metrics_port is not None:
            def _render(_ref=ref):
                eng = _ref()
                if eng is None or eng._closing:
                    # A scrape racing close(): the minimal valid
                    # exposition, never a half-torn-down read.
                    return "# EOF\n"
                return eng.render_openmetrics()

            self.exporter = openmetrics.MetricsExporter(
                _render, port=config.metrics_port,
                host=config.metrics_host)

        # Crash recovery (ISSUE 13): replay the registry journal, then
        # attach it. Replay runs BEFORE attach so a crash mid-replay
        # can never rewrite the durable record with a partial subset;
        # each journaled model re-registers through the normal
        # validate-stage-warm path at its exact pre-crash version, so
        # the rehydrated engine serves decisions identical to the one
        # that died. A missing/corrupt journaled model file fails
        # construction LOUDLY (ModelLoadError) — silently coming up
        # with a hole in the model set is the failure mode the journal
        # exists to prevent.
        self.journal = None
        self._rehydrated: list = []
        if config.journal_path:
            try:
                journal = RegistryJournal(config.journal_path)
                entries = journal.load()
                for name in sorted(entries):
                    rec = entries[name]
                    self.registry.restore(name, rec["source"],
                                          int(rec["version"]))
                    self._model_metrics(name)
                    self._rehydrated.append(name)
                if self._rehydrated:
                    self._obs.event(
                        "rehydrate", models=list(self._rehydrated),
                        versions={n: int(entries[n]["version"])
                                  for n in self._rehydrated})
                self.registry.attach_journal(journal)
                self.journal = journal
            except BaseException:
                # Failed construction: close() is unreachable on a
                # half-built engine, so tear down the already-started
                # pieces here — a leaked exporter keeps the metrics
                # port bound ('Address already in use' on every
                # construction retry) and a leaked sink/run log
                # accumulates per attempt.
                self._closing = True
                if self.exporter is not None:
                    self.exporter.close()
                compilelog.remove_sink(self._compile_sink)
                self._obs.finish(aborted=True)
                raise

    # ------------------------------------------------------ registration
    def _storage_of(self, entry: LoadedModel) -> str:
        """The entry's RESOLVED union storage (ISSUE 17): the config's
        requested storage adjudicated per model by the shared guard
        (serve.resolve_union_storage — a refused int8 request falls
        back loudly; auto picks the narrowest accepted storage).
        Resolved ONCE per entry and cached on it: the token is part of
        the entry's group key, so two models whose guard verdicts
        differ stage in DIFFERENT groups and a hot swap between
        storage dtypes restages correctly."""
        st = getattr(entry, "union_storage", None)
        if st is None:
            st, guard = resolve_union_storage(
                entry.ens, entry.kp,
                self.config.effective_union_storage(), stacklevel=7)
            entry.union_storage = st
            entry.storage_guard = guard
            if guard.get("note"):
                self._obs.event("storage_guard", model=entry.name,
                                version=entry.version, **guard)
        return st

    def _group_config(self) -> ServeConfig:
        """The config union groups stage under: the engine's CURRENT
        bucket ladder substituted for a ``buckets=None`` marker (the
        auto-apply path swaps the ladder between legs)."""
        if self.config.buckets == self._bucket_ladder:
            return self.config
        return self.config.replace(buckets=self._bucket_ladder)

    def _members_for(self, key, extra=None) -> list:
        """Current membership of a union group: live registry entries
        plus entries still holding queued work (an old version keeps
        its columns staged across a swap until its queue drains), plus
        the incoming entry when preparing a swap."""
        seen: list = []
        for e in self.registry.entries():
            if e.group_key(self._storage_of(e)) == key \
                    and e not in seen:
                seen.append(e)
        for e in self.scheduler.pending_entries():
            if e.group_key(self._storage_of(e)) == key \
                    and e not in seen:
                seen.append(e)
        if extra is not None and extra not in seen:
            seen.append(extra)
        return seen

    def _prepare_entry(self, entry: LoadedModel) -> None:
        """Registry prepare hook: stage + warm the incoming version's
        union group BEFORE the routing pointer flips — the
        zero-downtime half of the hot-swap contract. Storage
        resolution (_storage_of) runs the quality guard here: a
        refused narrow storage warns during registration, off the
        request path."""
        storage = self._storage_of(entry)
        with self._prep_lock:
            self._preparing += 1  # parks _gc_groups: the GC must not
        try:                      # shrink away a group being prepared
            key = entry.group_key(storage)
            group = UnionGroup(key,
                               self._members_for(key, extra=entry),
                               self._group_config(), storage=storage)
            self._tl.in_dispatch = True
            try:
                group.warm()
            finally:
                self._tl.in_dispatch = False
            # Publish the staged group. In-flight dispatches captured
            # their group object; queued requests of existing members
            # route here (a superset staging — their column slices are
            # present).
            self._groups[key] = group
        finally:
            with self._prep_lock:
                self._preparing -= 1

    def _on_swap(self, prev: LoadedModel, new: LoadedModel) -> None:
        self.hot_swaps.add(1)
        self._model_metrics(new.name)["swaps"].add(1)
        self._obs.event("hot_swap", model=new.name,
                        from_version=prev.version,
                        to_version=new.version,
                        union_changed=prev.union_fp != new.union_fp)

    def register(self, name: str, source) -> LoadedModel:
        entry = self.registry.register(name, source)
        self._obs.event("register", model=name, version=entry.version,
                        k=entry.k, d=entry.d,
                        n_union=int(entry.ens.n_union))
        self._model_metrics(name)  # instruments exist before traffic
        return entry

    def swap(self, name: str, source) -> LoadedModel:
        return self.registry.swap(name, source)

    def unregister(self, name: str) -> LoadedModel:
        return self.registry.unregister(name)

    # ----------------------------------------------------------- metrics
    def _model_metrics(self, name: str) -> dict:
        m = self._per_model.get(name)
        if m is None:
            m = {
                "requests": self.metrics.counter(
                    f"serve.requests.{name}"),
                "rows": self.metrics.counter(f"serve.rows.{name}"),
                "misses": self.metrics.counter(
                    f"serve.deadline_misses.{name}"),
                "expired": self.metrics.counter(
                    f"serve.expired.{name}"),
                "swaps": self.metrics.counter(f"serve.swaps.{name}"),
                "failures": self.metrics.counter(
                    f"serve.dispatch_failures.{name}"),
                "latency": self.metrics.histogram(
                    f"serve.request_seconds.{name}"),
            }
            self._per_model[name] = m
        return m

    # ------------------------------------------------------------ submit
    _DEADLINE_DEFAULT = object()  # sentinel: "use the config default"

    def submit(self, rows, model: Optional[str] = None,
               deadline_ms=_DEADLINE_DEFAULT) -> int:
        """Admit one request. ``model`` may be omitted when exactly one
        model is registered. ``deadline_ms``: omitted = the config
        default; an explicit number overrides it; an explicit ``None``
        means NO deadline for this request even when the config sets
        one (the synchronous decision()/predict() conveniences use
        this — they must never have their answer shed). Returns the
        ticket whose ServeResult a later pump/drain completes.
        Crossing ``max_pending`` queued rows forces scheduling steps
        until the queue is back under the bound (backpressure)."""
        entry = self.registry.get(model)
        q = np.asarray(rows)
        if q.ndim != 2 or q.shape[1] != entry.d:
            raise ValueError(
                f"queries for model {entry.name!r} must be "
                f"(n, {entry.d}); got {q.shape}")
        if deadline_ms is self._DEADLINE_DEFAULT:
            deadline_ms = self.config.deadline_ms
        now = time.perf_counter()
        ticket = self._next_ticket
        self._next_ticket += 1
        self.scheduler.submit(
            entry, q, now,
            None if deadline_ms is None else deadline_ms / 1e3,
            ticket, self._storage_of(entry))
        mm = self._model_metrics(entry.name)
        mm["requests"].add(1)
        mm["rows"].add(q.shape[0])
        while self.scheduler.queue_rows >= self.config.max_pending:
            self.pump()
        return ticket

    # -------------------------------------------------------- scheduling
    def pump(self) -> int:
        """One scheduling step: form the earliest-deadline group's next
        batch (shedding expired requests), dispatch it asynchronously,
        and complete whatever previous dispatch finished. Returns the
        number of requests completed by this call; results accumulate
        for :meth:`results`/:meth:`drain`."""
        completed = 0
        now = time.perf_counter()
        key = self.scheduler.next_key()
        if key is None:
            for item in self._dispatcher.drain():
                completed += self._complete_batch(item)
            # Idle moment: retire drained-out union groups here too —
            # a pump()/results()-driven server under sustained traffic
            # may never call drain(), and staged unions must not
            # accumulate across hot swaps.
            if not self._dispatcher.busy:
                self._gc_groups()
            return completed
        group = self._group_for(key)
        batch, expired = self.scheduler.form(key, now,
                                             group.buckets[-1])
        for req in expired:
            self._finish_expired(req)
            completed += 1
        if batch:
            completed += self._dispatch_batch(group, batch)
        elif not self.scheduler.queue_depth:
            for item in self._dispatcher.drain():
                completed += self._complete_batch(item)
        return completed

    def drain(self) -> dict:
        """Pump until every queued request and in-flight batch is
        complete; returns (and pops) all completed results. Serialized
        against close() on the lifecycle lock: a close() racing an
        active drain waits for it, and a drain() after close is a
        no-op returning whatever already completed — double-shutdown
        from any interleaving is idempotent."""
        with self._lifecycle:
            if self._closed:
                return self.results()
            while self.scheduler.queue_depth or self._dispatcher.busy:
                self.pump()
            self._gc_groups()
            # Between-legs idle moment: the only place the profile-
            # gated bucket auto-apply may swap the ladder (queues are
            # empty, nothing staged is mid-flight).
            self.maybe_apply_bucket_suggestion()
            return self.results()

    def results(self) -> dict:
        """Pop everything completed so far: {ticket: ServeResult}."""
        done = self._done
        self._done = {}
        return done

    def _group_for(self, key) -> UnionGroup:
        """The staged group for a key — normally staged by the prepare
        hook; restaged here only if a queued request's entry is not in
        the staged member set (possible after an unregister), or if
        the bucket ladder changed under the auto-apply."""
        group = self._groups.get(key)
        needed = {e for e in self.scheduler.pending_entries()
                  if e.group_key(self._storage_of(e)) == key}
        if group is None or not needed <= group.member_set():
            group = UnionGroup(key, self._members_for(key),
                               self._group_config(), storage=key[-1])
            self._tl.in_dispatch = True
            try:
                group.warm()
            finally:
                self._tl.in_dispatch = False
            self._groups[key] = group
        return group

    def _gc_groups(self) -> None:
        """Idle-time retirement (queues are empty here): drop groups
        with no live member, and restage groups still carrying a
        drained old version's columns — staged unions must not
        accumulate across many swaps. No-op while an admin thread is
        preparing a swap (its superset group must not be shrunk from
        under it before the routing flip)."""
        with self._prep_lock:
            if self._preparing:
                return
        live_keys: dict = {}
        for e in self.registry.entries():
            live_keys.setdefault(
                e.group_key(self._storage_of(e)), []).append(e)
        for key in list(self._groups):
            members = live_keys.get(key)
            if members is None:
                del self._groups[key]
            elif set(members) != self._groups[key].member_set():
                group = UnionGroup(key, members, self._group_config(),
                                   storage=key[-1])
                self._tl.in_dispatch = True
                try:
                    group.warm()
                finally:
                    self._tl.in_dispatch = False
                self._groups[key] = group

    # ---------------------------------------------------------- dispatch
    def _dispatch_batch(self, group: UnionGroup, batch) -> int:
        """Merge an EDF-formed batch into one padded bucket dispatch
        (a single oversized request loops over the top bucket — the v1
        discipline). Completion of the PREVIOUS in-flight batch happens
        inside issue(), after this batch's async dispatch."""
        rows = sum(r.n for r in batch)
        merged = np.concatenate(
            [np.asarray(r.rows, np.float32) for r in batch])
        top = group.buckets[-1]
        completed = 0
        if rows <= top:
            bucket = next(b for b in group.buckets if rows <= b)
            qb = merged
            if rows != bucket:
                qb = np.zeros((bucket, group.d), np.float32)
                qb[:rows] = merged
            completed += self._issue(group, qb, bucket, batch, rows,
                                     chain=None, final=True)
        else:
            # One oversized request (form() guarantees multi-request
            # batches fit the top bucket): loop the top bucket,
            # assembling segments into one output before completion.
            # The chain dict carries the segment parts AND the dead
            # flag a failed segment sets, so one failed dispatch fails
            # the whole request exactly once — later segments of a
            # dead chain complete as no-ops.
            chain = {"parts": [], "total": rows, "dead": False}
            s = 0
            while s < rows:
                if chain["dead"]:
                    # An already-completed segment failed the chain
                    # (raise or watchdog): the request is already
                    # 'failed' — dispatching the remaining segments
                    # would be pure wasted device work.
                    break
                take = min(rows - s, top)
                qb = merged[s:s + take]
                if take != top:
                    qp = np.zeros((top, group.d), np.float32)
                    qp[:take] = qb
                    qb = qp
                completed += self._issue(
                    group, qb, top, batch, take,
                    chain=chain, final=s + take >= rows)
                s += take
        return completed

    def _issue(self, group, qb, bucket, batch, used_rows,
               chain, final) -> int:
        # Counters advance BEFORE the dispatch and ride the meta as a
        # snapshot: the chunk record for THIS batch must carry ITS OWN
        # cumulative (pairs, dispatch) — the completion callback fires
        # one batch later (double buffer), when the live counters
        # already describe the next batch.
        self._dispatches += 1
        self._rows_total += used_rows
        meta = (group, batch, used_rows, chain, final,
                self._rows_total, self._dispatches)
        self._tl.in_dispatch = True
        try:
            items = self._dispatcher.issue(group, qb, bucket, meta)
        finally:
            self._tl.in_dispatch = False
        self.batch_occupancy.observe(used_rows / bucket)
        self.batch_rows.observe(used_rows)
        if final and len({r.entry.name for r in batch}) > 1:
            self.coalesced.add(1)
        completed = 0
        for item in items:
            completed += self._complete_batch(item)
        return completed

    def _complete_batch(self, item) -> int:
        (group, batch, used_rows, chain, final, rows_cum,
         dispatch_no), out, wait_s, window_s, err = item
        self.dispatch_seconds.observe(wait_s)
        self._obs.chunk(pairs=rows_cum, b_hi=0.0, b_lo=0.0,
                        device_seconds=wait_s,
                        dispatch=dispatch_no,
                        rows=int(used_rows), window_seconds=
                        round(window_s, 6),
                        **({"failed": True} if err is not None else {}))
        if err is not None:
            return self._fail_batch(batch, chain, err, dispatch_no)
        if chain is not None:
            if chain["dead"]:  # an earlier segment already failed it
                return 0
            chain["parts"].append(out[:used_rows])
            if not final:
                return 0
            out = np.concatenate(chain["parts"])
            used_rows = chain["total"]
        elif not final:  # pragma: no cover - unsegmented is always final
            return 0
        now = time.perf_counter()
        lo = 0
        for req in batch:
            dec = np.array(out[lo:lo + req.n, group.slices[req.entry]])
            lo += req.n
            if req.entry.f64_cols.size:
                _overwrite_f64(req.entry, req.rows, dec)
            self._finish_served(req, dec, now)
        return len(batch)

    def _fail_batch(self, batch, chain, err: str,
                    dispatch_no: int) -> int:
        """A dispatch raised or the watchdog tripped: complete every
        request of the batch with an explicit 'failed' verdict and the
        per-model counters — the engine itself keeps serving (the
        wedged dispatch cost one batch, not the pump thread)."""
        if chain is not None:
            if chain["dead"]:
                return 0  # the chain already failed once
            chain["dead"] = True
        self.dispatch_failures.add(1)
        if "watchdog" in err:
            self.watchdog_trips.add(1)
        names = sorted({r.entry.name for r in batch})
        self._obs.event("dispatch_failed", models=names,
                        error=err[:200], dispatch=dispatch_no,
                        watchdog=bool("watchdog" in err))
        now = time.perf_counter()
        for req in batch:
            mm = self._model_metrics(req.entry.name)
            mm["failures"].add(1)
            self._done[req.ticket] = ServeResult(
                ticket=req.ticket, model=req.entry.name,
                version=req.entry.version, decision=None,
                verdict="failed", latency_s=now - req.t_submit,
                entry=req.entry)
        return len(batch)

    # -------------------------------------------------------- completion
    def _finish_served(self, req: Request, dec: np.ndarray,
                       now: float) -> None:
        late = now > req.deadline
        latency = now - req.t_submit
        mm = self._model_metrics(req.entry.name)
        self.request_seconds.observe(latency)
        mm["latency"].observe(latency)
        if late:
            self.deadline_misses.add(1)
            mm["misses"].add(1)
        self._done[req.ticket] = ServeResult(
            ticket=req.ticket, model=req.entry.name,
            version=req.entry.version, decision=dec,
            verdict="late" if late else "ok", latency_s=latency,
            entry=req.entry)

    def _finish_expired(self, req: Request) -> None:
        now = time.perf_counter()
        mm = self._model_metrics(req.entry.name)
        self.deadline_misses.add(1)
        self.expired.add(1)
        mm["misses"].add(1)
        mm["expired"].add(1)
        self._done[req.ticket] = ServeResult(
            ticket=req.ticket, model=req.entry.name,
            version=req.entry.version, decision=None,
            verdict="expired", latency_s=now - req.t_submit,
            entry=req.entry)

    # -------------------------------------------------------- convenience
    def decision(self, rows, model: Optional[str] = None) -> np.ndarray:
        """Synchronous one-request convenience: submit + drain + slice
        (the v1 decision() shape)."""
        ticket = self.submit(rows, model=model, deadline_ms=None)
        done = self.drain()
        res = done.pop(ticket)
        self._done.update(done)  # other tickets stay claimable
        return res.decision

    def predict(self, rows, model: Optional[str] = None) -> np.ndarray:
        ticket = self.submit(rows, model=model, deadline_ms=None)
        done = self.drain()
        res = done.pop(ticket)
        self._done.update(done)
        return res.labels()  # the SERVING version's fold, swap-safe

    # --------------------------------------------------------- telemetry
    def attach_net(self, front) -> None:
        """Attach the network front door (serving/server.py): its
        counters join snapshot() (under the ``net`` key — the run
        log's final record and ``obs report``'s serve column read it)
        and its OpenMetrics families join the /metrics exposition.
        While a front door is attached, ITS pump thread is the
        engine's single driver — in-process submit()/drain() callers
        must not race it (registry swaps on admin threads remain
        fine)."""
        self._front = front

    def bucket_suggestion(self) -> dict:
        """Occupancy-driven ``ServeConfig.buckets`` advice from the
        engine's own dispatch telemetry (ISSUE 14 satellite; closes
        the ROADMAP item 2 occupancy-autotuning stub). Pure host read
        of the batch_rows histogram window. Report-only UNLESS
        ``buckets=None`` resolved to an armed auto-apply
        (maybe_apply_bucket_suggestion): whether right-sizing pays at
        all is a DEVICE property (the autotune ``serve_buckets``
        probe measures it), so applying stays behind the profile
        discipline."""
        return suggest_buckets(self.batch_rows.window_values(),
                               self._bucket_ladder)

    def maybe_apply_bucket_suggestion(self):
        """Profile-gated bucket AUTO-APPLY (ISSUE 17 second axis —
        PR 14's report-only advice graduated). No-op — returns None —
        unless ALL of:
          * ``config.buckets is None`` (an explicit ladder always
            wins: the resolve_auto_gate discipline),
          * the resolved provenance carries ``auto_apply`` (an
            AUTHORITATIVE serve_buckets pays verdict in the active
            DeviceProfile — CPU-harness verdicts pin False, so CI
            never flips this),
          * the occupancy suggestion exists and differs from the
            current ladder.
        On apply: swaps the engine's ladder, drops staged groups (they
        restage lazily, off the idle moment this runs in — drain()
        calls this between serving legs, with queues empty), and
        extends the provenance with what was applied so the snapshot
        carries the full decision trail."""
        if self.config.buckets is not None \
                or not self.bucket_provenance.get("auto_apply"):
            return None
        sug = self.bucket_suggestion()
        ladder = sug.get("suggested_buckets")
        if not ladder or tuple(ladder) == self._bucket_ladder:
            return None
        self._bucket_ladder = tuple(int(b) for b in ladder)
        self.bucket_provenance = {
            **self.bucket_provenance,
            "applied_buckets": list(self._bucket_ladder),
            "suggestion": sug}
        self._groups.clear()
        self._obs.event("buckets_auto_applied",
                        buckets=list(self._bucket_ladder),
                        occupancy=sug.get("projected_occupancy"))
        return list(self._bucket_ladder)

    def snapshot(self) -> dict:
        """JSON-able engine state: counters, queue state, histogram
        snapshots, per-model breakdown — the serve run log's final
        record and the loadgen artifact both consume this shape."""
        storage_by_model = {e.name: self._storage_of(e)
                            for e in self.registry.entries()}
        per_model = {}
        for name, mm in sorted(self._per_model.items()):
            per_model[name] = {
                "requests": mm["requests"].value,
                "rows": mm["rows"].value,
                "deadline_misses": mm["misses"].value,
                "expired": mm["expired"].value,
                "swaps": mm["swaps"].value,
                "dispatch_failures": mm["failures"].value,
                "request_seconds": mm["latency"].snapshot(),
                **({"union_storage": storage_by_model[name]}
                   if name in storage_by_model else {}),
            }
        staged = list(self._groups.values())
        return {
            "models": self.registry.names(),
            "versions": {e.name: e.version
                         for e in self.registry.entries()},
            "union_mesh_devices": self.config.num_devices,
            **({"replica": self.replica}
               if self.replica is not None else {}),
            "dispatches": self._dispatches,
            "rows": self._rows_total,
            "requests": self._next_ticket,
            "queue_depth": self.scheduler.queue_depth,
            "queue_rows": self.scheduler.queue_rows,
            "deadline_misses": self.deadline_misses.value,
            "expired": self.expired.value,
            "hot_swaps": self.hot_swaps.value,
            "dispatch_failures": self.dispatch_failures.value,
            "watchdog_trips": self.watchdog_trips.value,
            "rehydrated_models": list(self._rehydrated),
            "coalesced_dispatches": self.coalesced.value,
            "compiles": self.compiles.value,
            "batch_occupancy": self.batch_occupancy.snapshot(),
            "dispatch_seconds": self.dispatch_seconds.snapshot(),
            "request_seconds": self.request_seconds.snapshot(),
            "union_bytes": sum(g.union_bytes for g in staged),
            "quantized_unions": sum(
                1 for g in staged if g.union_storage == "int8"),
            "union_storage": storage_by_model,
            "buckets": list(self._bucket_ladder),
            "bucket_provenance": self.bucket_provenance,
            "per_model": per_model,
            **({"net": self._front.net_snapshot()}
               if self._front is not None else {}),
        }

    def render_openmetrics(self) -> str:
        """The /metrics exposition: per-model labelled counters and
        latency summaries, queue-depth gauges, deadline-miss and
        hot-swap counters, batch-occupancy summary — quantiles ARE
        Histogram.percentiles() (scrape == snapshot). Host reads only;
        a scrape can never add a device dispatch."""
        om = openmetrics
        depth = self.scheduler.depth_by_model()
        versions = {e.name: e.version for e in self.registry.entries()}
        req_s, row_s, miss_s, exp_s, swap_s = [], [], [], [], []
        fail_s = []
        lat_samples = []
        for name, mm in sorted(self._per_model.items()):
            lb = {"model": name}
            req_s.append(("_total", lb, mm["requests"].value))
            row_s.append(("_total", lb, mm["rows"].value))
            miss_s.append(("_total", lb, mm["misses"].value))
            exp_s.append(("_total", lb, mm["expired"].value))
            swap_s.append(("_total", lb, mm["swaps"].value))
            fail_s.append(("_total", lb, mm["failures"].value))
            if len(mm["latency"]):
                lat_samples.extend(om.summary_samples(
                    mm["latency"], labels=lb))
        fams = [
            om.metric("serving_requests", "counter",
                      "requests admitted", req_s),
            om.metric("serving_rows", "counter", "query rows admitted",
                      row_s),
            om.metric("serving_deadline_misses", "counter",
                      "requests that missed their deadline (served "
                      "late or shed)", miss_s),
            om.metric("serving_expired", "counter",
                      "requests shed at batch forming (deadline "
                      "already passed)", exp_s),
            om.metric("serving_hot_swaps", "counter",
                      "zero-downtime model version swaps", swap_s),
            om.metric("serving_dispatch_failures", "counter",
                      "requests failed by a raising or watchdog-"
                      "bounded device dispatch (explicit 'failed' "
                      "verdicts, engine kept serving)", fail_s),
            om.counter("serving_watchdog_trips",
                       "dispatches failed by the dispatch watchdog "
                       "(ServeConfig.dispatch_timeout_ms)",
                       self.watchdog_trips.value),
            om.gauge("serving_model_version",
                     "live registered version per model",
                     [({"model": n}, v)
                      for n, v in sorted(versions.items())]),
            om.gauge("serving_queue_depth",
                     "queued requests awaiting dispatch",
                     [({"model": n}, v)
                      for n, v in sorted(depth.items())]),
            om.gauge("serving_queue_rows",
                     "queued query rows awaiting dispatch",
                     [({}, self.scheduler.queue_rows)]),
            om.counter("serving_dispatches", "device bucket dispatches",
                       self._dispatches),
            om.counter("serving_coalesced_dispatches",
                       "dispatches answering more than one model from "
                       "one union matmul", self.coalesced.value),
            om.counter("serving_compiles",
                       "bucket executors compiled while serving",
                       self.compiles.value),
            om.gauge("serving_union_bytes",
                     "staged union argument bytes per model at its "
                     "resolved storage (int8 includes the f32 row "
                     "scales)",
                     [({"model": e.name,
                        "union_storage": self._storage_of(e)},
                       union_nbytes(self._storage_of(e),
                                    int(e.ens.sv_union.shape[0]),
                                    int(e.ens.sv_union.shape[1])))
                      for e in sorted(self.registry.entries(),
                                      key=lambda e: e.name)]),
            om.gauge("serving_quantized_unions",
                     "staged union groups serving from int8 rows",
                     [({}, sum(1 for g in self._groups.values()
                               if g.union_storage == "int8"))]),
        ]
        if lat_samples:
            fams.append(om.metric(
                "serving_request_seconds", "summary",
                "request latency (submit->complete), recent-window "
                "quantiles", lat_samples))
        if len(self.batch_occupancy):
            fams.append(om.summary(
                "serving_batch_occupancy",
                "rows dispatched / bucket capacity, recent window",
                self.batch_occupancy))
        if len(self.dispatch_seconds):
            fams.append(om.summary(
                "serving_dispatch_seconds",
                "host blocking wait per dispatch (overlap residual), "
                "recent window", self.dispatch_seconds))
        sug = self.bucket_suggestion()
        if sug.get("suggested_buckets"):
            # Occupancy-driven bucket advice (ISSUE 14): one gauge
            # sample per suggested ladder slot, so an operator's
            # dashboard can see the suggestion drift under live
            # traffic without log scraping. Self-applied only when
            # buckets=None resolved to an armed auto-apply (ISSUE 17).
            fams.append(om.gauge(
                "serving_suggested_bucket",
                "occupancy-driven ServeConfig.buckets suggestion "
                "(applied between legs only under the profile-gated "
                "auto-apply; otherwise report-only)",
                [({"slot": str(i)}, b)
                 for i, b in enumerate(sug["suggested_buckets"])]))
        if self._front is not None:
            # Front-door families (ISSUE 15): connection/frame/verdict
            # accounting rides the SAME exposition — one scrape, one
            # truth for the chaos legs' reconciliation.
            fams.extend(self._front.net_families())
        return om.render(fams)

    def close(self) -> None:
        """Drain outstanding work, stop /metrics FIRST (the ordering
        contract: a racing scrape sees a full exposition, the # EOF
        stub, or a clean refusal — never a half-torn-down read),
        detach the compile sink and finish the serve run log. A
        close() arriving DURING an active drain() waits on the
        lifecycle lock for that drain to complete, then tears down
        once; a second close() is a no-op (ISSUE 15 satellite)."""
        if self._closed:
            return
        with self._lifecycle:
            if self._closed:
                return
            self._closing = True
            if self.exporter is not None:
                self.exporter.close()
            while self.scheduler.queue_depth or self._dispatcher.busy:
                self.pump()
            self._gc_groups()
            compilelog.remove_sink(self._compile_sink)
            self._obs.finish(**self.snapshot())
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

