"""Network front door for the v2 serving engine (ISSUE 15).

The PR 9/13 ServingEngine — hot swap, EDF shedding, dispatch watchdog,
crash-recovery journal — was reachable only in-process; not one of the
failure modes a real network imposes (half-open connections, slow
writers, client deadlines, overload from strangers) had an answer or a
test. This module is that answer: a persistent-connection TCP endpoint
(threaded stdlib socketserver, no new deps) speaking the
length-prefixed binary frames of :mod:`dpsvm_tpu.serving.wire`, built
so that **every accepted frame terminates in exactly one wire verdict**
and every degraded behavior is a policy, not an accident:

* DEADLINE PROPAGATION is clock-skew-safe: the client ships its
  REMAINING BUDGET (a duration); the server anchors it to its own
  monotonic clock at parse time and hands it to the EDF scheduler —
  wall clocks never cross the wire (wire.py's clock contract).
* ADMISSION CONTROL turns saturation into an immediate ``rejected``
  verdict with a ``retry_after_ms`` hint instead of unbounded
  buffering: a request arriving past ``ServeConfig.admission_max_rows``
  queued rows never enters the engine.
* PER-CONNECTION READ/WRITE TIMEOUTS bound slow-loris and dead-peer
  cost: an idle half-open connection dies after
  ``conn_read_timeout_ms`` with no complete frame; a stalled reader
  whose verdict write blocks ``conn_write_timeout_ms`` kills ONLY that
  connection (its unsent verdicts counted undeliverable) — the pump
  thread never blocks on any socket.
* PROTOCOL ERRORS (bad magic, oversized length prefix, truncated or
  inconsistent frames) cost exactly their own connection: an ERROR
  frame goes out, the connection closes, every other connection and
  the engine itself are untouched.
* GRACEFUL DRAIN (:meth:`ServeServer.drain`, wired to SIGTERM by
  ``cli serve --listen``): stop accepting, finish or shed in-flight
  work by its own deadline through the normal engine verdicts, flush
  the final verdicts, send each connection a GOODBYE frame, close.
  The registry journal was written atomically at register/swap time,
  so the PR 13 rehydrate path needs nothing from the drain.

THREADING MODEL: reader threads (one per connection, socketserver's)
parse frames and enqueue them on ONE shared inbox; ONE pump thread PER
ENGINE REPLICA owns its engine — admission, submit, pump, result
routing all happen there (each engine is single-driver by design; only
registry swaps may run on admin threads). Writer threads (one per
connection) drain per-connection outboxes so a slow peer can never
block verdict routing. All accounting counters share one lock and
reconcile exactly: ``frames_accepted == sum(verdicts)`` and every
verdict is either delivered or counted undeliverable — the loadgen
``--net`` chaos leg asserts the whole conservation law against
client-side tallies and the run log.

REPLICA ROUTING (serving/replicas.py ReplicaFleet behind this front
door) is slotted into the pump/admission layer — the one place every
frame already passes through: a replica's pump thread pops the shared
inbox only while it will actually take new work (not draining, and its
queue under the admission bound unless EVERY live replica is equally
full — then any of them pops and the admission reject fires exactly as
on a single engine). Work therefore flows to whichever replica has
room, with no separate router thread, no per-frame routing decision
outside the pump layer, and — at one replica — byte-for-byte the
single-engine behavior. Per-replica ``drain_replica``/
``resume_replica`` make rolling restarts a policy: the drained replica
stops popping, finishes or sheds its queued work through the normal
verdicts, and parks while its peers keep serving.
"""

from __future__ import annotations

import queue
import select
import socket
import socketserver
import threading
import time
from typing import Optional

from dpsvm_tpu.obs import export as om
from dpsvm_tpu.serving import wire
from dpsvm_tpu.testing import faults

#: bounded per-connection outbox (verdict frames awaiting the writer).
#: A reader stalled long enough to back this up is a slow reader by
#: definition — the connection is killed (its verdicts counted
#: undeliverable) rather than letting the queue grow without bound.
OUTBOX_FRAMES = 1024


class _NetStats:
    """Front-door accounting. One lock, exact conservation:
    ``frames_accepted == sum(verdicts.values())`` at every quiescent
    instant, and ``verdicts[v] == delivered + undeliverable[v]`` —
    the loadgen chaos leg reconciles these against client tallies."""

    FIELDS = ("conns_opened", "conns_closed", "conns_killed",
              "accept_drops", "conns_aborted", "frames_accepted",
              "protocol_errors", "goodbyes_sent")

    def __init__(self):
        self.lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)
        self.verdicts = {v: 0 for v in wire.VERDICTS}
        self.undeliverable = {v: 0 for v in wire.VERDICTS}

    def bump(self, field: str, n: int = 1) -> None:
        with self.lock:
            setattr(self, field, getattr(self, field) + n)

    def verdict(self, name: str) -> None:
        with self.lock:
            self.verdicts[name] += 1

    def undelivered(self, name: str) -> None:
        with self.lock:
            self.undeliverable[name] += 1

    def snapshot(self) -> dict:
        with self.lock:
            out = {f: getattr(self, f) for f in self.FIELDS}
            out["verdicts"] = dict(self.verdicts)
            out["verdicts_undeliverable"] = dict(self.undeliverable)
            out["rejected"] = self.verdicts["rejected"]
            out["undeliverable_total"] = sum(
                self.undeliverable.values())
            return out


def _send_with_deadline(sock: socket.socket, data: bytes,
                        timeout_s: float) -> None:
    """sendall with a WHOLE-FRAME deadline (socket timeouts bound one
    syscall, not a frame trickled to a slow reader). select-gated so a
    full send buffer costs bounded wall clock, never a wedged writer
    thread. PRECONDITION: the socket must be in timeout mode (every
    front-door connection is — _serve_conn sets conn_read_timeout) so
    a post-select send() does one partial write instead of blocking
    for the whole buffer."""
    deadline = time.monotonic() + timeout_s
    view = memoryview(data)
    off = 0
    while off < len(view):
        remain = deadline - time.monotonic()
        if remain <= 0:
            raise socket.timeout(
                f"frame write exceeded {timeout_s:.3f}s "
                f"({off}/{len(view)} bytes)")
        _, writable, _ = select.select([], [sock], [], remain)
        if not writable:
            raise socket.timeout(
                f"frame write exceeded {timeout_s:.3f}s "
                f"({off}/{len(view)} bytes)")
        off += sock.send(view[off:])


class _Conn:
    """One live connection: the reader runs in the socketserver handler
    thread; ``outbox`` feeds the dedicated writer thread. Frames are
    (kind, bytes, verdict-name-or-None) — ``goodbye``/``error`` close
    the connection after sending; ``close`` closes silently.

    The enqueue/teardown race is closed by ``_lock``: a frame is
    either enqueued BEFORE the connection is marked dead (and then
    counted undeliverable by the teardown drain if never sent) or
    refused AFTER (and counted undeliverable by the caller) — no
    verdict can fall between the two accountings."""

    def __init__(self, server: "ServeServer", sock: socket.socket,
                 cid: int):
        self.server = server
        self.sock = sock
        self.cid = cid
        self.outbox: queue.Queue = queue.Queue(maxsize=OUTBOX_FRAMES)
        self.dead = False  # no further enqueues accepted
        self._lock = threading.Lock()
        self._drained_dead = False
        self.reader: Optional[threading.Thread] = None
        self.writer = threading.Thread(
            target=self._write_loop, daemon=True,
            name=f"dpsvm-net-writer-{cid}")
        self.writer.start()

    def enqueue(self, kind: str, frame: bytes,
                verdict: Optional[str] = None) -> bool:
        """Queue one frame; False (undeliverable accounting is then
        the CALLER's) when the connection is dead or the outbox is
        full — a backed-up outbox IS the slow-reader bound, so it
        kills the connection rather than growing."""
        with self._lock:
            if self.dead:
                return False
            try:
                self.outbox.put_nowait((kind, frame, verdict))
                return True
            except queue.Full:
                pass
        self.kill("outbox full (slow reader)")
        return False

    def kill(self, reason: str) -> None:
        """Server-initiated teardown: mark dead, wake reader AND
        writer via socket shutdown; the writer's exit path counts the
        unsent verdicts undeliverable."""
        with self._lock:
            if self.dead:
                return
            self.dead = True
        self.server._stats.bump("conns_killed")
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:  # wake a writer idle on an empty outbox
            self.outbox.put_nowait(("close", b"", None))
        except queue.Full:
            pass  # writer is mid-queue; its next send fails post-shutdown

    def _drain_dead(self) -> None:
        """Mark dead and count every still-queued verdict
        undeliverable (exactly once — the _lock closes the race with
        concurrent enqueues)."""
        with self._lock:
            if self._drained_dead:
                return
            self.dead = True
            self._drained_dead = True
            while True:
                try:
                    _, _, verdict = self.outbox.get_nowait()
                except queue.Empty:
                    break
                if verdict is not None:
                    self.server._stats.undelivered(verdict)

    def _write_loop(self) -> None:
        stats = self.server._stats
        timeout_s = self.server._write_timeout_s
        while True:
            kind, frame, verdict = self.outbox.get()
            if kind == "close":
                break
            try:
                _send_with_deadline(self.sock, frame, timeout_s)
            except (OSError, ValueError):
                # ValueError: fd already closed under select()
                if verdict is not None:
                    stats.undelivered(verdict)
                break
            if kind in ("goodbye", "error"):
                break
        self._drain_dead()
        # shutdown BEFORE close: close() alone does not wake a reader
        # blocked in recv on the shared fd; shutdown delivers it EOF.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._conn_closed(self)


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    front: "ServeServer" = None  # set right after construction

    def __init__(self, *a, **kw):
        import weakref

        self.owned_socks = weakref.WeakSet()
        super().__init__(*a, **kw)

    def shutdown_request(self, request):
        # socketserver closes the socket when the handler (our reader
        # loop) returns — but the connection's WRITER thread may still
        # be flushing verdicts on it. Once a _Conn owns the socket,
        # teardown belongs to the writer's exit path; refused
        # connections (verify_request False) never get an owner and
        # close here as usual.
        if request in self.owned_socks:
            return
        super().shutdown_request(request)

    def verify_request(self, request, client_address) -> bool:
        # Drain refusals and the net_accept fault seam (accept-queue
        # overflow) both drop the connection before any frame — the
        # client sees an instant EOF, the connect-retry class.
        if self.front._draining or faults.net_accept_drop():
            self.front._stats.bump("accept_drops")
            return False
        return True


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.server.front._serve_conn(self.request, self.client_address)


class ServeServer:
    """The TCP front door over one :class:`ServingEngine` — or over a
    :class:`serving.replicas.ReplicaFleet` of them (anything exposing
    ``engines``/``config``/``_obs``/``attach_net``).

    Construction binds the listener and starts the accept + pump
    threads (one pump thread per replica); the engine must already
    exist (models may register before or after — submits resolve at
    frame time). ``host``/``port`` default to the engine config's
    ``listen`` spec, else loopback on an ephemeral port (read
    ``server.port``).

    Lifecycle: :meth:`drain` is the graceful half (stop accepting,
    flush verdicts, GOODBYE, close connections, stop the pumps);
    :meth:`close` is drain + listener teardown and is idempotent;
    :meth:`drain_replica`/:meth:`resume_replica` are the per-replica
    rolling-restart half. The server never closes the engine — the
    caller owns that ordering (``cli serve --listen`` does drain →
    ``engine.close()`` on SIGTERM)."""

    def __init__(self, engine, host: Optional[str] = None,
                 port: Optional[int] = None):
        config = engine.config
        if host is None or port is None:
            if config.listen is not None:
                host, port = config.listen_addr()
            else:
                host, port = "127.0.0.1", 0
        # One engine or a fleet of replicas; either way the obs run
        # log and the /metrics attachment belong to the target, the
        # per-replica pump threads to this front door.
        self._fleet = engine if hasattr(engine, "engines") else None
        self._engine = None if self._fleet is not None else engine
        self._n_rep = (len(self._fleet.engines)
                       if self._fleet is not None else 1)
        self._obs = engine._obs
        self._stats = _NetStats()
        self._inbox: queue.Queue = queue.Queue()
        self._inbox_pending = 0  # put-but-not-yet-handled (drain gate)
        self._pending_lock = threading.Lock()
        # Per replica: ticket -> (conn, req_id, want_dec) — tickets are
        # per-engine counters, so the routing key is (replica, ticket).
        self._tickets = [dict() for _ in range(self._n_rep)]
        self._rep_draining = [False] * self._n_rep
        self._rep_parked = [False] * self._n_rep
        self._rep_lock = threading.Lock()
        self._rep_verdicts = [{v: 0 for v in wire.VERDICTS}
                              for _ in range(self._n_rep)]
        self._conns: dict = {}
        self._conns_lock = threading.Lock()
        self._next_cid = 0
        self._life = threading.RLock()
        self._draining = False
        self._drained = False
        self._closed = False
        self._stop_pump = threading.Event()
        self._read_timeout_s = config.conn_read_timeout_ms / 1e3
        self._write_timeout_s = config.conn_write_timeout_ms / 1e3
        self._max_payload = int(config.max_frame_bytes)
        self._admission_rows = (config.admission_max_rows
                                if config.admission_max_rows is not None
                                else config.max_pending)
        self._retry_base_ms = config.admission_retry_ms

        self._tcp = _TCP((host, int(port)), _Handler,
                         bind_and_activate=True)
        self._tcp.front = self
        self.host, self.port = self._tcp.server_address[:2]
        self._accept_thread = threading.Thread(
            target=self._tcp.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="dpsvm-net-accept")
        self._pump_threads = [
            threading.Thread(
                target=self._pump_loop, args=(i,), daemon=True,
                name=("dpsvm-net-pump" if self._n_rep == 1
                      else f"dpsvm-net-pump-{i}"))
            for i in range(self._n_rep)]
        self._pump_thread = self._pump_threads[0]
        engine.attach_net(self)
        self._obs.event("listen", host=self.host, port=self.port,
                        admission_max_rows=self._admission_rows,
                        replicas=self._n_rep)
        self._accept_thread.start()
        for th in self._pump_threads:
            th.start()

    def _eng(self, rep: int = 0):
        """The live engine for replica `rep` — read through the fleet
        on every call so restart_replica's fresh engine is picked up
        by the very next pump iteration."""
        if self._fleet is not None:
            return self._fleet.engines[rep]
        return self._engine

    # -------------------------------------------------------- reader side
    def _serve_conn(self, sock: socket.socket, addr) -> None:
        with self._conns_lock:
            cid = self._next_cid
            self._next_cid += 1
        threading.current_thread().name = f"dpsvm-net-conn-{cid}"
        self._tcp.owned_socks.add(sock)  # writer-thread teardown now
        sock.settimeout(self._read_timeout_s)
        conn = _Conn(self, sock, cid)
        conn.reader = threading.current_thread()
        with self._conns_lock:
            self._conns[cid] = conn
        self._stats.bump("conns_opened")
        self._obs.event("conn_open", conn=cid,
                                peer=f"{addr[0]}:{addr[1]}")
        # The HELLO banner: the client's proof this connection was
        # actually accepted (a handshake alone completes in the listen
        # backlog — EOF before HELLO is the retry-safe accept-drop).
        conn.enqueue("hello", wire.pack_hello())
        try:
            self._read_loop(conn)
        finally:
            if not conn.dead:
                conn.enqueue("close", b"")
            # the writer owns the socket close + closed accounting

    def _read_loop(self, conn: _Conn) -> None:
        while not conn.dead and not self._closed:
            try:
                head = wire.recv_exact(conn.sock, wire.HEADER_BYTES)
            except wire.ConnectionClosed as e:
                if e.mid_frame:
                    self._stats.bump("conns_aborted")
                return  # clean goodbye at a frame boundary
            except socket.timeout:
                conn.kill("read timeout (idle or half-open peer)")
                return
            except OSError:
                return
            try:
                ftype, length = wire.parse_header(head, self._max_payload)
                if ftype != wire.T_REQUEST:
                    raise wire.WireError(
                        f"clients may only send REQUEST frames "
                        f"(got type {ftype})")
                payload = wire.recv_exact(conn.sock, length)
                req = wire.parse_request(payload)
            except wire.ConnectionClosed:
                self._stats.bump("conns_aborted")
                return
            except socket.timeout:
                conn.kill("read timeout mid-frame")
                return
            except wire.WireError as e:
                self._protocol_error(conn, str(e))
                return
            except OSError:
                return
            with self._pending_lock:
                self._inbox_pending += 1
            self._inbox.put((conn, req))

    def _protocol_error(self, conn: _Conn, msg: str) -> None:
        """A malformed frame kills ONLY its own connection, with an
        ERROR frame out first so the peer knows why."""
        self._stats.bump("protocol_errors")
        self._obs.event("protocol_error", conn=conn.cid,
                                error=msg[:200])
        conn.enqueue("error", wire.pack_error(0, msg))

    # ---------------------------------------------------------- pump side
    def _takes_new(self, rep: int) -> bool:
        """Eligibility gate: may replica `rep`'s pump thread pop the
        shared inbox right now?  A draining replica never pops; under
        a server-wide drain anyone pops (the frame gets its drain
        reject); otherwise pop while this replica's queue is under the
        admission bound — and when it ISN'T, pop anyway only if every
        live peer is equally full, so the admission reject fires
        exactly as it would on a single engine instead of the frame
        rotting in the inbox. At one replica this reduces to
        unconditional popping — the pre-fleet behavior."""
        if self._rep_draining[rep]:
            return False
        if self._draining:
            return True
        if self._eng(rep).scheduler.queue_rows < self._admission_rows:
            return True
        for i in range(self._n_rep):
            if i == rep or self._rep_draining[i]:
                continue
            if self._eng(i).scheduler.queue_rows < self._admission_rows:
                return False  # a peer with room will take it
        return True  # everyone is full: reject rather than buffer

    def _pump_loop(self, rep: int) -> None:
        while not self._stop_pump.is_set():
            # Read the engine through the fleet EVERY iteration so a
            # restart_replica swap is picked up immediately.
            eng = self._eng(rep)
            handled = False
            if self._takes_new(rep):
                try:
                    conn, req = self._inbox.get(timeout=0.02)
                    handled = True
                except queue.Empty:
                    pass
            if handled:
                try:
                    self._handle_request(rep, eng, conn, req)
                finally:
                    with self._pending_lock:
                        self._inbox_pending -= 1
                # drain whatever else arrived without blocking, while
                # still eligible (queue may have crossed the bound)
                while self._takes_new(rep):
                    try:
                        conn, req = self._inbox.get_nowait()
                    except queue.Empty:
                        break
                    try:
                        self._handle_request(rep, eng, conn, req)
                    finally:
                        with self._pending_lock:
                            self._inbox_pending -= 1
            busy = eng.scheduler.queue_depth or eng._dispatcher.busy
            if busy:
                eng.pump()
            for ticket, res in eng.results().items():
                self._route(rep, ticket, res)
            if (self._rep_draining[rep] and not self._rep_parked[rep]
                    and not self._tickets[rep]
                    and not eng.scheduler.queue_depth
                    and not eng._dispatcher.busy):
                # drain_replica's signal; under _rep_lock so the park
                # flag never races resume_replica's reset (threadlint
                # guarded-by contract: _rep_parked is _rep_lock's).
                with self._rep_lock:
                    self._rep_parked[rep] = True
            if not handled and not busy:
                time.sleep(0.002)  # parked/ineligible: don't spin
        # Final sweep: a frame parsed between the drain's quiescence
        # check and the stop flag must still get its one verdict (a
        # drain-phase rejection, usually undeliverable past the
        # GOODBYE — but COUNTED, never silently dropped). Any pump
        # thread may pop it; each frame is handled exactly once.
        while True:
            try:
                conn, req = self._inbox.get_nowait()
            except queue.Empty:
                break
            try:
                self._handle_request(rep, self._eng(rep), conn, req)
            finally:
                with self._pending_lock:
                    self._inbox_pending -= 1

    def _handle_request(self, rep: int, eng, conn: _Conn,
                        req: wire.Request) -> None:
        self._stats.bump("frames_accepted")
        if self._draining:
            self._reject(rep, conn, req, "server draining",
                         retry_ms=int(self._retry_base_ms))
            return
        queued = eng.scheduler.queue_rows
        if queued >= self._admission_rows:
            # Deterministic hint: base, scaled by overshoot — enough
            # signal for a polite client backoff without pretending to
            # model service time.
            retry = int(self._retry_base_ms
                        * (1.0 + queued / self._admission_rows))
            self._reject(rep, conn, req,
                         f"admission: {queued} queued rows >= "
                         f"{self._admission_rows}", retry_ms=retry)
            return
        t0 = time.perf_counter()
        try:
            if req.budget_ms is None:
                ticket = eng.submit(req.rows, model=req.model)
            else:
                # The clock contract: budget_ms is a REMAINING DURATION;
                # submit anchors it to the server's monotonic clock.
                ticket = eng.submit(req.rows, model=req.model,
                                    deadline_ms=req.budget_ms)
        except (ValueError, KeyError) as e:
            # Request-level failure (unknown model, wrong width):
            # explicit 'failed' — NOT retryable, the frame itself is
            # wrong.
            self._send_verdict(conn, wire.pack_verdict(
                req.req_id, "failed", model=req.model or "",
                latency_ms=(time.perf_counter() - t0) * 1e3,
                message=str(e)[:300]), "failed", rep)
            return
        # Tickets are per-engine counters: the routing key is
        # (replica, ticket), kept as one dict per replica.
        self._tickets[rep][ticket] = (conn, req.req_id,
                                      req.want_decision)

    def _reject(self, rep: int, conn: _Conn, req: wire.Request,
                reason: str, retry_ms: int) -> None:
        self._send_verdict(conn, wire.pack_verdict(
            req.req_id, "rejected", model=req.model or "",
            retry_after_ms=retry_ms, message=reason), "rejected", rep)

    def _route(self, rep: int, ticket: int, res) -> None:
        meta = self._tickets[rep].pop(ticket, None)
        if meta is None:
            return  # not a wire ticket (in-process submit on this engine)
        conn, req_id, want_dec = meta
        verdict = "served" if res.verdict == "ok" else res.verdict
        labels = decision = None
        if res.decision is not None:
            if want_dec:
                decision = res.decision
            else:
                # ServeResult.labels(): the SERVING version's fold —
                # the one hot-swap-safe definition of label folding.
                labels = res.labels()
        self._send_verdict(conn, wire.pack_verdict(
            req_id, verdict, model=res.model, version=res.version,
            latency_ms=res.latency_s * 1e3, labels=labels,
            decision=decision), verdict, rep)

    def _send_verdict(self, conn: _Conn, frame: bytes,
                      verdict: str, rep: Optional[int] = None) -> None:
        """EVERY wire verdict passes here: counted at enqueue (the
        conservation law's left side); a dead/backed-up connection
        counts it undeliverable instead. `rep` additionally attributes
        the verdict to the replica that produced it — the per-replica
        counters sum exactly to the global ones."""
        self._stats.verdict(verdict)
        if rep is not None:
            with self._rep_lock:
                self._rep_verdicts[rep][verdict] += 1
        if not conn.enqueue("verdict", frame, verdict):
            self._stats.undelivered(verdict)

    # ----------------------------------------------------------- lifecycle
    def _conn_closed(self, conn: _Conn) -> None:
        with self._conns_lock:
            if self._conns.pop(conn.cid, None) is None:
                return
        self._stats.bump("conns_closed")
        self._obs.event("conn_close", conn=conn.cid)

    def drain(self, timeout_s: float = 60.0) -> dict:
        """Graceful drain: stop accepting, let queued work finish or
        shed BY ITS OWN DEADLINE through the engine's normal verdicts,
        flush every outbox, GOODBYE + close each connection, stop the
        pump. Returns the final net snapshot. Idempotent; concurrent
        callers serialize on the lifecycle lock."""
        with self._life:
            if self._drained:
                return self._stats.snapshot()
            self._draining = True
            self._obs.event("drain", phase="begin",
                            conns=len(self._conns),
                            queued=sum(self._eng(i).scheduler.queue_depth
                                       for i in range(self._n_rep)))
            self._tcp.shutdown()  # accept loop exits; no new conns
            # Quiescence: nothing unparsed in the inbox, no un-routed
            # ticket on ANY replica, every engine queue empty, no
            # in-flight device batch anywhere.
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                with self._pending_lock:
                    pending = self._inbox_pending
                if (pending == 0
                        and not any(self._tickets)
                        and all(not self._eng(i).scheduler.queue_depth
                                and not self._eng(i)._dispatcher.busy
                                for i in range(self._n_rep))):
                    break
                time.sleep(0.005)
            # Flush + goodbye. Verdicts already enqueued ride out
            # FIFO ahead of the GOODBYE frame.
            with self._conns_lock:
                conns = list(self._conns.values())
            for conn in conns:
                if conn.enqueue("goodbye",
                                wire.pack_goodbye("server draining")):
                    self._stats.bump("goodbyes_sent")
            for conn in conns:
                conn.writer.join(timeout=self._write_timeout_s + 5.0)
                if conn.writer.is_alive():  # pragma: no cover - wedged
                    conn.kill("writer did not flush within bound")
            for conn in conns:  # readers wake on the writer's shutdown
                if conn.reader is not None:
                    conn.reader.join(timeout=5.0)
            self._stop_pump.set()
            for th in self._pump_threads:
                th.join(timeout=10.0)
            self._tcp.server_close()
            self._accept_thread.join(timeout=5.0)
            self._drained = True
            snap = self._stats.snapshot()
            self._obs.event("drain", phase="end", **{
                k: snap[k] for k in ("frames_accepted", "conns_opened",
                                     "conns_closed", "goodbyes_sent",
                                     "undeliverable_total")})
            return snap

    def close(self) -> dict:
        """drain() + mark closed. Idempotent. Never touches the
        engine — callers own ``engine.close()`` ordering."""
        with self._life:
            snap = self.drain()
            self._closed = True
            return snap

    def drain_replica(self, rep: int, timeout_s: float = 60.0) -> dict:
        """Drain ONE replica for a rolling restart: its pump thread
        stops popping the shared inbox, finishes or sheds its queued
        work through the normal engine verdicts (deadlines still
        honored), routes the final results, then PARKS — peers keep
        serving throughout. Refuses to drain the last live replica
        (that is :meth:`drain`'s job, with the GOODBYE protocol).
        Returns the replica's parked-state snapshot; the engine itself
        is NOT closed — :meth:`ReplicaFleet.restart_replica` owns
        that ordering."""
        if not 0 <= rep < self._n_rep:
            raise ValueError(f"replica {rep} out of range "
                             f"(0..{self._n_rep - 1})")
        with self._life:
            if self._draining:
                raise RuntimeError(
                    "server is draining; per-replica drain is moot")
            live = [i for i in range(self._n_rep)
                    if i != rep and not self._rep_draining[i]]
            if not live:
                raise RuntimeError(
                    f"refusing to drain replica {rep}: it is the last "
                    f"live replica (use drain() to stop serving)")
            with self._rep_lock:
                already = self._rep_draining[rep]
                self._rep_draining[rep] = True
                if not already:
                    self._rep_parked[rep] = False
            self._obs.event("drain_replica", phase="begin", replica=rep,
                            queued=self._eng(rep).scheduler.queue_depth)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._rep_parked[rep]:
            time.sleep(0.005)
        self._obs.event("drain_replica", phase="end", replica=rep,
                        parked=self._rep_parked[rep])
        return {"replica": rep, "parked": self._rep_parked[rep],
                "verdicts": dict(self._rep_verdicts[rep])}

    def resume_replica(self, rep: int) -> None:
        """Put a drained (or restarted) replica back in rotation — its
        pump thread resumes popping on the very next iteration."""
        if not 0 <= rep < self._n_rep:
            raise ValueError(f"replica {rep} out of range "
                             f"(0..{self._n_rep - 1})")
        with self._life:
            with self._rep_lock:
                self._rep_draining[rep] = False
                self._rep_parked[rep] = False
        self._obs.event("resume_replica", replica=rep)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------- telemetry
    def net_snapshot(self) -> dict:
        with self._conns_lock:
            open_conns = len(self._conns)
        return {**self._stats.snapshot(), "open_connections": open_conns,
                "listen": f"{self.host}:{self.port}",
                "draining": self._draining,
                "replicas": self._n_rep}

    def replica_snapshot(self) -> list:
        """Per-replica routing state, one dict per replica. Kept OUT
        of :meth:`net_snapshot` so the loadgen's field-wise delta
        arithmetic over that flat dict stays valid; the per-replica
        verdict counters here sum exactly to the global
        ``verdicts`` (both counted at enqueue, under their locks)."""
        out = []
        with self._rep_lock:
            verdicts = [dict(v) for v in self._rep_verdicts]
        for i in range(self._n_rep):
            eng = self._eng(i)
            out.append({
                "replica": i,
                "queue_rows": eng.scheduler.queue_rows,
                "queue_depth": eng.scheduler.queue_depth,
                "inflight_tickets": len(self._tickets[i]),
                "draining": self._rep_draining[i],
                "parked": self._rep_parked[i],
                "verdicts": verdicts[i],
            })
        return out

    def net_families(self) -> list:
        """OpenMetrics families the engine's /metrics render appends —
        the front door's counters ride the SAME exposition as the
        engine's (one scrape, one truth)."""
        s = self.net_snapshot()
        return [
            om.counter("serving_net_connections_opened",
                       "front-door connections accepted",
                       s["conns_opened"]),
            om.counter("serving_net_connections_closed",
                       "front-door connections fully closed",
                       s["conns_closed"]),
            om.counter("serving_net_connections_killed",
                       "connections the server killed (read/write "
                       "timeout, protocol error, slow-reader outbox "
                       "bound)", s["conns_killed"]),
            om.counter("serving_net_accept_drops",
                       "connections dropped at accept (net_accept "
                       "fault seam / drain refusals)",
                       s["accept_drops"]),
            om.counter("serving_net_frames_accepted",
                       "REQUEST frames successfully parsed (each "
                       "terminates in exactly one wire verdict)",
                       s["frames_accepted"]),
            om.counter("serving_net_protocol_errors",
                       "malformed frames (ERROR frame sent, only the "
                       "offending connection closed)",
                       s["protocol_errors"]),
            om.metric("serving_net_verdicts", "counter",
                      "wire verdicts by class (counted at enqueue)",
                      [("_total", {"verdict": v}, c)
                       for v, c in sorted(s["verdicts"].items())]),
            om.counter("serving_net_verdicts_undeliverable",
                       "verdicts that could not be delivered (dead or "
                       "slow peer)", s["undeliverable_total"]),
            om.gauge("serving_net_open_connections",
                     "currently open front-door connections",
                     [({}, s["open_connections"])]),
            *self._replica_families(),
        ]

    def _replica_families(self) -> list:
        """serving_replica_* families — one labeled sample per
        replica, present even at one replica (rep="0") so dashboards
        need no schema switch when a fleet appears."""
        reps = self.replica_snapshot()
        return [
            om.gauge("serving_replica_queue_rows",
                     "queued rows on each replica's scheduler (the "
                     "admission/routing signal)",
                     [({"rep": str(r["replica"])}, r["queue_rows"])
                      for r in reps]),
            om.gauge("serving_replica_queue_depth",
                     "queued requests on each replica's scheduler",
                     [({"rep": str(r["replica"])}, r["queue_depth"])
                      for r in reps]),
            om.gauge("serving_replica_inflight_tickets",
                     "wire tickets submitted to a replica and not yet "
                     "routed back", [({"rep": str(r["replica"])},
                                      r["inflight_tickets"])
                                     for r in reps]),
            om.gauge("serving_replica_draining",
                     "1 while the replica is draining for a rolling "
                     "restart (2 once parked)",
                     [({"rep": str(r["replica"])},
                       (2 if r["parked"] else 1) if r["draining"] else 0)
                      for r in reps]),
            om.metric("serving_replica_verdicts", "counter",
                      "wire verdicts by replica and class (sums to "
                      "serving_net_verdicts)",
                      [("_total", {"rep": str(r["replica"]),
                                   "verdict": v}, c)
                       for r in reps
                       for v, c in sorted(r["verdicts"].items())]),
        ]
