"""The autotune probe registry: one seeded micro-probe per gated knob
(ISSUE 14 tentpole).

Catanzaro et al. tuned their GPU solver by measuring the hardware, not
by guessing, and ThunderSVM re-learned the same lesson at the
working-set level: the crossover points are device properties. This
registry defines the measurements that decide this repo's auto gates:

======================  =======================  =======================
probe                   A variant                B variant
======================  =======================  =======================
``pipeline``            plain block round        pipelined round
``pipeline_mesh``       plain mesh round         pipelined mesh round
``shardlocal``          global mesh working set  P shard-local chains
``ring``                all_gather exchange      Pallas DMA ring
``fused_round``         stock fused engine       one-HBM-pass round
``ooc_shrink``          full ooc tile stream     shrunken stream + recon
``bf16_gram``           float32 X storage        bfloat16 X storage
``serve_buckets``       right-sized dispatch     padded top-bucket
======================  =======================  =======================

Each probe is a short FIXED-SHAPE whole-chunk A/B in the style of the
``tools/profile_round.py`` ablations, run through the shared
measurement core (dpsvm_tpu/autotune/probe.py — the same salted /
differenced / best-of-N discipline), from seeded synthetic data.
Results are recorded through the runlog as schema'd ``probe`` records
and assembled into a :class:`~dpsvm_tpu.autotune.profile.DeviceProfile`
whose ``decisions`` feed solver/block.py's gate resolution.

THE HONESTY RULE: a verdict can only be True when the probe is
AUTHORITATIVE — measured on a real TPU, where the Pallas kernels run
their compiled lowerings. On the CPU harness the fused/ring kernels run
in interpret mode (a structure check, not a cost measurement), so every
CPU probe records its ratio with ``authoritative: false`` and the
verdict pinned False. That is what makes the committed CPU-harness seed
profile provably zero-HLO-effect: its decisions are identical to the
no-profile defaults.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from dpsvm_tpu.autotune.probe import (differenced_rounds, salted,
                                      timed_loop)

#: probe name -> the SVMConfig knob its verdict resolves (None =
#: informational only: recorded in the profile, never a gate input).
PROBE_KNOBS = {
    "pipeline": "pipeline_rounds",
    # The mesh pipelined engine is measured SEPARATELY: its overlap is
    # structural (collective-async gather/psum racing the replicated
    # subproblem chain) while the single-chip variant merely reorders
    # kernels — one verdict must not adjudicate the other engine.
    "pipeline_mesh": "pipeline_rounds_mesh",
    "shardlocal": "local_working_sets",
    "ring": "ring_exchange",
    "fused_round": "fused_round",
    # The ooc shrunken tile stream (solver/ooc.py, ISSUE 19): whether
    # skipping stream tiles cuts round wall time enough to amortize
    # the periodic full-stream reconstruction is a host<->device link
    # property (H2D bandwidth vs dispatch floor), so it is measured,
    # not assumed.
    "ooc_shrink": "ooc_shrink",
    "bf16_gram": None,  # the per-problem perturbation gate governs
    # Graduated from report-only (ISSUE 17): an authoritative pays
    # verdict arms the engine's between-legs bucket AUTO-APPLY when
    # ServeConfig.buckets=None. CPU-harness verdicts stay pinned False
    # (the honesty rule), so CI never auto-applies.
    "serve_buckets": "serve_buckets",
    # Informational only: whether the int8 union GEMM beats f32 on
    # this device. The ACTUAL int8 gate is the per-model calibrated
    # perturbation guard (serve.resolve_union_storage) — a device-wide
    # speed verdict must never overrule a per-model accuracy bound.
    "serve_quant": None,
}


@dataclasses.dataclass
class ProbeContext:
    """Shared knobs for one probe pass. ``smoke`` shrinks every shape
    to the CI-feasible minimum; ``timer`` is injectable so the
    determinism tests can drive the pass with a fake clock."""

    seed: int = 0
    smoke: bool = False
    timer: object = time.perf_counter
    obs: Optional[object] = None  # a RunLog (or None)
    # Fixed probe shapes (covtype-like d; rows a multiple of 1024 so
    # the fused padding contract q/2 <= n_pad/128 holds at every q).
    n: int = 4096
    d: int = 54
    q: int = 64
    reps: int = 6
    tries: int = 3

    def __post_init__(self):
        if self.smoke:
            self.n, self.d, self.q = 1024, 16, 16
            self.reps, self.tries = 2, 2
        if self.n % 1024 or self.q // 2 > self.n // 128:
            raise ValueError(
                f"probe shapes must satisfy the fused padding contract "
                f"(n % 1024 == 0, q/2 <= n/128): n={self.n} q={self.q}")

    @property
    def inner(self) -> int:
        return 2 * self.q

    def on_tpu(self) -> bool:
        import jax

        return jax.default_backend() == "tpu"

    def shapes(self) -> dict:
        return {"n": self.n, "d": self.d, "q": self.q,
                "inner": self.inner, "reps": self.reps}


def _dataset(ctx: ProbeContext, offset: int):
    """Seeded covtype-like synthetic rows (+/-1 labels) — the ONE
    generator bench.py's mesh/ooc/fused legs also use, so probe
    verdicts and BENCH artifacts measure the same data family."""
    from dpsvm_tpu.data import make_covtype_like

    return make_covtype_like(ctx.n, ctx.d, seed=ctx.seed + offset)


def _cfg(ctx: ProbeContext):
    from dpsvm_tpu.config import SVMConfig

    return SVMConfig(c=32.0, gamma=0.03125, epsilon=1e-3, engine="block",
                     working_set_size=ctx.q)


def _single_chip_operands(ctx: ProbeContext, offset: int, dtype=None):
    """Device operands + zero-start BlockState for the single-chip
    chunk runners (rows already probe-shaped, so no extra padding)."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       squared_norms)
    from dpsvm_tpu.solver.block import BlockState

    x, y = _dataset(ctx, offset)
    cfg = _cfg(ctx)
    kp = KernelParams("rbf", cfg.resolve_gamma(ctx.d))
    xd = jnp.asarray(x, dtype or jnp.float32)
    yd = jnp.asarray(y, jnp.float32)
    x_sq = jax.jit(squared_norms)(xd)
    k_diag = jax.jit(kernel_diag, static_argnames="params")(x_sq,
                                                            params=kp)
    valid = jnp.ones((ctx.n,), bool)
    base = BlockState(alpha=jnp.zeros((ctx.n,), jnp.float32), f=-yd,
                      b_hi=jnp.float32(-1e9), b_lo=jnp.float32(1e9),
                      pairs=jnp.int32(0), rounds=jnp.int32(0))
    return xd, yd, x_sq, k_diag, valid, base, kp, cfg


def _ab_record(probe: str, ctx: ProbeContext, a_label: str,
               b_label: str, a_seconds: float, b_seconds: float,
               authoritative: bool, note: Optional[str] = None,
               threshold: float = None) -> dict:
    """Assemble one schema'd probe record; the verdict rule lives here
    so every probe shares it: authoritative AND B at or under
    `threshold` x A."""
    from dpsvm_tpu.autotune.profile import PAYS_THRESHOLD

    thr = PAYS_THRESHOLD if threshold is None else threshold
    # None (not inf/0.0) unless BOTH sides measured above the clock's
    # resolution: the differenced timers clamp at 0.0, so a zero on
    # EITHER side is jitter, not a measurement — and a verdict must
    # never flip a gate ON from a 0.0/a "infinitely better" reading
    # (strict-JSON clean as a bonus).
    ratio = (b_seconds / a_seconds
             if a_seconds > 0 and b_seconds > 0 else None)
    rec = {
        "probe": probe,
        "knob": PROBE_KNOBS[probe],
        "shapes": ctx.shapes(),
        "seed": ctx.seed,
        "a": a_label,
        "b": b_label,
        # 9 digits: the per-rep/per-pair probes measure down to
        # nanoseconds-scale units, and a committed profile must stay
        # reconcilable from its own a/b fields.
        "a_seconds": round(a_seconds, 9),
        "b_seconds": round(b_seconds, 9),
        "ratio": round(ratio, 4) if ratio is not None else None,
        "threshold": thr,
        "authoritative": bool(authoritative),
        "verdict": bool(authoritative and ratio is not None
                        and ratio <= thr),
    }
    if note:
        rec["note"] = note
    return rec


def _skip_record(probe: str, ctx: ProbeContext, reason: str) -> dict:
    return {"probe": probe, "knob": PROBE_KNOBS[probe],
            "shapes": ctx.shapes(), "seed": ctx.seed, "skipped": reason,
            "authoritative": False, "verdict": False}


# ------------------------------------------------------ single-chip A/Bs

def probe_pipeline(ctx: ProbeContext) -> dict:
    """Plain vs pipelined block round (the pipeline_rounds gate)."""
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import (run_chunk_block,
                                        run_chunk_block_pipelined)
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    xd, yd, x_sq, k_diag, valid, base, kp, cfg = \
        _single_chip_operands(ctx, offset=11)
    on_tpu = ctx.on_tpu()
    impl = "pallas" if on_tpu else "xla"
    common = (kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), ctx.q,
              ctx.inner)

    def make_plain(rpc):
        return lambda st: run_chunk_block(
            xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9),
            *common, rpc, inner_impl=impl)

    def make_pipe(rpc):
        return lambda st: run_chunk_block_pipelined(
            xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9),
            *common, rpc, inner_impl=impl, interpret=not on_tpu,
            pallas_select=on_tpu)

    ta, _, _ = differenced_rounds(make_plain, base, ctx.reps,
                                  salt_base=1, tries=ctx.tries,
                                  timer=ctx.timer)
    tb, _, _ = differenced_rounds(make_pipe, base, ctx.reps,
                                  salt_base=2, tries=ctx.tries,
                                  timer=ctx.timer)
    return _ab_record(
        "pipeline", ctx, "plain_round", "pipelined_round", ta, tb,
        authoritative=on_tpu,
        note=None if on_tpu else
        "CPU harness: XLA-only variants (no Pallas candidate kernel); "
        "structure check, verdict pinned False")


def probe_fused_round(ctx: ProbeContext) -> dict:
    """Stock fused engine vs the one-HBM-pass round (the fused_round
    gate). Interpret-mode kernels off-TPU — structure check only."""
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import (run_chunk_block_fused,
                                        run_chunk_block_fusedround)
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    xd, yd, x_sq, k_diag, valid, base, kp, cfg = \
        _single_chip_operands(ctx, offset=12)
    on_tpu = ctx.on_tpu()
    impl = "pallas" if on_tpu else "xla"
    common = (kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), ctx.q,
              ctx.inner)

    def make_fused(rpc):
        return lambda st: run_chunk_block_fused(
            xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9),
            *common, rpc, inner_impl=impl, interpret=not on_tpu)

    def make_fusedround(rpc):
        return lambda st: run_chunk_block_fusedround(
            xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9),
            *common, rpc, inner_impl=impl, interpret=not on_tpu)

    ta, _, _ = differenced_rounds(make_fused, base, ctx.reps,
                                  salt_base=3, tries=ctx.tries,
                                  timer=ctx.timer)
    tb, _, _ = differenced_rounds(make_fusedround, base, ctx.reps,
                                  salt_base=4, tries=ctx.tries,
                                  timer=ctx.timer)
    return _ab_record(
        "fused_round", ctx, "fused_fold", "one_pass_round", ta, tb,
        authoritative=on_tpu,
        note=None if on_tpu else
        "CPU harness: interpret-mode Pallas (emulated DMAs); structure "
        "check, verdict pinned False")


def probe_ooc_shrink(ctx: ProbeContext) -> dict:
    """Full vs shrunken out-of-core stream round (the ooc_shrink gate).

    A streams EVERY tile of a seeded host-resident X through the
    double-buffered fold (the solver/ooc.py round body, stripped of
    selection/subproblem — the stream is what shrinking changes); B
    streams only a quarter of the tiles (the active view's live set)
    PLUS the amortized reconstruction share — ceil(tiles /
    _SHRINK_CYCLE_ROUNDS) extra tiles per round, the per-round cost of
    the full rebuild each cycle pays. Verdict True means the tile skip
    pays its reconstruction freight on this host<->device link.

    The stream is host-driven by construction (each tile's device_put
    is issued from host memory), so this probe cannot ride the
    in-dispatch timed_loop; it keeps the rest of the measurement
    discipline — warmed compiles, best-of-`tries` with salted fresh
    gradient buffers, the shared verdict rule. On the CPU harness a
    "device_put" is a memcpy, not a DMA over the host link, so the
    timing is not representative of any TPU and the verdict stays
    pinned False (the honesty rule)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dpsvm_tpu.ops.kernels import KernelParams, squared_norms
    from dpsvm_tpu.ops.ooc import ooc_fold_tile
    from dpsvm_tpu.solver.ooc import (_SHRINK_CYCLE_ROUNDS, _put_tile,
                                      _tile_sq)

    x, _ = _dataset(ctx, offset=18)
    x = np.ascontiguousarray(np.asarray(x, np.float32))
    kp = KernelParams("rbf", _cfg(ctx).resolve_gamma(ctx.d))
    device = jax.devices()[0]
    tiles = 8
    tile = ctx.n // tiles
    rng = np.random.default_rng(ctx.seed + 18)
    w = rng.choice(ctx.n, size=ctx.q, replace=False)
    qx = jax.device_put(jnp.asarray(x[w]), device)
    qsq = jax.jit(squared_norms)(qx)
    xsq_tiles = [
        _tile_sq(jax.device_put(
            jnp.asarray(x[i * tile:(i + 1) * tile]), device))
        for i in range(tiles)
    ]
    # Small coefficients keep the folded gradient finite across reps
    # (cost is value-independent; the salt only needs live buffers).
    coef = jax.device_put(
        jnp.asarray(rng.normal(size=(ctx.q,)).astype(np.float32) * 1e-3),
        device)

    def stream(order, f):
        ft = None
        nxt = _put_tile(x, order[0] * tile, tile, ctx.n, ctx.d,
                        jnp.float32, device)
        for oi, i in enumerate(order):
            cur, nxt = nxt, (
                _put_tile(x, order[oi + 1] * tile, tile, ctx.n, ctx.d,
                          jnp.float32, device)
                if oi + 1 < len(order) else None)
            s = i * tile
            ft, _, _ = ooc_fold_tile(cur, xsq_tiles[i], f[s:s + tile],
                                     None, qx, qsq, coef, kp=kp)
        jax.block_until_ready(ft)

    recon_share = -(-tiles // _SHRINK_CYCLE_ROUNDS)
    full = list(range(tiles))
    live = list(range(max(1, tiles // 4))) \
        + [i % tiles for i in range(recon_share)]

    def run_variant(order, salt_base):
        f0 = jax.device_put(jnp.asarray(-np.ones(ctx.n, np.float32)),
                            device)
        stream(order, f0)  # compile + warm (one shape for every tile)
        best = None
        for k in range(ctx.tries):
            fk = salted(f0, salt_base + k)
            t0 = ctx.timer()
            for _ in range(ctx.reps):
                stream(order, fk)
            t = ctx.timer() - t0
            best = t if best is None or t < best else best
        return best / ctx.reps

    ta = run_variant(full, salt_base=1)
    tb = run_variant(live, salt_base=101)
    on_tpu = ctx.on_tpu()
    rec = _ab_record(
        "ooc_shrink", ctx, "full_stream_round",
        "shrunken_round_amortized", ta, tb, authoritative=on_tpu,
        note="B folds len(live) of len(full) tiles incl. the amortized "
             "reconstruction share; verdict True arms the ooc_shrink "
             "auto gate" if on_tpu else
             "CPU harness: device_put is a memcpy, not the host link; "
             "structure check, verdict pinned False")
    rec["shapes"] = {**ctx.shapes(), "tiles": tiles, "tile_rows": tile,
                     "live_tiles": len(live),
                     "recon_share_tiles": recon_share}
    return rec


def probe_bf16_gram(ctx: ProbeContext) -> dict:
    """float32 vs bfloat16 X storage through the plain block chunk (the
    storage flip config.bf16_gram makes when its perturbation bound
    accepts). Informational: the PER-PROBLEM quality gate still
    governs; this measures whether the halved fold/Gram read traffic
    shows up on this device at all."""
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import run_chunk_block
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    on_tpu = ctx.on_tpu()
    times = {}
    for name, dt in (("float32", jnp.float32),
                     ("bfloat16", jnp.bfloat16)):
        xd, yd, x_sq, k_diag, valid, base, kp, cfg = \
            _single_chip_operands(ctx, offset=13, dtype=dt)

        def make(rpc, xd=xd, yd=yd, x_sq=x_sq, k_diag=k_diag,
                 valid=valid, kp=kp, cfg=cfg):
            return lambda st: run_chunk_block(
                xd, yd, x_sq, k_diag, valid, st, jnp.int32(10 ** 9),
                kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau),
                ctx.q, ctx.inner, rpc, inner_impl="xla")

        times[name], _, _ = differenced_rounds(
            make, base, ctx.reps, salt_base=5 if name == "float32"
            else 6, tries=ctx.tries, timer=ctx.timer)
    return _ab_record(
        "bf16_gram", ctx, "float32_x", "bfloat16_x",
        times["float32"], times["bfloat16"], authoritative=on_tpu,
        note="informational: config.bf16_gram stays behind the "
             "per-problem perturbation bound either way")


# ------------------------------------------------------------- mesh A/Bs

def _mesh_operands(ctx: ProbeContext, offset: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                       squared_norms)
    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                         pad_rows)
    from dpsvm_tpu.solver.block import BlockState

    x, y = _dataset(ctx, offset)
    cfg = _cfg(ctx)
    kp = KernelParams("rbf", cfg.resolve_gamma(ctx.d))
    mesh = make_data_mesh()
    p_dev = int(mesh.devices.size)
    n_pad = pad_rows(ctx.n, p_dev)
    x_p = np.zeros((n_pad, ctx.d), np.float32)
    x_p[:ctx.n] = x
    y_p = np.ones((n_pad,), np.float32)
    y_p[:ctx.n] = y
    valid = np.zeros((n_pad,), bool)
    valid[:ctx.n] = True
    shard = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    xd = jax.device_put(jnp.asarray(x_p), shard)
    yd = jax.device_put(jnp.asarray(y_p), shard)
    x_sq = jax.jit(squared_norms, out_shardings=shard)(xd)
    k_diag = jax.jit(kernel_diag, static_argnames="params",
                     out_shardings=shard)(x_sq, params=kp)
    vd = jax.device_put(jnp.asarray(valid), shard)
    base = BlockState(
        alpha=jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard),
        f=jax.device_put(jnp.asarray(-y_p, jnp.float32), shard),
        b_hi=jax.device_put(jnp.float32(-1e9), rep),
        b_lo=jax.device_put(jnp.float32(1e9), rep),
        pairs=jax.device_put(jnp.int32(0), rep),
        rounds=jax.device_put(jnp.int32(0), rep))
    return mesh, p_dev, xd, yd, x_sq, k_diag, vd, base, kp, cfg


def probe_shardlocal(ctx: ProbeContext, sync_rounds: int = 2) -> dict:
    # sync_rounds must divide BOTH differenced chunk lengths (reps and
    # 2*reps are even) — a sync window that rounds them to the same
    # rounds_per_chunk would zero the differenced measurement.
    """Global vs shard-local mesh working sets over every visible
    device (the local_working_sets gate). The decisive number is
    pairs/s — P concurrent chains execute MORE pairs per wall-round —
    so this probe's ratio is seconds-per-EXECUTED-PAIR, not raw chunk
    seconds."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.parallel.dist_block import (
        make_block_chunk_runner, make_block_shardlocal_chunk_runner)
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    if len(jax.devices()) < 2:
        # The ring-probe discipline: a P=1 mesh measures pure sync
        # overhead (the expected-loss regime), and committing that as
        # an AUTHORITATIVE kind-wide False would mask that the knob
        # was never measured in its paying P>=2 regime — skip, knob
        # stays on defaults.
        return _skip_record(
            "shardlocal", ctx,
            "needs >= 2 devices (P=1 is pure sync overhead, not the "
            "concurrent-chain regime)")
    mesh, p_dev, xd, yd, x_sq, k_diag, vd, base, kp, cfg = \
        _mesh_operands(ctx, offset=14)
    on_tpu = ctx.on_tpu()
    impl = "pallas" if on_tpu else "xla"
    args = (kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), ctx.q,
            ctx.inner)

    def wrap(runner):
        return lambda st: runner(xd, yd, x_sq, k_diag, vd, st,
                                 jnp.int32(10 ** 9))

    def make_global(rpc):
        return wrap(make_block_chunk_runner(mesh, *args, rpc, impl))

    def make_local(rpc):
        rpc = -(-rpc // sync_rounds) * sync_rounds
        return wrap(make_block_shardlocal_chunk_runner(
            mesh, *args, rpc, sync_rounds, impl,
            interpret=not on_tpu))

    ta, _, pa = differenced_rounds(make_global, base, ctx.reps,
                                   salt_base=7, tries=ctx.tries,
                                   timer=ctx.timer)
    tb, _, pb = differenced_rounds(make_local, base, ctx.reps,
                                   salt_base=8, tries=ctx.tries,
                                   timer=ctx.timer)
    # seconds per executed pair: the shard-local engine's P concurrent
    # chains legitimately execute ~P x the pairs per wall-round.
    spa = ta / max(pa, 1)
    spb = tb / max(pb, 1)
    rec = _ab_record(
        "shardlocal", ctx, "global_working_set",
        f"shardlocal_p{p_dev}", spa, spb, authoritative=on_tpu,
        note=None if on_tpu else
        "CPU harness mesh: structure check, verdict pinned False")
    rec["unit"] = "seconds_per_pair"
    rec["n_devices"] = p_dev
    rec["sync_rounds"] = sync_rounds
    rec["pairs"] = {"a": int(pa), "b": int(pb)}
    return rec


def probe_pipeline_mesh(ctx: ProbeContext) -> dict:
    """Global vs PIPELINED mesh block runner (the mesh-specific
    pipeline_rounds gate, knob ``pipeline_rounds_mesh``). This is the
    engine where the overlap is STRUCTURAL — the prefetched
    all_gather/psum pair is collective-async and can hide behind the
    replicated subproblem chain — so it gets its own measurement
    instead of inheriting the single-chip probe's verdict (that
    variant only reorders kernels and is expected to measure a loss).
    Needs >= 2 devices: at P=1 the collectives are trivial and there
    is nothing to overlap."""
    import jax
    import jax.numpy as jnp

    from dpsvm_tpu.parallel.dist_block import (
        make_block_chunk_runner, make_block_pipelined_chunk_runner)
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    if len(jax.devices()) < 2:
        return _skip_record(
            "pipeline_mesh", ctx,
            "needs >= 2 devices (the overlap is the collective-vs-"
            "chain race)")
    mesh, p_dev, xd, yd, x_sq, k_diag, vd, base, kp, cfg = \
        _mesh_operands(ctx, offset=17)
    on_tpu = ctx.on_tpu()
    impl = "pallas" if on_tpu else "xla"
    args = (kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), ctx.q,
            ctx.inner)

    def make(pipelined):
        def _make(rpc):
            mk = (make_block_pipelined_chunk_runner if pipelined
                  else make_block_chunk_runner)
            runner = mk(mesh, *args, rpc, impl)
            return lambda st: runner(xd, yd, x_sq, k_diag, vd, st,
                                     jnp.int32(10 ** 9))
        return _make

    ta, _, _ = differenced_rounds(make(False), base, ctx.reps,
                                  salt_base=11, tries=ctx.tries,
                                  timer=ctx.timer)
    tb, _, _ = differenced_rounds(make(True), base, ctx.reps,
                                  salt_base=12, tries=ctx.tries,
                                  timer=ctx.timer)
    rec = _ab_record(
        "pipeline_mesh", ctx, "plain_mesh_round",
        "pipelined_mesh_round", ta, tb, authoritative=on_tpu,
        note=None if on_tpu else
        "CPU harness mesh: structure check, verdict pinned False")
    rec["n_devices"] = p_dev
    return rec


def probe_ring(ctx: ProbeContext) -> dict:
    """all_gather vs Pallas DMA-ring candidate exchange on the global
    mesh runner (the ring_exchange gate). Needs >= 2 devices (a
    one-device ring has no hops)."""
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < 2:
        return _skip_record("ring", ctx,
                            "needs >= 2 devices (no hops to ring)")

    from dpsvm_tpu.parallel.dist_block import make_block_chunk_runner
    from dpsvm_tpu.solver.smo import _BUDGET_EPS

    mesh, p_dev, xd, yd, x_sq, k_diag, vd, base, kp, cfg = \
        _mesh_operands(ctx, offset=15)
    on_tpu = ctx.on_tpu()
    impl = "pallas" if on_tpu else "xla"
    args = (kp, cfg.c_bounds(), _BUDGET_EPS, float(cfg.tau), ctx.q,
            ctx.inner)

    def make(ring):
        def _make(rpc):
            runner = make_block_chunk_runner(
                mesh, *args, rpc, impl, interpret=not on_tpu,
                ring_exchange=ring)
            return lambda st: runner(xd, yd, x_sq, k_diag, vd, st,
                                     jnp.int32(10 ** 9))
        return _make

    ta, _, _ = differenced_rounds(make(False), base, ctx.reps,
                                  salt_base=9, tries=ctx.tries,
                                  timer=ctx.timer)
    tb, _, _ = differenced_rounds(make(True), base, ctx.reps,
                                  salt_base=10, tries=ctx.tries,
                                  timer=ctx.timer)
    rec = _ab_record(
        "ring", ctx, "all_gather", "dma_ring", ta, tb,
        authoritative=on_tpu,
        note=None if on_tpu else
        "CPU harness: interpret-mode ring (DMAs emulated as gathers); "
        "structure check, verdict pinned False")
    rec["n_devices"] = p_dev
    return rec


# -------------------------------------------------------- serving probe

def probe_serve_buckets(ctx: ProbeContext) -> dict:
    """Padded top-bucket dispatch vs a right-sized bucket at the same
    live rows: does dispatch cost actually scale with the bucket on
    this device, or is it latency-floored? When right-sizing pays
    (ratio well under 1), the engine's batch-occupancy histogram is
    actionable and ``suggest_buckets`` advice is worth applying; when
    it does not, padding is free and coarse buckets win on compile
    count. Graduated from report-only (ISSUE 17): an authoritative
    pays verdict arms the serving engine's between-legs bucket
    auto-apply when ``ServeConfig.buckets=None``; an explicit ladder
    always wins."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(ctx.seed + 16)
    s_rows = 256 if ctx.smoke else 1024  # SV-union rows
    big, small = (64, 16) if ctx.smoke else (256, 64)
    sv = jnp.asarray(rng.normal(size=(s_rows, ctx.d)), jnp.float32)
    coef = jnp.asarray(rng.normal(size=(s_rows,)), jnp.float32)
    reps = 256 if ctx.smoke else 2048
    times = {}
    for bucket in (big, small):
        qb = jnp.asarray(rng.normal(size=(bucket, ctx.d)), jnp.float32)

        def dispatch(qb, sv, coef):
            # The bucket executor's compute shape: (bucket, d) x (d, S)
            # kernel dots + the coef contraction (serve.py's decision
            # fold, stripped of the kernel transform — same roofline).
            k = qb @ sv.T
            dec = k @ coef
            return qb + jnp.float32(1e-20) * dec[0], sv, coef

        # Far more in-dispatch reps than the solver probes: one bucket
        # dispatch is microseconds-scale, and the differenced time must
        # clear the clock's resolution on every harness.
        times[bucket] = timed_loop(dispatch, qb, sv, coef,
                                   reps=reps, timer=ctx.timer)
    rec = _ab_record(
        "serve_buckets", ctx, f"bucket_{big}", f"bucket_{small}",
        times[big], times[small], authoritative=ctx.on_tpu(),
        threshold=float(small) / big + 0.25,
        note="verdict True means dispatch cost tracks the bucket "
             "(occupancy-driven bucket suggestions pay): arms the "
             "engine's between-legs auto-apply for buckets=None; an "
             "explicit ServeConfig.buckets always wins")
    # This probe's record must describe ITS measurement, not the
    # solver-probe shapes the shared ctx carries: a (bucket, d) x
    # (d, sv_rows) dispatch GEMM at `reps` in-dispatch reps — the
    # committed profile is reconcilable from these fields.
    rec["shapes"] = {"d": ctx.d, "sv_rows": s_rows,
                     "bucket_a": big, "bucket_b": small, "reps": reps}
    return rec


def probe_serve_quant(ctx: ProbeContext) -> dict:
    """f32 vs int8 union storage at the serve bucket's compute shape:
    the quantized executor's roofline — on-device per-row query
    quantization, an int8 x int8 -> i32 MXU dot, the f32 dequant fuse,
    the coef contraction — against the plain f32 dispatch GEMM.

    Informational ONLY (knob None): whether int8 is FAST here is a
    device property, but whether it is SAFE is a per-model property —
    the calibrated perturbation guard (serve.resolve_union_storage)
    adjudicates storage, and a device-wide speed verdict must never
    overrule an accuracy bound. The record lands in the DeviceProfile
    so BENCH_SERVE frontiers and operators can see where the MXU's
    int8 path pays; on the CPU harness the timing is emulation-shaped
    and the verdict stays pinned False (the honesty rule)."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(ctx.seed + 17)
    s_rows = 256 if ctx.smoke else 1024  # SV-union rows
    bucket = 64 if ctx.smoke else 256
    reps = 256 if ctx.smoke else 2048
    sv_f = rng.normal(size=(s_rows, ctx.d)).astype(np.float32)
    from dpsvm_tpu.ops.kernels import quantize_rows_int8

    sv_q_np, sv_scale_np = quantize_rows_int8(sv_f)
    sv = jnp.asarray(sv_f)
    sv_q = jnp.asarray(sv_q_np)
    sv_scale = jnp.asarray(sv_scale_np)
    coef = jnp.asarray(rng.normal(size=(s_rows,)), jnp.float32)
    qb = jnp.asarray(rng.normal(size=(bucket, ctx.d)), jnp.float32)

    def dispatch_f32(qb, sv, coef):
        k = qb @ sv.T
        dec = k @ coef
        return qb + jnp.float32(1e-20) * dec[0], sv, coef

    def dispatch_int8(qb, sv_q, sv_scale, coef):
        # The int8 bucket executor's algebra, stripped of the kernel
        # transform (same roofline as _dense_batch_int8_factory).
        t = jnp.max(jnp.abs(qb), axis=1) / 127.0
        t = jnp.where(t > 0, t, 1.0)
        q_q = jnp.clip(jnp.round(qb / t[:, None]), -127, 127
                       ).astype(jnp.int8)
        idots = jnp.dot(q_q, sv_q.T,
                        preferred_element_type=jnp.int32)
        k = idots.astype(jnp.float32) * (t[:, None] * sv_scale[None, :])
        dec = k @ coef
        return qb + jnp.float32(1e-20) * dec[0], sv_q, sv_scale, coef

    t_f32 = timed_loop(dispatch_f32, qb, sv, coef,
                       reps=reps, timer=ctx.timer)
    t_int8 = timed_loop(dispatch_int8, qb, sv_q, sv_scale, coef,
                        reps=reps, timer=ctx.timer)
    rec = _ab_record(
        "serve_quant", ctx, "union_f32", "union_int8", t_f32, t_int8,
        authoritative=ctx.on_tpu(),
        note="informational: int8 union GEMM vs f32 at the serve "
             "bucket shape; storage is adjudicated per-model by the "
             "calibrated perturbation guard, never by this record"
             + ("" if ctx.on_tpu() else
                "; CPU harness: int8 dot emulated, verdict pinned "
                "False"))
    rec["shapes"] = {"d": ctx.d, "sv_rows": s_rows,
                     "bucket": bucket, "reps": reps}
    return rec


#: registry order = execution order (cheap single-chip first).
PROBES = {
    "pipeline": probe_pipeline,
    "bf16_gram": probe_bf16_gram,
    "fused_round": probe_fused_round,
    "ooc_shrink": probe_ooc_shrink,
    "shardlocal": probe_shardlocal,
    "pipeline_mesh": probe_pipeline_mesh,
    "ring": probe_ring,
    "serve_buckets": probe_serve_buckets,
    "serve_quant": probe_serve_quant,
}


def run_probes(knobs=None, seed: int = 0, smoke: bool = False,
               timer=None, obs_config=None, verbose: bool = True):
    """Run the registry (or the `knobs` subset of probe names) and
    assemble a DeviceProfile. With obs enabled, every probe mirrors its
    record into an ``autotune`` runlog stream as a ``probe`` record
    (plus the manifest/final envelope every tool shares)."""
    from dpsvm_tpu.autotune.profile import DeviceProfile, stamp
    from dpsvm_tpu.obs import obs_enabled
    from dpsvm_tpu.obs.runlog import RunLog

    ctx = ProbeContext(seed=seed, smoke=smoke,
                       **({"timer": timer} if timer is not None else {}))
    names = list(PROBES) if knobs is None else list(knobs)
    unknown = [k for k in names if k not in PROBES]
    if unknown:
        raise ValueError(f"unknown probes {unknown}; "
                         f"registry has {list(PROBES)}")
    ident = stamp()
    rl = None
    if obs_config is not None and obs_enabled(obs_config):
        rl = RunLog.open("autotune", obs_config=obs_config,
                         meta={"probes": names, "seed": seed,
                               "smoke": bool(smoke), **ctx.shapes()})
    probes, decisions = {}, {}
    try:
        for name in names:
            rec = PROBES[name](ctx)
            probes[name] = rec
            if PROBE_KNOBS[name] is not None \
                    and not rec.get("skipped"):
                # A SKIPPED probe must leave its knob OUT of the
                # decisions map (gate falls back to the hand-measured
                # default) — recording False would masquerade as a
                # measured verdict, e.g. a 1-device host pinning
                # ring_exchange for the whole device kind.
                decisions[PROBE_KNOBS[name]] = bool(rec["verdict"])
            if rl is not None:
                rl.record("probe", **rec)
            if verbose:
                import sys

                if rec.get("skipped"):
                    line = f"skipped ({rec['skipped']})"
                else:
                    rr = rec["ratio"]
                    line = (f"{rec['a']} {rec['a_seconds']:.4f}s vs "
                            f"{rec['b']} {rec['b_seconds']:.4f}s — "
                            f"ratio {f'{rr:.3f}' if rr is not None else '-'} "
                            f"(threshold {rec['threshold']}, "
                            f"authoritative={rec['authoritative']}) "
                            f"-> verdict {rec['verdict']}")
                print(f"[autotune] {name}: {line}", file=sys.stderr)
    finally:
        if rl is not None:
            rl.finish(decisions=decisions)
    return DeviceProfile(seed=seed, probes=probes, decisions=decisions,
                         **ident)
