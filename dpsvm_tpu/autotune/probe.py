"""Shared A/B probe measurement core (ISSUE 14 satellite).

Every device A/B this repo runs — the ``tools/profile_round.py``
``--pipeline/--shardlocal/--ring/--bf16-gram/--fused-round`` ablations
and the autotune pass's registry probes (dpsvm_tpu/autotune/probes.py)
— needs the same three defenses against the tunneled runtime:

* :func:`salted` — a representable off-clock perturbation of the probe
  state, because re-dispatching an identical buffer OR identical
  contents can be served from the result cache without executing
  (measured ~0 ms; the tools/bench_predict.py trap);
* :func:`differenced_rounds` — the whole-chunk differenced timing: run
  the same chunk body at two chunk lengths (reps and 2*reps) and
  difference, so the tunnel's fixed per-dispatch latency (~60-80 ms)
  cancels instead of reading as +F/reps ms on every round;
* best-of-N per chunk length, absorbing tunnel jitter between probes.

Before this module each ablation re-implemented the warmup/salt/timing
loop; factoring it here makes the tool ablations and the autotune
probes the SAME measurement — a profile verdict and a profile_round
table can be compared number for number.

``timer`` is injectable everywhere (default ``time.perf_counter``) so
the autotune determinism tests can drive the whole measurement path
with a fake clock and assert byte-stable records.
"""

from __future__ import annotations

import time
from functools import partial


def salted(x, k: int):
    """Return a copy of float array/scalar x whose contents differ
    REPRESENTABLY from x (relative 2^-20 bump, exact in fp32 for any
    magnitude) in a fresh device buffer. Both properties matter on the
    tunneled runtime: re-dispatching the same buffer OR content-identical
    values can be served from the result cache without executing
    (measured ~0 ms readings; see the bench_predict.py trap notes). The
    perturbation is harmless to cost profiling — probe runs never need
    exact optima."""
    import jax
    import jax.numpy as jnp

    out = x * jnp.float32(1.0 + k * 2.0 ** -20)
    jax.block_until_ready(out)
    return out


def timed_loop(fn, *args, reps: int, timer=time.perf_counter) -> float:
    """Seconds per repetition of fn, measured inside one dispatch.

    Differences two in-dispatch repetition counts (reps and 2*reps) so the
    tunnel's fixed per-dispatch latency cancels — a single-dispatch
    measurement reads tens of ms of sync overhead into every stage
    (the trap documented in tools/bench_predict.py; on a local TPU the
    two estimates agree)."""
    import jax
    from jax import lax

    @partial(jax.jit, static_argnames="n")
    def loop(*a, n):
        def body(i, carry):
            return fn(*carry)
        return lax.fori_loop(0, n, body, a)

    jax.block_until_ready(loop(*args, n=reps))      # compile 1
    jax.block_until_ready(loop(*args, n=2 * reps))  # compile 2

    salt = [0]

    def run(n):
        # Off-clock representable perturbation of the first float arg —
        # see salted() for why both fresh buffer and fresh contents are
        # required on this runtime.
        salt[0] += 1
        a = (salted(args[0], salt[0]),) + args[1:]
        t0 = timer()
        jax.block_until_ready(loop(*a, n=n))
        return timer() - t0

    # best-of-2 per count absorbs tunnel jitter between the two probes.
    t1 = min(run(reps), run(reps))
    t2 = min(run(2 * reps), run(2 * reps))
    return max(t2 - t1, 0.0) / reps


def best_chunk(run, base_state, salt_base: int, tries: int = 3,
               timer=time.perf_counter):
    """Best-of-`tries` timed executions of one chunk runner from salted
    fresh starts. `run(state)` must return a state carrying ``.rounds``
    and ``.pairs`` (the BlockState contract every chunk runner shares).
    Returns ``(seconds, rounds, pairs)`` of the fastest try."""
    import jax

    best = None
    for k in range(tries):
        st = base_state._replace(f=salted(base_state.f, salt_base + k))
        t0 = timer()
        out = run(st)
        jax.block_until_ready(out)
        t = timer() - t0
        if best is None or t < best[0]:
            best = (t, int(out.rounds), int(out.pairs))
    return best


def differenced_rounds(make_run, base_state, reps: int, *,
                       salt_base: int = 0, tries: int = 3,
                       timer=time.perf_counter):
    """THE whole-chunk differenced probe: build (and warm) the chunk
    runner at `reps` and `2*reps` rounds per chunk, time each best-of-
    `tries` from salted starts, and difference — the tunnel's fixed
    per-dispatch latency and the warmed first-execution ramp both
    cancel, leaving `reps` rounds of pure chunk-body time.

    `make_run(rounds_per_chunk)` returns a callable ``run(state) ->
    state`` whose output carries ``.rounds``/``.pairs``. Returns
    ``(seconds, rounds, pairs)`` for the differenced `reps`-round
    window (clamped at >= 0 seconds)."""
    import jax

    runs = {}
    for rpc in (reps, 2 * reps):
        run = make_run(rpc)
        jax.block_until_ready(run(base_state))  # compile + warm
        runs[rpc] = best_chunk(run, base_state,
                               salt_base=salt_base + 101 * rpc,
                               tries=tries, timer=timer)
    t = max(runs[2 * reps][0] - runs[reps][0], 0.0)
    rounds = runs[2 * reps][1] - runs[reps][1]
    pairs = runs[2 * reps][2] - runs[reps][2]
    return t, rounds, pairs
