"""dpsvm_tpu.autotune — measured device profiling for the auto gates
(ISSUE 14; ROADMAP item 5).

Turns the obs spine from a recorder into a decision-maker:

* :mod:`dpsvm_tpu.autotune.probe`   — the shared A/B measurement core
  (salted starts, differenced whole-chunk timing) used by BOTH the
  tools/profile_round.py ablations and the registry probes.
* :mod:`dpsvm_tpu.autotune.probes`  — one seeded micro-probe per gated
  knob (pipeline / shardlocal / ring / fused_round, plus the
  informational bf16_gram and serve_buckets probes), each recorded
  through the runlog as a schema'd ``probe`` record.
* :mod:`dpsvm_tpu.autotune.profile` — the committed ``DeviceProfile``
  JSON (one per device kind, jax-version-stamped, regenerated via
  ``make autotune``) and the gate-decision lookup solver/block.py's
  :func:`~dpsvm_tpu.solver.block.resolve_auto_gate` consults.

CLI: ``python -m dpsvm_tpu.cli autotune {run,show,diff}`` (cli.py
forwards argv verbatim to :func:`run_cli` — the lint/obs forwarding
discipline).

The contract, pinned by tests/test_autotune.py: the autotuner changes
*decisions*, never *programs* — no applicable profile means every gate
behaves exactly as the hand-measured defaults, and a CPU-harness
profile (non-authoritative probes) resolves to those same defaults
while still recording measured ratios and provenance.
"""

from __future__ import annotations

import os
import sys

from dpsvm_tpu.autotune.probe import (differenced_rounds, salted,
                                      timed_loop)
from dpsvm_tpu.autotune.probes import PROBE_KNOBS, PROBES, run_probes
from dpsvm_tpu.autotune.profile import (DeviceProfile, ProfileError,
                                        active_profile, gate_decision,
                                        load_profile, profile_path,
                                        profiles_dir, slug, use_profile)

__all__ = [
    "DeviceProfile", "ProfileError", "PROBES", "PROBE_KNOBS",
    "active_profile", "differenced_rounds", "gate_decision",
    "load_profile", "profile_path", "profiles_dir", "run_cli",
    "run_probes", "salted", "timed_loop", "use_profile",
]

#: probe-record fields that must be byte-stable across two passes with
#: the same seed on the same harness (the determinism contract the
#: smoke target asserts; timings legitimately jitter).
STABLE_PROBE_FIELDS = ("probe", "knob", "shapes", "seed", "a", "b",
                       "threshold", "authoritative", "skipped", "unit",
                       "n_devices", "sync_rounds")


def stable_view(profile: DeviceProfile) -> dict:
    """The deterministic projection of a profile: everything except
    the measured seconds/ratios and the identity timestamp."""
    return {
        "device_kind": profile.device_kind,
        "backend": profile.backend,
        "n_devices": profile.n_devices,
        "seed": profile.seed,
        "decisions": dict(profile.decisions),
        "probes": {name: {k: rec[k] for k in STABLE_PROBE_FIELDS
                          if k in rec}
                   for name, rec in profile.probes.items()},
    }


def _decision_table(profile: DeviceProfile) -> str:
    lines = [f"{'probe':<14} {'knob':<18} {'ratio':>8} {'thr':>5} "
             f"{'auth':>5} {'verdict':>7}",
             "-" * 62]
    for name, rec in profile.probes.items():
        if rec.get("skipped"):
            lines.append(f"{name:<14} {str(rec.get('knob')):<18} "
                         f"{'skipped: ' + rec['skipped']}")
            continue
        rr = rec.get("ratio")
        lines.append(
            f"{name:<14} {str(rec.get('knob')):<18} "
            f"{f'{rr:.3f}' if rr is not None else '-':>8} "
            f"{rec.get('threshold', 0):>5.2f} "
            f"{str(rec.get('authoritative')):>5} "
            f"{str(rec.get('verdict')):>7}")
    lines.append("")
    lines.append("decisions: " + (", ".join(
        f"{k}={v}" for k, v in sorted(profile.decisions.items()))
        or "(none)"))
    return "\n".join(lines)


def _merge_partial(fresh: DeviceProfile, path: str) -> DeviceProfile:
    """Merge a partial (``--knobs`` subset) pass into the existing
    profile at `path`: the fresh probes/decisions overlay the old
    ones, so re-probing one knob cannot silently drop every OTHER
    measured decision for the device kind (they would revert to the
    OFF defaults on every future solve, with no warning). Refuses to
    blend across device kinds or a jax skew — a stale base must be
    re-measured whole, not patched."""
    import dataclasses

    from dpsvm_tpu.autotune.profile import jax_compatible

    old = load_profile(path)
    if old.device_kind != fresh.device_kind:
        raise ProfileError(
            f"{path}: partial run measured {fresh.device_kind!r} but "
            f"the existing profile is for {old.device_kind!r}; refusing "
            "to merge — use --out or run the full pass")
    if not jax_compatible(old):
        raise ProfileError(
            f"{path}: existing profile was measured under jax "
            f"{old.jax}; a partial pass cannot be merged over a "
            "version-skewed base — rerun the full `make autotune`")
    # A SKIPPED fresh probe carries no new information: keep the old
    # MEASURED record (and its surviving decision) instead of letting
    # the skip record clobber it — otherwise a 1-device partial pass
    # would leave e.g. ring_exchange=True backed by a 'skipped'
    # probe, violating the provenance contract.
    overlay = {name: rec for name, rec in fresh.probes.items()
               if not (rec.get("skipped")
                       and name in old.probes
                       and not old.probes[name].get("skipped"))}
    return dataclasses.replace(
        fresh,
        probes={**old.probes, **overlay},
        decisions={**old.decisions, **fresh.decisions})


def _maybe_merge(prof: DeviceProfile, out: str,
                 partial: bool) -> DeviceProfile:
    """The save-path merge policy. EVERY pass merges over a
    compatible existing profile at `out` — a FULL pass on a 1-device
    host of a measured kind skips its mesh probes, and without the
    merge the save would silently drop the pod-measured authoritative
    decisions for those knobs (the exact hazard _merge_partial
    documents). An incompatible existing file (jax skew, device-kind
    mismatch) refuses a partial pass but is REPLACED by a full pass:
    complete re-measurement is the documented regeneration path."""
    if not os.path.exists(out):
        return prof
    try:
        merged = _merge_partial(prof, out)
    except ProfileError:
        if partial:
            raise
        print(f"[autotune] replacing incompatible existing {out} "
              "(full pass = regeneration)", file=sys.stderr)
        return prof
    retained = set(merged.probes) - set(prof.probes) | {
        n for n in prof.probes
        if prof.probes[n].get("skipped")
        and not merged.probes[n].get("skipped")}
    print(f"[autotune] merged over existing {out}"
          + (f" (previously measured records retained: "
             f"{','.join(sorted(retained))})" if retained else ""),
          file=sys.stderr)
    return merged


def _cmd_run(args) -> int:
    import json

    from dpsvm_tpu.config import ObsConfig

    ocfg = ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir)
    knobs = ([k for k in args.knobs.split(",") if k]
             if args.knobs else None)
    prof = run_probes(knobs=knobs, seed=args.seed, smoke=args.smoke,
                      obs_config=ocfg)
    if args.smoke:
        # Determinism contract for CI: a second pass with the same
        # seed must produce byte-identical stable fields + decisions
        # (timings jitter; verdicts cannot, because CPU probes are
        # non-authoritative and TPU smoke uses the same threshold
        # margin the full pass does).
        prof2 = run_probes(knobs=knobs, seed=args.seed, smoke=True,
                           obs_config=ocfg, verbose=False)
        a, b = stable_view(prof), stable_view(prof2)
        if any(p.get("authoritative") for p in prof.probes.values()):
            # On a REAL device the verdicts derive from timing ratios
            # and may legitimately straddle the threshold between two
            # passes — the determinism contract covers the record
            # structure, not authoritative measurements (CI pins the
            # CPU backend, where decisions are deterministic too).
            a.pop("decisions")
            b.pop("decisions")
            print("[autotune] smoke on a real device: decisions "
                  "excluded from the determinism check (timing-"
                  "derived)", file=sys.stderr)
        if a != b:
            print("[autotune] DETERMINISM FAIL:\n"
                  f"  first : {json.dumps(a, sort_keys=True)}\n"
                  f"  second: {json.dumps(b, sort_keys=True)}",
                  file=sys.stderr)
            return 1
        print("[autotune] smoke determinism: PASS (stable fields + "
              "decisions identical across two passes)",
              file=sys.stderr)
    if args.out:
        out = args.out
    elif args.smoke:
        import tempfile

        out = os.path.join(tempfile.mkdtemp(prefix="dpsvm_autotune_"),
                           f"{slug(prof.device_kind)}.json")
    else:
        out = profile_path(prof.device_kind)
    prof = _maybe_merge(prof, out, partial=knobs is not None)
    prof.save(out)
    # Schema check: what we just wrote must load back clean (the smoke
    # target's schema assertion; free everywhere else).
    load_profile(out)
    print(_decision_table(prof))
    print(f"[autotune] wrote {out} (device_kind={prof.device_kind!r}, "
          f"jax {prof.jax})", file=sys.stderr)
    return 0


def _cmd_show(args) -> int:
    import json

    if args.path:
        prof = load_profile(args.path)
        src = args.path
    else:
        prof = active_profile()
        if prof is None:
            from dpsvm_tpu.autotune.profile import current_device_kind

            kind = current_device_kind()
            print(f"no active profile for device kind {kind!r} "
                  f"(looked at {profile_path(kind)}); gates use the "
                  "hand-measured defaults (OFF)")
            return 1
        src = prof.path or "<in-process>"
    print(f"profile: {src}")
    print(f"device_kind={prof.device_kind!r} backend={prof.backend} "
          f"n_devices={prof.n_devices} jax={prof.jax} "
          f"utc={prof.utc} git={prof.git_sha[:12]}")
    print(_decision_table(prof))
    if args.json:
        print(json.dumps(prof.to_json(), sort_keys=True))
    return 0


def _cmd_diff(args) -> int:
    a, b = load_profile(args.a), load_profile(args.b)
    print(f"A: {args.a} ({a.device_kind!r}, jax {a.jax}, {a.utc})")
    print(f"B: {args.b} ({b.device_kind!r}, jax {b.jax}, {b.utc})")
    moved = 0
    for name in sorted(set(a.probes) | set(b.probes)):
        ra, rb = a.probes.get(name), b.probes.get(name)
        if ra is None or rb is None:
            moved += 1
            print(f"  {name:<14} only in {'B' if ra is None else 'A'}")
            continue
        va, vb = ra.get("verdict"), rb.get("verdict")
        qa, qb = ra.get("ratio"), rb.get("ratio")
        mark = " <-- verdict moved" if va != vb else ""
        if va != vb or qa != qb:
            moved += 1
            print(f"  {name:<14} ratio {qa} -> {qb}, "
                  f"verdict {va} -> {vb}{mark}")
    da, db = a.decisions, b.decisions
    for knob in sorted(set(da) | set(db)):
        if da.get(knob) != db.get(knob):
            print(f"  decision {knob}: {da.get(knob)} -> "
                  f"{db.get(knob)}")
    if not moved:
        print("  no probe drift (ratios + verdicts identical)")
    return 0


def run_cli(argv=None) -> int:
    """``cli autotune`` engine (argv forwarded verbatim from
    dpsvm_tpu/cli.py — one flag surface, the lint/obs discipline)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="dpsvm-tpu autotune",
        description="measured device profiling for the solver's auto "
                    "gates (dpsvm_tpu/autotune)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser(
        "run", help="run the probe registry on the current backend and "
                    "persist a DeviceProfile JSON (default: the "
                    "committed profiles dir; commit the diff)")
    rp.add_argument("--out", default=None,
                    help="profile path override (default: "
                         "dpsvm_tpu/autotune/profiles/<device>.json; "
                         "--smoke defaults to a temp file)")
    rp.add_argument("--knobs", default=None,
                    help="comma list of probe names to run (default: "
                         f"all of {','.join(PROBES)})")
    rp.add_argument("--seed", type=int, default=0)
    rp.add_argument("--smoke", action="store_true",
                    help="tiny-shape CI pass: probe twice, assert the "
                         "stable record fields + decisions are "
                         "deterministic, write to a temp profile")
    rp.add_argument("--obs", action="store_true",
                    help="mirror every probe record into an 'autotune' "
                         "runlog stream (DPSVM_OBS=1 equivalent)")
    rp.add_argument("--obs-dir", default=None)

    sp = sub.add_parser(
        "show", help="print the active profile for this device kind "
                     "(or an explicit file) with its decisions")
    sp.add_argument("path", nargs="?", default=None)
    sp.add_argument("--json", action="store_true")

    dp = sub.add_parser(
        "diff", help="compare two profile files: ratio/verdict drift "
                     "per probe, decision flips")
    dp.add_argument("a")
    dp.add_argument("b")

    args = ap.parse_args(argv)
    try:
        if args.cmd == "run":
            return _cmd_run(args)
        if args.cmd == "show":
            return _cmd_show(args)
        return _cmd_diff(args)
    except (ProfileError, OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
