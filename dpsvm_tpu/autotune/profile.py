"""DeviceProfile: the committed, versioned measurement artifact that
closes the observability loop on the solver's auto gates (ISSUE 14).

The tpulint-budgets discipline applied to MEASUREMENT: the autotune
pass (dpsvm_tpu/autotune/probes.py) runs once per device kind, its
verdicts persist as one JSON file per device kind under
``dpsvm_tpu/autotune/profiles/`` (committed; regenerated via
``make autotune``; jax-version-stamped), and the gate helpers in
solver/block.py resolve ``None``-valued config knobs from the profile
for the CURRENT device kind — with full provenance (profile file,
probe ratio, threshold) surfaced in ``SolveResult.stats['autotune']``
and the runlog manifest.

The contract: the autotuner changes *decisions*, never *programs*.
With no applicable profile, :func:`gate_decision` returns None and the
gates fall back to the hand-measured defaults in solver/block.py
(currently OFF for every profile-gated knob), so the committed tpulint
budgets regenerate byte-identical either way. A profile's verdicts can
only be True when the probe was AUTHORITATIVE (measured on a real
device, not an interpret-mode structure check) — the CPU-harness seed
profile therefore always resolves to the same OFF decisions as no
profile at all, while recording the measured ratios.

Resolution order for the active profile (first hit wins):

1. an in-process override installed via :func:`use_profile` (tests,
   A/B harnesses);
2. ``DPSVM_AUTOTUNE_PROFILE`` — an explicit profile file path
   (``0``/``off`` disables profiles entirely);
3. ``<profiles dir>/<slug(device_kind)>.json`` where the profiles dir
   is ``DPSVM_AUTOTUNE_DIR`` or the committed package directory.

A profile whose stamped jax major.minor differs from the running jax
is REFUSED (warned once, treated as absent): probe verdicts are
properties of the compiled programs, and a jax upgrade invalidates
them the same way it invalidates tpulint budgets.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
import warnings
from typing import Optional

#: schema of the profile JSON; bump on incompatible shape changes.
#: Readers refuse NEWER schemas explicitly (the runlog discipline).
PROFILE_SCHEMA = 1

#: pays-verdict threshold per gated knob: the B-variant must measure at
#: or under this fraction of the A-variant's chunk seconds before an
#: AUTHORITATIVE probe flips the knob on. Deliberately well inside the
#: ±10%-class session jitter both PROFILE.md and the bench regression
#: band carry — a wash must never flip a gate.
PAYS_THRESHOLD = 0.90

_MISSING = object()
_override = _MISSING  # use_profile() in-process override
_cache: dict = {}  # device_kind -> (source_key, profile_or_None)
_warned: set = set()


class ProfileError(ValueError):
    """A profile file exists but cannot be honored (bad schema, bad
    JSON shape). Distinct from 'absent', which is never an error."""


@dataclasses.dataclass(frozen=True)
class DeviceProfile:
    """One device kind's measured probe results + gate decisions."""

    device_kind: str
    backend: str
    n_devices: int
    jax: str
    utc: str
    git_sha: str
    seed: int
    #: probe name -> full probe record (shapes, seed, a/b seconds,
    #: ratio, threshold, authoritative, verdict, note).
    probes: dict
    #: config knob -> bool (the gate resolution input). Only knobs the
    #: pass measured appear; absent knobs fall back to the defaults.
    decisions: dict
    schema: int = PROFILE_SCHEMA
    path: Optional[str] = None  # where this profile was loaded from

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("path")
        return d

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self.to_json(), fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)  # atomic: readers never see a torn file
        return path


def slug(device_kind: str) -> str:
    """Filesystem name for a device kind: 'TPU v5e' -> 'tpu-v5e'."""
    s = "".join(c if c.isalnum() else "-" for c in device_kind.lower())
    while "--" in s:
        s = s.replace("--", "-")
    return s.strip("-") or "unknown"


def profiles_dir() -> str:
    """The profile directory: DPSVM_AUTOTUNE_DIR or the committed
    package dir (dpsvm_tpu/autotune/profiles)."""
    return (os.environ.get("DPSVM_AUTOTUNE_DIR")
            or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "profiles"))


def profile_path(device_kind: str) -> str:
    return os.path.join(profiles_dir(), f"{slug(device_kind)}.json")


def load_profile(path: str) -> DeviceProfile:
    """Parse + validate one profile file. Raises ProfileError on a
    malformed or newer-schema file (a committed artifact this build
    cannot honor must fail loudly, not half-apply)."""
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ProfileError(f"{path}: profile must be a JSON object")
    try:
        schema = int(doc.get("schema", 0))
    except (TypeError, ValueError):
        raise ProfileError(f"{path}: non-integer schema") from None
    if schema > PROFILE_SCHEMA:
        raise ProfileError(
            f"{path}: profile schema {schema} is newer than this "
            f"build's {PROFILE_SCHEMA}; regenerate with make autotune")
    missing = {"device_kind", "jax", "probes", "decisions"} - doc.keys()
    if missing:
        raise ProfileError(f"{path}: missing fields {sorted(missing)}")
    if not isinstance(doc["probes"], dict) \
            or not isinstance(doc["decisions"], dict):
        raise ProfileError(f"{path}: probes/decisions must be objects")
    try:
        prof = DeviceProfile(
            device_kind=str(doc["device_kind"]),
            backend=str(doc.get("backend", "")),
            n_devices=int(doc.get("n_devices", 0)),
            jax=str(doc["jax"]),
            utc=str(doc.get("utc", "")),
            git_sha=str(doc.get("git_sha", "")),
            seed=int(doc.get("seed", 0)),
            probes=dict(doc["probes"]),
            decisions={k: bool(v) for k, v in doc["decisions"].items()},
            schema=schema,
            path=path,
        )
    except (TypeError, ValueError) as e:
        # A malformed field (e.g. "n_devices": null) must surface as
        # the refusal contract — active_profile warns once and treats
        # the file as absent — never crash a solve.
        raise ProfileError(f"{path}: malformed field ({e})") from None
    # THE HONESTY RULE enforced at LOAD, not just at write: a True
    # decision must be backed by an authoritative True-verdict probe
    # for the same knob. A hand-edited or corrupted committed artifact
    # that violates it is refused whole (treated as absent upstream) —
    # never half-applied with provenance reading authoritative=false.
    for knob, dec in prof.decisions.items():
        if not dec:
            continue
        rec = next((p for p in prof.probes.values()
                    if p.get("knob") == knob), None)
        if (rec is None or rec.get("skipped")
                or not rec.get("authoritative")
                or not rec.get("verdict")):
            raise ProfileError(
                f"{path}: decision {knob}=true is not backed by an "
                "authoritative True-verdict probe (the honesty rule); "
                "regenerate with make autotune")
    return prof


def _jax_minor(version: str) -> str:
    return ".".join(str(version).split(".")[:2])


def jax_compatible(profile: DeviceProfile) -> bool:
    """Version-skew refusal: probe verdicts are properties of the
    compiled programs, so a profile stamped by a different jax
    major.minor is stale the way tpulint budgets would be."""
    import jax

    return _jax_minor(profile.jax) == _jax_minor(jax.__version__)


def _warn_once(key: str, msg: str) -> None:
    if key not in _warned:
        _warned.add(key)
        warnings.warn(msg, stacklevel=3)


def device_kind_of(device) -> str:
    """THE device-kind keying rule ('cpu', 'TPU v5e', ...), shared by
    every writer that must agree on the string — profile resolution
    here, the solvers' gate provenance, and bench's artifact stamp +
    DEVICE_MISMATCH refusal. One definition, or the cross-checks
    silently stop matching."""
    return getattr(device, "device_kind", "") or device.platform


def current_device_kind() -> str:
    """device_kind_of the running backend's first device. Callers on a
    solve path pass their own device's kind instead — this initializes
    a backend if none is live."""
    import jax

    return device_kind_of(jax.devices()[0])


@contextlib.contextmanager
def use_profile(profile):
    """In-process override for tests and A/B harnesses:
    ``use_profile(None)`` forces the no-profile behavior even when a
    committed profile exists for this device kind;
    ``use_profile(DeviceProfile(...))`` or ``use_profile(path)``
    installs one regardless of device kind matching."""
    global _override
    prev = _override
    _override = (load_profile(profile) if isinstance(profile, str)
                 else profile)
    _cache.clear()
    try:
        yield
    finally:
        _override = prev
        _cache.clear()


def active_profile(device_kind: Optional[str] = None):
    """The profile governing gate decisions for `device_kind` (default:
    the running backend's), or None. Cached per device kind and
    invalidated when the source file changes — the lookup sits on the
    solve path and must stay at dict-read cost."""
    if _override is not _MISSING:
        return _override
    env = os.environ.get("DPSVM_AUTOTUNE_PROFILE")
    if env is not None and env.strip().lower() in ("", "0", "off"):
        return None
    if device_kind is None:
        device_kind = current_device_kind()
    path = env or profile_path(device_kind)
    # One stat per lookup (it is what detects a freshly written or
    # regenerated profile); the cache key includes the mtime (None =
    # absent), so both the loaded-profile and the no-profile cases hit
    # without re-parsing — gate resolution sits on the solve path.
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        mtime = None
    key = (path, mtime)
    hit = _cache.get(device_kind)
    if hit is not None and hit[0] == key:
        return hit[1]
    if mtime is None:
        _cache[device_kind] = (key, None)
        return None
    prof: Optional[DeviceProfile]
    try:
        prof = load_profile(path)
    except (ProfileError, OSError, json.JSONDecodeError) as e:
        _warn_once(f"bad:{path}", f"autotune profile {path} refused "
                                  f"({e}); gates use defaults")
        prof = None
    if prof is not None and prof.device_kind != device_kind:
        _warn_once(f"kind:{path}",
                   f"autotune profile {path} was measured on "
                   f"{prof.device_kind!r}, not {device_kind!r}; "
                   "refusing it — gates use defaults")
        prof = None
    if prof is not None and not jax_compatible(prof):
        import jax

        _warn_once(f"jax:{path}",
                   f"autotune profile {path} was measured under jax "
                   f"{prof.jax}, running {jax.__version__}; refusing "
                   "it — rerun make autotune on this jax")
        prof = None
    _cache[device_kind] = (key, prof)
    return prof


def gate_decision(knob: str,
                  device_kind: Optional[str] = None) -> Optional[dict]:
    """The active profile's resolution for one auto-gated config knob:
    ``{"decision", "profile", "device_kind", "probe", "ratio",
    "threshold", "authoritative"}`` — the provenance record the solvers
    embed in SolveResult.stats — or None when no applicable profile
    (or the profile never measured this knob)."""
    prof = active_profile(device_kind)
    if prof is None or knob not in prof.decisions:
        return None
    rec = next((p for p in prof.probes.values()
                if p.get("knob") == knob), {})
    return {
        "decision": bool(prof.decisions[knob]),
        "profile": prof.path or "<in-process>",
        "device_kind": prof.device_kind,
        "probe": rec.get("probe"),
        "ratio": rec.get("ratio"),
        "threshold": rec.get("threshold"),
        "authoritative": rec.get("authoritative"),
    }


def stamp() -> dict:
    """The identity fields every freshly measured profile carries."""
    import jax

    from dpsvm_tpu.obs.runlog import git_sha

    devs = jax.devices()
    return {
        "device_kind": device_kind_of(devs[0]),
        "backend": devs[0].platform,
        "n_devices": len(devs),
        "jax": jax.__version__,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": git_sha(),
    }
