"""scikit-learn-compatible estimator facade.

The reference is a CLI tool with no library API at all (svmTrainMain.cpp
parses flags into a global struct and writes a text model); this module is
the opposite end of the adoption surface: drop-in ``SVC`` / ``SVR`` /
``OneClassSVM`` estimators with sklearn ``fit``/``predict``/``score``
semantics, backed by the TPU solver. Subclassing
``sklearn.base.BaseEstimator`` makes ``get_params``/``set_params``/
``clone`` work, so ``GridSearchCV``, ``cross_val_score``, ``Pipeline``
etc. compose with TPU-trained SVMs unchanged.

sklearn itself is only imported lazily (it is a test/facade dependency,
not a solver dependency).
"""

from __future__ import annotations

import numpy as np

try:  # pragma: no cover - exercised implicitly by import
    from sklearn.base import BaseEstimator, ClassifierMixin, OutlierMixin, RegressorMixin
except ImportError:  # sklearn genuinely absent: degrade to plain objects
    class BaseEstimator:  # type: ignore[no-redef]
        def get_params(self, deep=True):
            import inspect
            keys = inspect.signature(type(self).__init__).parameters
            return {k: getattr(self, k) for k in keys if k != "self"}

        def set_params(self, **params):
            for k, v in params.items():
                setattr(self, k, v)
            return self

    class ClassifierMixin:  # type: ignore[no-redef]
        pass

    class RegressorMixin:  # type: ignore[no-redef]
        pass

    class OutlierMixin:  # type: ignore[no-redef]
        pass

from dpsvm_tpu.config import SVMConfig

try:
    from sklearn.utils.metaestimators import available_if as _available_if
except ImportError:
    def _available_if(check):
        def deco(fn):
            return fn
        return deco


def _has_probability(est) -> bool:
    """predict_proba exists only when probability=True — sklearn.SVC's
    own contract (hasattr-based checks must see it absent, or every
    method-invariance/pickle check calls it and trips the
    AttributeError)."""
    if not est.probability:
        raise AttributeError(
            "predict_proba requires probability=True at fit time")
    return True


def _validate_fit(est, X, y=None, *, y_numeric=False, requires_y=True):
    """sklearn's fit-time input contract (estimator_checks battery):
    2-D finite real X (sparse rejected with the standard TypeError),
    ``n_features_in_``/``feature_names_in_`` recorded, y 1-D and
    length-matched (column-vector y warns + ravels), informative error
    on y=None for supervised estimators. Degrades to plain asarray when
    sklearn is absent."""
    try:
        from sklearn.utils.validation import validate_data
    except ImportError:
        X = np.asarray(X, np.float32)
        return (X, None) if y is None else (X, np.asarray(y))
    if y is None and not requires_y:
        return validate_data(est, X, dtype=np.float32), None
    # y=None on a supervised estimator raises the standard
    # "requires y to be passed" ValueError inside validate_data.
    return validate_data(est, X, y, dtype=np.float32, y_numeric=y_numeric)


def _validate_predict(est, X):
    """Predict-time counterpart: NotFittedError before fit, the same X
    contract, and a feature-count match against fit."""
    try:
        from sklearn.utils.validation import check_is_fitted, validate_data
    except ImportError:
        return np.asarray(X, np.float32)
    check_is_fitted(est)
    return validate_data(est, X, dtype=np.float32, reset=False)


def _check_classification_y(y):
    try:
        from sklearn.utils.multiclass import check_classification_targets
    except ImportError:
        return
    check_classification_targets(y)


def _resolve_gamma(gamma, x: np.ndarray) -> float:
    if gamma == "scale":
        var = float(x.var())
        return 1.0 / (x.shape[1] * var) if var > 0 else 1.0 / x.shape[1]
    if gamma == "auto":
        return 1.0 / x.shape[1]
    return float(gamma)


def _base_config(est, gamma: float) -> SVMConfig:
    return SVMConfig(
        c=est.C if hasattr(est, "C") else 1.0,
        gamma=gamma,
        kernel=est.kernel,
        degree=est.degree,
        coef0=est.coef0,
        epsilon=est.tol,
        max_iter=est.max_iter if est.max_iter > 0 else 150_000,
        selection=getattr(est, "selection", "mvp"),
        engine=getattr(est, "engine", "xla"),
        working_set_size=getattr(est, "working_set_size", 128),
        pair_batch=getattr(est, "pair_batch", 1),
        # None = auto (on when the per-pair engine's (n, n) Gram fits
        # device memory); estimators expose it for the extreme-C tails.
        gram_resident=getattr(est, "gram_resident", None),
        # Multi-problem batching (solver/fleet.py): multiclass
        # reductions and svc_c_sweep train up to fleet_size submodels
        # per compiled dispatch sequence.
        fleet_size=getattr(est, "fleet_size", 16),
        cache_lines=est.cache_lines,
        dtype=est.dtype,
    )


def _install_binary_fit(est, res, y_pm) -> None:
    """Shared binary fit-assembly: install (fit_result_, n_support_,
    n_iter_) from a SolveResult. One definition so SVC.fit (dense and
    precomputed branches) and svc_c_sweep can never drift on what a
    fitted binary estimator's counters mean."""
    est.fit_result_ = res
    sv_mask = np.asarray(res.alpha) > 0
    est.n_support_ = np.array(
        [(sv_mask & (y_pm < 0)).sum(), (sv_mask & (y_pm > 0)).sum()])
    est.n_iter_ = res.iterations


def _weighted_accuracy(pred, y, sample_weight=None) -> float:
    y = np.asarray(y)
    if sample_weight is not None:
        w = np.asarray(sample_weight, np.float64)
        return float(((pred == y) * w).sum() / w.sum())
    return float((pred == y).mean())


def _weighted_r2(pred, y, sample_weight=None) -> float:
    """R^2 as sklearn defines it (shared by the regressor facades)."""
    y = np.asarray(y, np.float64)
    pred = np.asarray(pred, np.float64)
    w = (np.ones_like(y) if sample_weight is None
         else np.asarray(sample_weight, np.float64))
    ss_res = float((w * (y - pred) ** 2).sum())
    ss_tot = float((w * (y - np.average(y, weights=w)) ** 2).sum())
    return 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0


class SVC(ClassifierMixin, BaseEstimator):
    """C-SVC with sklearn semantics on the TPU solver.

    Accepts arbitrary (binary or multiclass) integer/str labels; multiclass
    is reduced via one-vs-rest or one-vs-one (``strategy``). ``class_weight``
    ({label: w} or "balanced") is honored for binary problems, mirroring
    LibSVM ``-w``.

    Numerics note: like sklearn, prediction evaluates in float32. For
    extreme-C models, fp32 accumulation can swamp near-boundary decision
    signs (predict.decision_risk estimates when); use the module-level
    ``predict.decision_function(model, X, precision='float64')`` on the
    fitted binary model for exact evaluation.
    """

    def __init__(self, C=1.0, kernel="rbf", degree=3, gamma="scale",
                 coef0=0.0, tol=1e-3, max_iter=-1, class_weight=None,
                 strategy="ovr", backend="auto", selection="mvp",
                 engine="xla", working_set_size=128, pair_batch=1,
                 gram_resident=None, fleet_size=16, cache_lines=0,
                 dtype="float32", probability=False, probability_cv=3,
                 random_state=0):
        self.gram_resident = gram_resident
        self.fleet_size = fleet_size
        self.C = C
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.tol = tol
        self.max_iter = max_iter
        self.class_weight = class_weight
        self.strategy = strategy
        self.backend = backend
        self.selection = selection
        self.engine = engine
        self.working_set_size = working_set_size
        self.pair_batch = pair_batch
        self.cache_lines = cache_lines
        self.dtype = dtype
        self.probability = probability
        self.probability_cv = probability_cv
        self.random_state = random_state

    def _weights(self, y: np.ndarray, classes: np.ndarray) -> tuple[float, float]:
        """(weight_pos, weight_neg) for a binary problem where classes[1]
        maps to +1 and classes[0] to -1."""
        if self.class_weight is None:
            return 1.0, 1.0
        if self.class_weight == "balanced":
            n = y.shape[0]
            counts = {c: int((y == c).sum()) for c in classes}
            return (n / (2.0 * counts[classes[1]]),
                    n / (2.0 * counts[classes[0]]))
        return (float(self.class_weight.get(classes[1], 1.0)),
                float(self.class_weight.get(classes[0], 1.0)))

    def fit(self, X, y):
        from dpsvm_tpu.models.multiclass import train_multiclass
        from dpsvm_tpu.train import train

        X, y = _validate_fit(self, X, y)
        _check_classification_y(y)
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        if self.classes_.shape[0] < 2:
            raise ValueError(
                f"SVC needs at least 2 classes; the data has "
                f"{self.classes_.shape[0]} class")
        if (self.probability and self.classes_.shape[0] > 2
                and self.strategy != "ovr"):
            # Constructor-parameter check — fail before k*(k-1)/2 solver
            # runs are spent, not after.
            raise ValueError(
                "probability=True requires strategy='ovr' for multiclass "
                "(per-class Platt + normalization)")
        if self.kernel == "precomputed":
            # gamma is meaningless here (and gamma='scale' would run an
            # O(n^2) variance pass over the Gram matrix to produce it);
            # pin a dummy value instead of resolving it.
            cfg = _base_config(self, 1.0)
            # LibSVM -t 4: X is the (n, n) Gram matrix. The model is
            # (support indices, dual coef, b) — there are no feature
            # rows — and prediction takes K(test, train) columns, exactly
            # sklearn's contract for kernel='precomputed'.
            from dpsvm_tpu.solver.smo import solve

            if self.backend not in ("auto", "single"):
                raise ValueError(
                    "kernel='precomputed' is single-chip only this round; "
                    "use backend='auto' or 'single'")
            if self.classes_.shape[0] != 2:
                raise ValueError(
                    "kernel='precomputed' supports binary problems only "
                    "(the OvR/OvO reductions would need per-split Gram "
                    "sub-matrices)")
            if self.probability:
                raise ValueError(
                    "probability=True is not supported with "
                    "kernel='precomputed' (the CV folds would need "
                    "per-fold Gram sub-matrices)")
            wp, wn = self._weights(y, self.classes_)
            cfg = cfg.replace(weight_pos=wp, weight_neg=wn)
            y_pm = np.where(y == self.classes_[1], 1, -1).astype(np.int32)
            res = solve(np.asarray(X, np.float32), y_pm, cfg)
            self._binary_model = None
            self._multiclass_model = None
            self._pre_n = int(X.shape[0])
            alpha = np.asarray(res.alpha)
            self.support_ = np.nonzero(alpha > 0)[0].astype(np.int32)
            self._pre_coef = (alpha * y_pm)[self.support_].astype(np.float64)
            self._pre_b = float(res.b)
            _install_binary_fit(self, res, y_pm)
            return self
        self._pre_coef = None
        cfg = _base_config(self, _resolve_gamma(self.gamma, X))

        if self.classes_.shape[0] == 2:
            wp, wn = self._weights(y, self.classes_)
            cfg = cfg.replace(weight_pos=wp, weight_neg=wn)
            y_pm = np.where(y == self.classes_[1], 1, -1).astype(np.int32)
            model, res = train(X, y_pm, cfg, backend=self.backend)
            self._binary_model = model
            self._multiclass_model = None
            _install_binary_fit(self, res, y_pm)
            if self.probability:
                self._platt = self._fit_platt_cv(X, y_pm, cfg)
        else:
            if self.class_weight is not None:
                raise ValueError(
                    "class_weight is only supported for binary problems "
                    "(per-class weights do not decompose over OvR/OvO splits)")
            mc, results = train_multiclass(
                X, y, cfg, strategy=self.strategy, backend=self.backend)
            self._binary_model = None
            self._multiclass_model = mc
            self.fit_result_ = results
            self.n_iter_ = int(sum(r.iterations for r in results))
            if self.probability:
                self._platt = [
                    self._fit_platt_cv(
                        X, np.where(y == cl, 1, -1).astype(np.int32), cfg)
                    for cl in self.classes_]
        return self

    def _fit_platt_cv(self, X, y_pm, cfg):
        from dpsvm_tpu.models.platt import fit_platt_cv

        # random_state passes through unchanged: None keeps sklearn's
        # fresh-entropy-per-fit semantics (default_rng(None)), and 0 is a
        # distinct deterministic seed rather than an alias of None.
        return fit_platt_cv(X, y_pm, cfg, backend=self.backend,
                            k=self.probability_cv,
                            seed=self.random_state)

    @_available_if(_has_probability)
    def predict_proba(self, X):
        """Class-probability matrix (n, k), classes in ``classes_`` order.
        Only available when probability=True (sklearn.SVC contract)."""
        from dpsvm_tpu.models.platt import platt_probability
        X = _validate_predict(self, X)
        if self._binary_model is not None:
            p_pos = platt_probability(self.decision_function(X), *self._platt)
            return np.stack([1.0 - p_pos, p_pos], axis=1)
        from dpsvm_tpu.models.multiclass import decision_matrix
        from dpsvm_tpu.models.platt import platt_probability_matrix
        scores = decision_matrix(self._multiclass_model, X)
        probs = platt_probability_matrix(scores, self._platt)
        probs = np.clip(probs, 1e-12, 1.0)
        return probs / probs.sum(axis=1, keepdims=True)

    def decision_function(self, X):
        """(n,) for binary, (n, k) per-class scores otherwise (OvO models
        are folded to per-class vote scores, sklearn's default ovr shape)."""
        from dpsvm_tpu.predict import decision_function
        X = _validate_predict(self, X)
        if getattr(self, "_pre_coef", None) is not None:
            # X is K(test, train): kernel values against every TRAINING
            # row, columns indexed by the stored support set.
            if X.ndim != 2 or X.shape[1] != self._pre_n:
                raise ValueError(
                    f"kernel='precomputed' prediction needs K(test, train) "
                    f"with {self._pre_n} columns (one per training row); "
                    f"got shape {X.shape}")
            return X[:, self.support_] @ self._pre_coef - self._pre_b
        if self._binary_model is not None:
            return decision_function(self._binary_model, X)
        from dpsvm_tpu.models.multiclass import vote_matrix
        return vote_matrix(self._multiclass_model, X)

    def predict(self, X):
        X = _validate_predict(self, X)
        if (getattr(self, "_pre_coef", None) is not None
                or self._binary_model is not None):
            d = self.decision_function(X)
            return np.where(d >= 0, self.classes_[1], self.classes_[0])
        from dpsvm_tpu.models.multiclass import predict_multiclass
        return predict_multiclass(self._multiclass_model, X)

    def score(self, X, y, sample_weight=None):
        return _weighted_accuracy(self.predict(X), y, sample_weight)


def svc_c_sweep(X, y, Cs, warm=False, **svc_params) -> list:
    """Fit one binary ``SVC`` per value in `Cs` with ALL the solves
    batched through the fleet executor (solver/fleet.py): the box bound
    is a traced per-problem value, so every C shares one compiled
    while_loop, the shared X (or resident Gram) uploads once, and the
    whole sweep costs ceil(len(Cs) / fleet_size) dispatch sequences
    instead of len(Cs) — the hyperparameter-search shape GridSearchCV
    drives as sequential fits.

    ``warm=True`` switches to the regularization-path walk: the C grid
    is visited in ascending order, each solve seeded from the previous
    C's alphas (solver/warmstart.py repairs the seed into the new box
    and rebuilds the gradient in one streamed pass) instead of
    cold-starting the fleet.  Sequential by construction — each fit
    depends on the last — so it trades the fleet's batched dispatches
    for a large cut in total optimization pairs; `tools/bench_learn.py`
    measures the trade.  Results are still returned in `Cs` order.

    Returns fitted SVC estimators in `Cs` order (each with its own
    ``fit_result_``; per-problem convergence masking means a
    fast-converging C never waits on a hard one's iterations beyond
    sharing its dispatch). `svc_params` are forwarded to every SVC;
    binary labels only, and probability / class_weight / precomputed
    kernels are not supported under the sweep.

    SINGLE-CHIP by construction (the fleet is one device's executor,
    and the warm walk runs the single-chip solver): backend='auto'
    resolves to one device here — explicit mesh / reference / native
    backends are refused, and a problem sized to fit only as mesh
    shards must be swept per-C with ``SVC(backend='mesh')``.
    """
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams
    from dpsvm_tpu.solver.fleet import FleetProblem, fleet_chunks, solve_fleet

    Cs = [float(c) for c in Cs]
    if not Cs:
        raise ValueError("Cs must be non-empty")
    template = SVC(C=Cs[0], **svc_params)
    if template.probability:
        raise ValueError("svc_c_sweep does not support probability=True "
                         "(per-C Platt CV refits are sequential work)")
    if template.class_weight is not None:
        raise ValueError("svc_c_sweep does not support class_weight")
    if template.backend != "single":
        # The mesh would shard each solve across devices; the fleet is
        # single-chip. De-sharding silently could OOM device 0 on a
        # problem sized for shards, and backend='auto' on a multi-device
        # host is the same hazard (SVC.fit would pick the mesh there) —
        # so, like _fleet_eligible's auto rule, 'auto' is only accepted
        # when one device is visible; backend='single' is the explicit
        # opt-in.
        multi = False
        if template.backend == "auto":
            import jax
            multi = len(jax.devices()) > 1
        if template.backend != "auto" or multi:
            raise ValueError(
                f"svc_c_sweep is single-chip (the fleet executor); "
                f"backend={template.backend!r} on this host would "
                "de-shard the solves — pass backend='single' to accept "
                "the single-chip sweep, or fit per-C with SVC")
    from dpsvm_tpu.solver.fleet import fleet_routing_reasons

    reasons = [] if warm else fleet_routing_reasons(_base_config(template, 1.0))
    if reasons:
        # The gate train_multiclass(use_fleet=True) enforces, from the
        # same shared predicate: silently training a requested
        # engine='block' sweep on the per-pair MVP fleet executor would
        # make the per-C results incomparable to SVC(engine='block').
        raise ValueError(
            "svc_c_sweep cannot route this config through the fleet "
            "executor: " + "; ".join(reasons)
            + " — fit such configs per-C with SVC instead")
    # The same fit-time input contract SVC.fit applies — the sweep
    # advertises per-C SVC-fit equivalence, so a NaN/mis-shaped X must
    # raise the same clear validation error here, not flow into the
    # solver as silently-garbage alphas.
    X, y = _validate_fit(template, X, y)
    _check_classification_y(y)
    X = np.asarray(X, np.float32)
    y = np.asarray(y)
    classes = np.unique(y)
    if classes.shape[0] != 2:
        raise ValueError(
            f"svc_c_sweep is binary-only ({classes.shape[0]} classes "
            "found); sweep a multiclass SVC per-C instead")
    y_pm = np.where(y == classes[1], 1, -1).astype(np.int32)
    cfg = _base_config(template, _resolve_gamma(template.gamma, X))
    kp = KernelParams(cfg.kernel, cfg.resolve_gamma(X.shape[1]),
                      cfg.degree, cfg.coef0)
    if warm:
        # Regularization-path walk: ascending C, each solve seeded from
        # the previous C's alphas.  Ascending means the previous optimum
        # always sits inside the next (larger) box, so the repair stage
        # only has to absorb rounding — no clipping mass is lost.
        from dpsvm_tpu.solver.smo import solve
        from dpsvm_tpu.solver.warmstart import WarmStart

        order = np.argsort(Cs, kind="stable")
        results = [None] * len(Cs)
        prev_alpha = None
        for pos in order:
            cfg_c = cfg.replace(c=Cs[pos])
            ws = (WarmStart(alpha=prev_alpha)
                  if prev_alpha is not None and prev_alpha.any() else None)
            res = solve(X, y_pm, cfg_c, warm_start=ws)
            prev_alpha = np.asarray(res.alpha, np.float64)
            results[pos] = res
    else:
        problems = [FleetProblem(y=y_pm, c=c, tag=("C", c)) for c in Cs]
        results = []
        for chunk in fleet_chunks(problems, cfg.fleet_size):
            results.extend(solve_fleet(X, chunk, cfg))

    fitted = []
    for c, res in zip(Cs, results):
        est = SVC(C=c, **svc_params)
        est.classes_ = classes
        # Fit-metadata parity with SVC.fit: validate_data recorded these
        # on the template; every returned estimator must carry them so
        # predict-time validation behaves identically.
        est.n_features_in_ = getattr(template, "n_features_in_",
                                     X.shape[1])
        if hasattr(template, "feature_names_in_"):
            est.feature_names_in_ = template.feature_names_in_
        est._binary_model = SVMModel.from_dense(X, y_pm, res.alpha,
                                                res.b, kp)
        est._multiclass_model = None
        est._pre_coef = None
        _install_binary_fit(est, res, y_pm)
        fitted.append(est)
    return fitted


class SVR(RegressorMixin, BaseEstimator):
    """epsilon-SVR with sklearn semantics on the TPU solver."""

    def __init__(self, C=1.0, kernel="rbf", degree=3, gamma="scale",
                 coef0=0.0, tol=1e-3, epsilon=0.1, max_iter=-1,
                 backend="auto", selection="mvp", engine="xla",
                 working_set_size=128, pair_batch=1, gram_resident=None,
                 cache_lines=0, dtype="float32"):
        self.gram_resident = gram_resident
        self.C = C
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.tol = tol
        self.epsilon = epsilon
        self.max_iter = max_iter
        self.backend = backend
        self.selection = selection
        self.engine = engine
        self.working_set_size = working_set_size
        self.pair_batch = pair_batch
        self.cache_lines = cache_lines
        self.dtype = dtype

    def fit(self, X, y):
        from dpsvm_tpu.models.svr import train_svr
        X, y = _validate_fit(self, X, y, y_numeric=True)
        y = np.asarray(y, np.float32)
        cfg = _base_config(self, _resolve_gamma(self.gamma, X))
        backend = self.backend
        if backend == "auto":
            backend = "single"
        self._model, res = train_svr(X, y, cfg, svr_epsilon=self.epsilon,
                                     backend=backend)
        self.fit_result_ = res
        self.n_iter_ = res.iterations
        return self

    def predict(self, X):
        X = _validate_predict(self, X)  # NotFittedError before _model
        return self._model.predict(X)

    def score(self, X, y, sample_weight=None):
        return _weighted_r2(self.predict(X), y, sample_weight)


class OneClassSVM(OutlierMixin, BaseEstimator):
    """nu-one-class SVM with sklearn semantics on the TPU solver."""

    def __init__(self, nu=0.5, kernel="rbf", degree=3, gamma="scale",
                 coef0=0.0, tol=1e-3, max_iter=-1, backend="auto",
                 engine="xla", working_set_size=128,
                 cache_lines=0, dtype="float32"):
        self.nu = nu
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.tol = tol
        self.max_iter = max_iter
        self.backend = backend
        self.engine = engine
        self.working_set_size = working_set_size
        self.cache_lines = cache_lines
        self.dtype = dtype

    def fit(self, X, y=None):
        from dpsvm_tpu.models.oneclass import train_oneclass
        X, _ = _validate_fit(self, X, requires_y=False)
        cfg = _base_config(self, _resolve_gamma(self.gamma, X))
        backend = self.backend
        if backend == "auto":
            backend = "single"
        self._model, res = train_oneclass(X, nu=self.nu, config=cfg,
                                          backend=backend)
        self.fit_result_ = res
        self.n_iter_ = res.iterations
        # sklearn's convention: decision_function = score_samples -
        # offset_ with score_samples the UNSHIFTED kernel sum, so
        # offset_ IS rho (sklearn stores intercept_ = -rho and
        # offset_ = -intercept_).
        self.offset_ = float(self._model.rho)
        return self

    def decision_function(self, X):
        X = _validate_predict(self, X)  # NotFittedError before _model
        # float64 out: sklearn's outlier API contract asserts the
        # double dtype (check_outliers_train); the evaluation itself is
        # the shared f32 MXU path.
        return self._model.decision_function(X).astype(np.float64)

    def score_samples(self, X):
        """The unshifted kernel sum sum_i coef_i K(sv_i, X): sklearn's
        contract decision_function = score_samples - offset_ with
        offset_ = rho."""
        return self.decision_function(X) + self.offset_

    def predict(self, X):
        return np.where(self.decision_function(X) >= 0, 1, -1)


class NuSVC(ClassifierMixin, BaseEstimator):
    """nu-SVC with sklearn semantics on the TPU solver (the nu duals
    run the per-class-selection engine; see models/nusvm.py). Multiclass
    problems reduce transparently via one-vs-one with the nu trainer
    under each pair — nu bounds the margin-error/SV fractions PER PAIR,
    matching sklearn.svm.NuSVC's own OvO semantics."""

    def __init__(self, nu=0.5, kernel="rbf", degree=3, gamma="scale",
                 coef0=0.0, tol=1e-3, max_iter=-1, backend="auto",
                 cache_lines=0, dtype="float32"):
        self.nu = nu
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.tol = tol
        self.max_iter = max_iter
        self.backend = backend
        self.cache_lines = cache_lines
        self.dtype = dtype

    def fit(self, X, y):
        from dpsvm_tpu.models.nusvm import train_nusvc

        X, y = _validate_fit(self, X, y)
        _check_classification_y(y)
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        if self.classes_.shape[0] < 2:
            raise ValueError(
                f"NuSVC needs at least 2 classes; the data has "
                f"{self.classes_.shape[0]} class")
        cfg = _base_config(self, _resolve_gamma(self.gamma, X))
        if self.classes_.shape[0] == 2:
            y_pm = np.where(y == self.classes_[1], 1, -1).astype(np.int32)
            self._model, res = train_nusvc(X, y_pm, nu=self.nu,
                                           config=cfg,
                                           backend=self.backend)
            self._multiclass_model = None
            self.fit_result_ = res
            self.n_iter_ = res.iterations
            return self
        # Multiclass: the one-vs-one reduction with the nu-SVC trainer
        # under it (sklearn.NuSVC is OvO multiclass too; nu bounds the
        # margin-error/SV fractions PER PAIR, its natural scope —
        # pad_to is ignored because the nu start point depends on exact
        # class counts).
        from dpsvm_tpu.models.multiclass import train_multiclass

        def nu_trainer(xx, yy, c, backend="auto", num_devices=None,
                       pad_to=None):
            return train_nusvc(xx, yy, nu=self.nu, config=c,
                               backend=backend, num_devices=num_devices)

        mc, results = train_multiclass(X, y, cfg, strategy="ovo",
                                       backend=self.backend,
                                       trainer=nu_trainer)
        self._model = None
        self._multiclass_model = mc
        self.fit_result_ = results
        self.n_iter_ = int(sum(r.iterations for r in results))
        return self

    def decision_function(self, X):
        from dpsvm_tpu.predict import decision_function
        X = _validate_predict(self, X)  # NotFittedError before _model
        if self._model is None:
            from dpsvm_tpu.models.multiclass import vote_matrix
            return vote_matrix(self._multiclass_model, X)
        return decision_function(self._model, X)

    def predict(self, X):
        scores = self.decision_function(X)
        if scores.ndim == 2:  # multiclass: per-class vote scores
            return self.classes_[np.argmax(scores, axis=1)]
        return self.classes_[(scores > 0).astype(int)]

    def score(self, X, y, sample_weight=None):
        return _weighted_accuracy(self.predict(X), y, sample_weight)


class NuSVR(RegressorMixin, BaseEstimator):
    """nu-SVR with sklearn semantics on the TPU solver: nu replaces the
    epsilon tube width (see models/nusvm.py)."""

    def __init__(self, nu=0.5, C=1.0, kernel="rbf", degree=3, gamma="scale",
                 coef0=0.0, tol=1e-3, max_iter=-1, backend="auto",
                 cache_lines=0, dtype="float32"):
        self.nu = nu
        self.C = C
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.tol = tol
        self.max_iter = max_iter
        self.backend = backend
        self.cache_lines = cache_lines
        self.dtype = dtype

    def fit(self, X, y):
        from dpsvm_tpu.models.nusvm import train_nusvr

        X, y = _validate_fit(self, X, y, y_numeric=True)
        y = np.asarray(y, np.float32)
        cfg = _base_config(self, _resolve_gamma(self.gamma, X))
        self._model, res = train_nusvr(X, y, nu=self.nu, c=self.C,
                                       config=cfg, backend=self.backend)
        self.fit_result_ = res
        self.n_iter_ = res.iterations
        return self

    def predict(self, X):
        X = _validate_predict(self, X)  # NotFittedError before _model
        return self._model.predict(X)

    def score(self, X, y, sample_weight=None):
        return _weighted_r2(self.predict(X), y, sample_weight)
