"""Compile accounting: make XLA executor builds visible (ISSUE 8).

Recompiles are one of the two runtime costs that actually gate TPU
scale (the other is HBM footprint, pinned statically by tpulint's
``memory.*`` budget facts) — and until this module they were invisible:
a shape-bucketing bug or a weak-type retrace shows up only as
mysteriously slow first chunks. jax already reports every backend
compile through its monitoring hooks
(``/jax/core/compile/backend_compile_duration``); this module turns
those events into

* a PROCESS-LEVEL counter (:func:`compiles_total`) — the number the
  serving /metrics endpoint exports as ``serve_compiles``;
* per-run ``compile`` runlog records ``{entrypoint, shape, seconds}``
  via registered sinks (obs/__init__.py RunObs registers one while a
  run is live; serve.PredictServer keeps one for its lifetime), with
  the entrypoint taken from the innermost active :func:`label` — the
  span name of the dispatch that triggered the build (``solver/chunk``,
  ``serve/bucket1024``, ...), or ``"<unlabeled>"`` for compiles outside
  any instrumented dispatch.

ZERO-DEVICE-EFFECT: everything here is a host-side observer of events
jax emits anyway. The listener is installed LAZILY — the first live
RunObs or PredictServer installs it — so a process that never enables
observability and never serves pays nothing; once installed it stays
(jax's listener registry has no public unregister), counting into the
process total with an O(#sinks) fan-out that only runs at compile
time, which is seconds-scale work already. No compiled program, chunk
cadence or dispatch count changes (the obs-enabled tpulint budget
check stays the pin).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

#: monitoring event names that mean "one backend executable was built".
#: jaxpr tracing / MLIR lowering durations are deliberately excluded —
#: the contract counts EXECUTABLES, not trace passes.
_COMPILE_EVENTS = ("/jax/core/compile/backend_compile_duration",)

_installed = False
_compiles = 0
_seconds = 0.0
# (entrypoint, shape) labels, innermost last. Compiles happen on the
# dispatching thread in this codebase (the metrics HTTP thread never
# compiles), so a plain list under the GIL is enough.
_labels: List[Tuple[str, Optional[str]]] = []
_sinks: List[Callable] = []


def _listener(event: str, secs: float, **kw) -> None:
    global _compiles, _seconds
    if event not in _COMPILE_EVENTS:
        return
    _compiles += 1
    _seconds += secs
    if not _sinks:
        return
    name, shape = _labels[-1] if _labels else ("<unlabeled>", None)
    for sink in list(_sinks):
        try:
            sink(name, shape, float(secs))
        except Exception:
            # An observer must never break the compile that fed it.
            pass


def install() -> bool:
    """Idempotently register the jax monitoring listener. Returns True
    when the hook is live (False on jax builds without the monitoring
    module — accounting then degrades to zeros, never an error)."""
    global _installed
    if _installed:
        return True
    try:
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(_listener)
    except Exception:
        return False
    _installed = True
    return True


def compiles_total() -> int:
    """Backend executables built since :func:`install` (process-wide)."""
    return _compiles


def compile_seconds_total() -> float:
    """Total backend-compile seconds since :func:`install`."""
    return round(_seconds, 6)


def add_sink(sink: Callable) -> None:
    """Register ``sink(entrypoint, shape, seconds)`` for future compile
    events (installs the listener if needed)."""
    install()
    if sink not in _sinks:
        _sinks.append(sink)


def remove_sink(sink: Callable) -> None:
    if sink in _sinks:
        _sinks.remove(sink)


class label:
    """Context manager naming the entrypoint (and optionally its shape
    signature) for any compile events fired inside it. Nested labels
    attribute to the innermost — the same convention as trace spans."""

    __slots__ = ("_entry",)

    def __init__(self, entrypoint: str, shape: Optional[str] = None):
        self._entry = (entrypoint, shape)

    def __enter__(self):
        _labels.append(self._entry)
        return self

    def __exit__(self, *exc):
        # Remove THIS entry even under exotic interleaving (a sibling
        # exiting out of order must not pop our label).
        for i in range(len(_labels) - 1, -1, -1):
            if _labels[i] is self._entry:
                del _labels[i]
                break
        return False
