"""OpenMetrics/Prometheus text export for the serving engine (ISSUE 8).

Until this module PredictServer's histograms were reachable only by
calling ``snapshot()`` in-process; a fleet operator's scrape loop needs
an HTTP endpoint. Two pieces, both stdlib-only (no new deps):

* Rendering helpers (:func:`metric`, :func:`render`) producing
  OpenMetrics 1.0 text — ``# TYPE``/``# HELP`` headers, label sets,
  summary quantiles, the mandatory ``# EOF`` terminator — from plain
  Python values and the obs/metrics instruments. Quantiles come from
  ``Histogram.percentiles()``, the SAME call ``PredictServer.
  snapshot()`` reports, so a scrape and a snapshot can never disagree
  (pinned in tests/test_obs.py).
* :class:`MetricsExporter` — a daemon-threaded ``http.server`` serving
  ``GET /metrics`` from a render callback. ``port=0`` binds an
  ephemeral port (tests, `tools/bench_serve.py` self-scrape); the
  bound port is ``exporter.port``. The render callback runs on the
  HTTP thread and must only READ host state — PredictServer's
  instruments are lock-free single-writer structures whose readers see
  a consistent-enough recent window (the concurrent-scrape test pins
  no-crash + parseable output under sustained enqueue).

Scrape contract: the endpoint serves whatever the render callback
returns at that instant; there is no caching and no device work —
reading /metrics can never add a dispatch (the zero-device-effect
contract, budget-checked with the exporter live in the suite).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _num(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def metric(name: str, mtype: str, help_text: str, samples) -> str:
    """One metric family. `samples` is [(suffix, labels, value), ...]
    with suffix "" for the bare sample, "_total"/"_count"/"_sum" for
    the typed ones (OpenMetrics counters MUST expose `_total`)."""
    lines = [f"# TYPE {name} {mtype}",
             f"# HELP {name} {_escape(help_text)}"]
    for suffix, labels, value in samples:
        lines.append(f"{name}{suffix}{_labels(labels)} {_num(value)}")
    return "\n".join(lines)


def counter(name: str, help_text: str, value,
            labels: Optional[dict] = None) -> str:
    return metric(name, "counter", help_text,
                  [("_total", labels, value)])


def gauge(name: str, help_text: str, samples) -> str:
    """`samples`: [(labels, value), ...]."""
    return metric(name, "gauge", help_text,
                  [("", lb, v) for lb, v in samples])


def summary_samples(hist, qs=(50, 95, 99),
                    labels: Optional[dict] = None) -> list:
    """Summary-sample tuples for one obs/metrics Histogram:
    recent-window quantiles (exactly ``hist.percentiles(qs)`` — the
    snapshot() definition) plus lifetime `_count`/`_sum`. Compose
    several instruments (label-distinguished) into ONE family via
    :func:`metric` — OpenMetrics allows each family to appear once."""
    samples = []
    pct = hist.percentiles(qs)
    for q in qs:
        if f"p{q}" in pct:
            lb = dict(labels or {})
            lb["quantile"] = f"{q / 100:g}"
            samples.append(("", lb, pct[f"p{q}"]))
    samples.append(("_count", labels, hist.count))
    samples.append(("_sum", labels, round(getattr(hist, "total", 0.0),
                                          6)))
    return samples


def summary(name: str, help_text: str, hist, qs=(50, 95, 99),
            labels: Optional[dict] = None) -> str:
    """A summary family from one Histogram (see summary_samples)."""
    return metric(name, "summary", help_text,
                  summary_samples(hist, qs, labels))


def render(families) -> str:
    """Families (already-rendered blocks) -> one OpenMetrics exposition
    ending in the mandatory `# EOF`."""
    return "\n".join(list(families) + ["# EOF", ""])


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (stdlib API name)
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        try:
            body = self.server.render_fn().encode("utf-8")
        except Exception as e:  # a scrape must answer, never hang
            self.send_error(500, explain=str(e)[:200])
            return
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # scrapes are not stderr news
        pass


class MetricsExporter:
    """Daemon-threaded /metrics endpoint over a render callback.

    ``port=0`` binds an ephemeral port; read the real one from
    ``self.port``. ``close()`` is idempotent and joins the thread, so a
    server shutdown never leaks the socket."""

    def __init__(self, render_fn: Callable[[], str], port: int = 0,
                 host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.render_fn = render_fn
        self.host = host
        self.port = int(self._httpd.server_address[1])
        # State the close path reads is fully initialized BEFORE the
        # serving thread starts — nothing observes a half-built
        # exporter.
        self._closed = False
        self._close_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"dpsvm-metrics-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        # Serialized teardown: concurrent close() callers all BLOCK
        # until the socket is unbound and the thread joined. The old
        # flag-first idempotence let a second caller return while the
        # first was still mid-shutdown — engine teardown would proceed
        # believing the port and thread were gone (the last member of
        # the scrape-during-close race family; regression-pinned in
        # tests/test_obs.py).
        with self._close_lock:
            if self._closed:
                return
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5.0)
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
