"""Metrics layer of the telemetry spine: process-local, bounded,
lock-free counters / gauges / histograms with a strict no-op mode.

Design constraints (ISSUE 7):

* SERVE-HOT-PATH SAFE: every instrument method is a few plain Python
  ops under the GIL — no locks, no allocation on the hot path (the
  histogram ring is pre-allocated), so a PredictServer dispatch can
  observe a latency without perturbing what it measures.
* BOUNDED: a histogram holds a fixed bin array plus a fixed-size ring
  of recent raw samples (the percentile window — the role serve.py's
  maxlen=4096 deques played); total memory is O(bins + window) no
  matter how many observations arrive.
* STRICT NO-OP MODE: a disabled :class:`Registry` hands out shared
  null instruments whose methods return immediately and record
  nothing. Nothing obs-gated ever reaches the device — metrics are fed
  exclusively from values the host already observed (chunk scalars,
  perf counters), which is what keeps the tpulint budgets byte-
  identical with observability on (the CI pin).

The default process registry is enabled by ``DPSVM_OBS=1`` in the
environment or programmatically via :func:`enable`; library code that
wants per-instance instruments regardless of the global switch (the
serving engine's latency histograms, which predate obs and must stay
always-on) constructs its own ``Registry(enabled=True)``.
"""

from __future__ import annotations

import math
import os
from typing import Optional

import numpy as np


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, v: int = 1) -> None:
        self.value += v

    def snapshot(self):
        return self.value


class Gauge:
    """Last-written value."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Bounded histogram of non-negative samples (latencies, sizes).

    Two bounded structures, each serving one consumer:

    * log2 BINS over [2^lo_exp, 2^hi_exp): lifetime distribution shape
      (counts never reset, O(1) memory) for the runlog's final dump;
    * a RING of the most recent ``window`` raw samples: exact
      percentiles of the recent window — the semantics serve.py's
      bounded deques provided, now shared by every consumer
      (``offered_load_sweep``, ``cli serve --server-bench``,
      tools/bench_serve.py).

    Lock-free: ``observe`` is index arithmetic + two array stores under
    the GIL; no allocation.
    """

    __slots__ = ("name", "window", "count", "total", "vmin", "vmax",
                 "_ring", "_i", "_bins", "_lo_exp", "_hi_exp")

    def __init__(self, name: str, window: int = 4096,
                 lo_exp: int = -20, hi_exp: int = 7):
        # Default bin span [2^-20 s ~ 1 us, 2^7 s = 128 s) fits every
        # latency this repo measures; out-of-range samples clamp to the
        # edge bins (counted, never dropped).
        self.name = name
        self.window = int(window)
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._ring = np.empty((self.window,), np.float64)
        self._i = 0
        self._lo_exp = lo_exp
        self._hi_exp = hi_exp
        self._bins = np.zeros((hi_exp - lo_exp + 1,), np.int64)

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        self._ring[self._i % self.window] = v
        self._i += 1
        e = int(math.floor(math.log2(v))) if v > 0 else self._lo_exp
        e = min(max(e, self._lo_exp), self._hi_exp)
        self._bins[e - self._lo_exp] += 1

    def __len__(self) -> int:  # recent-window size (deque parity)
        return min(self.count, self.window)

    def window_values(self, last: Optional[int] = None) -> np.ndarray:
        """The most recent min(count, window[, last]) raw samples in
        arrival order — `last` lets a caller scope a shared histogram
        to the observations ITS phase added (e.g. one offered-load
        sweep on a long-lived server)."""
        m = len(self)
        if last is not None:
            m = min(m, max(int(last), 0))
        idx = (self._i - m + np.arange(m)) % self.window
        return self._ring[idx]

    def percentiles(self, qs=(50, 95, 99),
                    last: Optional[int] = None) -> dict:
        """{"p50": ..., ...} over the RECENT WINDOW, or over only the
        most recent `last` samples (exact for the window; the lifetime
        shape lives in the bins). Empty selection reports an empty
        dict."""
        v = self.window_values(last)
        if v.size == 0:
            return {}
        return {f"p{q}": round(float(np.percentile(v, q)), 6)
                for q in qs}

    def snapshot(self) -> dict:
        out = {"count": self.count, "window": len(self)}
        if self.count:
            out.update({
                "sum": round(self.total, 6),
                "mean": round(self.total / self.count, 6),
                "min": round(self.vmin, 6),
                "max": round(self.vmax, 6),
                **self.percentiles(),
            })
            nz = np.nonzero(self._bins)[0]
            out["log2_bins"] = {
                str(int(e) + self._lo_exp): int(self._bins[e])
                for e in nz}
        return out


class _Null:
    """Shared do-nothing instrument (all three APIs)."""

    __slots__ = ()
    name = "<null>"
    value = 0
    count = 0

    def add(self, v: int = 1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def percentiles(self, qs=(50, 95, 99), last=None) -> dict:
        return {}

    def window_values(self, last=None):
        return np.empty((0,), np.float64)

    def snapshot(self):
        return None


NULL = _Null()


class Registry:
    """Name -> instrument map. Disabled registries hand out the shared
    null instruments (strict no-op mode); enablement is resolved when
    the instrument is REQUESTED, so per-run code fetches fresh handles
    (the solver obs helper does) and long-lived holders keep whatever
    mode they were created under."""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._m: dict = {}

    def _get(self, name: str, cls, **kw):
        if not self.enabled:
            return NULL
        inst = self._m.get(name)
        if inst is None or inst.__class__ is not cls:
            inst = cls(name, **kw) if kw else cls(name)
            self._m[name] = inst
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 4096) -> Histogram:
        return self._get(name, Histogram, window=window)

    def snapshot(self) -> dict:
        """JSON-able {name: value-or-dict} of everything registered."""
        return {k: v.snapshot() for k, v in sorted(self._m.items())}

    def reset(self) -> None:
        self._m.clear()


_DEFAULT: Optional[Registry] = None


def _env_enabled() -> bool:
    return os.environ.get("DPSVM_OBS", "") not in ("", "0")


def get_registry() -> Registry:
    """The process-default registry (env ``DPSVM_OBS=1`` enables)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Registry(enabled=_env_enabled())
    return _DEFAULT


def enable(on: bool = True) -> Registry:
    """Flip the default registry's mode (tests; programmatic opt-in)."""
    reg = get_registry()
    reg.enabled = bool(on)
    return reg
