"""dpsvm_tpu.obs — the telemetry spine (ISSUE 7).

Three layers, one contract:

* :mod:`dpsvm_tpu.obs.trace`   — spans: named host timeline + device
  TraceAnnotation (Perfetto) when a jax trace is running.
* :mod:`dpsvm_tpu.obs.metrics` — bounded lock-free counters / gauges /
  histograms with a strict no-op mode.
* :mod:`dpsvm_tpu.obs.runlog`  — schema-versioned JSONL run logs
  (manifest / chunk / event / span / final records).

THE CONTRACT — ZERO DEVICE EFFECT: observability reads only values the
host already holds (chunk-boundary scalars, perf counters) and never
issues a dispatch, transfer or collective of its own. The committed
tpulint budgets are checked with obs ENABLED in CI, so a violation is
a lint failure, not a code-review hope. Disabled (the default), every
entry here is a strict no-op: ``run_obs`` returns the shared
:data:`NULL_OBS`, ``trace.span`` returns the shared null context
manager, and a disabled registry hands out null instruments.

Enablement: ``config.obs.enabled`` (SVMConfig/ServeConfig), the
``--obs`` CLI flags, or the ``DPSVM_OBS=1`` environment variable (the
CI hook). ``DPSVM_OBS_DIR`` overrides the run-log directory,
``DPSVM_TRACE_DIR`` the device-trace directory.

The solver-facing surface is :func:`run_obs`: the host loops in
solver/smo.py, parallel/dist_smo.py and solver/fleet.py call it once
per solve and get either :data:`NULL_OBS` or a live :class:`RunObs`
that owns a run log, a trace session and the registry instruments —
``chunk()`` / ``event()`` / ``finish()`` / ``span()``.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from dpsvm_tpu.obs import compilelog, metrics, runlog, trace
from dpsvm_tpu.obs.metrics import Registry, enable, get_registry
from dpsvm_tpu.obs.runlog import SCHEMA_VERSION, RunLog, read_runlog
from dpsvm_tpu.obs.trace import TraceSession, span

__all__ = [
    "compilelog", "metrics", "runlog", "trace", "Registry", "RunLog",
    "TraceSession",
    "SCHEMA_VERSION", "enable", "get_registry", "read_runlog", "span",
    "obs_enabled", "run_obs", "RunObs", "NULL_OBS",
]


def obs_enabled(obs_config=None) -> bool:
    """Effective on/off: explicit config wins; DPSVM_OBS=1 is the
    ambient opt-in (CI uses it so the tier-1 suite and the tpulint
    budget check both run with the spine live)."""
    if obs_config is not None and getattr(obs_config, "enabled", False):
        return True
    return os.environ.get("DPSVM_OBS", "") not in ("", "0")


def _trace_dir(obs_config=None) -> Optional[str]:
    if obs_config is not None and getattr(obs_config, "trace_dir", None):
        return obs_config.trace_dir
    return os.environ.get("DPSVM_TRACE_DIR") or None


class _NullObs:
    """Disabled-mode run handle: every method is a no-op; ``span``
    returns the shared null context manager. One shared instance."""

    __slots__ = ()
    run_id = None
    live = False

    def chunk(self, **fields) -> None:
        pass

    def event(self, name: str, **fields) -> None:
        pass

    def finish(self, **fields) -> None:
        pass

    def span(self, name: str):
        return trace.span(name)  # null unless an outer session is live

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_OBS = _NullObs()


class _LabeledSpan:
    """RunObs span: the trace span plus a compile-attribution label, so
    an executor built inside this dispatch yields a ``compile`` runlog
    record naming the span (obs/compilelog.py). Entered/exited in
    label-then-span order so compile events during the dispatch see the
    label either way."""

    __slots__ = ("_span", "_label")

    def __init__(self, name: str, shape):
        self._span = trace.span(name)
        self._label = compilelog.label(name, shape)

    def __enter__(self):
        self._label.__enter__()
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        self._label.__exit__(*exc)
        return False


def _shape_signature(meta) -> Optional[str]:
    """Human-grep-able shape signature from a run's manifest meta —
    the field compile records carry when the triggering dispatch's
    label has no more specific one."""
    if not meta:
        return None
    keys = ("n", "n_pad", "d", "k", "n_union", "n_devices", "buckets")
    parts = [f"{k}={meta[k]}" for k in keys if k in meta]
    return " ".join(parts) or None


class RunObs:
    """Live per-run observability: a RunLog (manifest written at
    construction), a TraceSession whose span events sink into the same
    JSONL, and the registry instruments the chunk records feed.

    All record fields come from host-held values — callers pass the
    scalars they already pulled (the packed chunk observation); this
    class never touches device arrays.
    """

    live = True

    def __init__(self, tool: str, config=None, meta=None,
                 obs_config=None, directory: Optional[str] = None):
        self._log = RunLog.open(tool, config=config, meta=meta,
                                obs_config=obs_config,
                                directory=directory)
        self.run_id = self._log.run_id
        self._session = TraceSession(trace_dir=_trace_dir(obs_config),
                                     sink=self._log.span_sink)
        self._session.__enter__()
        # PRIVATE per-run registry, always live: a run enabled via
        # config/--obs must record regardless of the AMBIENT
        # (env-gated) default registry's state — using get_registry()
        # here would silently dump "metrics": {} for every flag-enabled
        # run. The final record's dump is therefore THIS RUN's
        # instruments, which is also the right scoping (two runs in one
        # process don't sum into each other).
        self.registry = Registry(enabled=True)
        self._pairs = self.registry.counter(f"{tool}.pairs_total")
        self._dispatches = self.registry.counter(
            f"{tool}.dispatches_total")
        self._gap = self.registry.gauge(f"{tool}.gap")
        self._chunk_s = self.registry.histogram(f"{tool}.chunk_seconds")
        self._events = self.registry.counter(f"{tool}.events_total")
        self._last_pairs = None
        self._finished = False
        self._t0 = time.perf_counter()
        # Compile accounting (obs/compilelog.py): every backend
        # executable built while this run is live yields a `compile`
        # record {entrypoint, shape, seconds} and bumps the counter —
        # runtime visibility for the cost tpulint's budgets pin
        # statically. Sink removed in finish() (idempotent). The sink
        # must hold the run WEAKLY: a strong reference from the global
        # sink registry would keep a faulted run alive and defeat the
        # __del__ exception-safety path (the fault-retry contract).
        import weakref

        self._sig = _shape_signature(meta)
        self._compiles = self.registry.counter(f"{tool}.compiles_total")
        ref = weakref.ref(self)

        def _sink(entrypoint, shape, seconds, _ref=ref):
            run = _ref()
            if run is not None:
                run._on_compile(entrypoint, shape, seconds)

        self._compile_sink = _sink
        compilelog.add_sink(self._compile_sink)

    def _on_compile(self, entrypoint: str, shape, seconds: float):
        self._compiles.add(1)
        self._log.record("compile", entrypoint=entrypoint,
                         shape=shape or self._sig,
                         seconds=round(seconds, 6))

    def chunk(self, pairs: int, b_hi: float, b_lo: float,
              device_seconds: float, dispatch: int, **fields) -> None:
        """One host observation of device progress. ``pairs`` is the
        run-cumulative count the host just unpacked; the delta vs the
        previous observation is derived here so runlog consumers can
        sum deltas without replaying cumulative state."""
        delta = pairs - (self._last_pairs
                         if self._last_pairs is not None else 0)
        self._last_pairs = pairs
        self._pairs.add(max(delta, 0))
        self._dispatches.add(1)
        self._gap.set(b_lo - b_hi)
        self._chunk_s.observe(device_seconds)
        self._log.record("chunk", pairs=int(pairs),
                         pairs_delta=int(delta),
                         b_hi=float(b_hi), b_lo=float(b_lo),
                         gap=float(b_lo - b_hi),
                         device_seconds=round(float(device_seconds), 6),
                         dispatch=int(dispatch), **fields)

    def event(self, name: str, **fields) -> None:
        self._events.add(1)
        self._log.record("event", name=name, **fields)

    def finish(self, **fields) -> None:
        if self._finished:
            return
        self._finished = True
        compilelog.remove_sink(self._compile_sink)
        self._session.__exit__(None, None, None)
        self._log.finish(wall_seconds=round(
            time.perf_counter() - self._t0, 6),
            metrics=self.registry.snapshot(), **fields)

    def __del__(self):
        # Exception safety: a solve that faults mid-loop (the
        # fault-retry path) never reaches its finish() call; when the
        # handler releases the frames, this closes the run log and —
        # critically — exits the global trace session so later runs
        # don't feed a dead session. Idempotent; best-effort during
        # interpreter shutdown.
        try:
            self.finish(aborted=True)
        except Exception:
            pass

    def span(self, name: str, shape: Optional[str] = None):
        return _LabeledSpan(name, shape or self._sig)

    @property
    def path(self) -> str:
        return self._log.path

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


def run_obs(tool: str, config=None, meta=None, directory=None):
    """The solver/tool entry point: NULL_OBS when observability is off
    (the strict zero-overhead default), else a live RunObs. `config`
    may be an SVMConfig/ServeConfig (its ``obs`` field is consulted
    and its snapshot lands in the manifest), any dataclass, or None.

    IMPORTANT behavioral invariant: enabling obs never changes solver
    control flow — chunk cadence, dispatch counts and compiled
    programs are identical with obs on and off (records simply ride
    the observations the host was already making). Pinned by
    tests/test_obs.py and the obs-enabled tpulint CI check.
    """
    ocfg = getattr(config, "obs", None)
    if not obs_enabled(ocfg):
        return NULL_OBS
    return RunObs(tool, config=config, meta=meta, obs_config=ocfg,
                  directory=directory)
