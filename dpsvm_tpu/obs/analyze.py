"""Runlog analytics: turn the telemetry spine's records into answers.

The spine (ISSUE 7) only *records* — runlogs pile up in CI artifacts
with nothing that reads them. This module is the reader (ISSUE 8):

* :func:`load_runs` / :func:`summarize_run` — parse one or many runlog
  JSONL files (or directories of them) into per-run summaries:
  convergence diagnostics (gap trajectory, stall windows, pairs/s per
  chunk), per-phase wall-clock breakdown, compile records.
* :func:`report` — the aggregate table (text or markdown — the
  markdown mode is what CI renders into the GitHub job summary).
* :func:`diff_runs` — attribute a regression between two runs to the
  phase that moved (the Catanzaro/ThunderSVM-style per-phase
  attribution PAPERS.md describes), plus headline pairs/s and compile
  deltas.
* :func:`tail_records` — the last N records of a stream, one line per
  record (the `kubectl logs`-shaped view for live runs).

CLI surface: ``python -m dpsvm_tpu.cli obs {report,diff,tail}``
(cli.py forwards argv verbatim to :func:`run_cli` — one flag surface,
the lint-subcommand discipline).

Everything here is a pure reader of JSONL already on disk — no jax, no
device work — so it runs anywhere the artifacts land (CI, laptops).
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import List, Optional

from dpsvm_tpu.obs.runlog import read_runlog

#: a chunk "stalls" when its gap fails to shrink by at least this
#: relative amount vs the previous chunk — consecutive stalled chunks
#: form a stall window (the diagnostic that catches working-set cycling
#: long before max_iter does).
STALL_REL_TOL = 1e-3


@dataclasses.dataclass
class Run:
    """One run's records, split by kind (stream order preserved)."""

    path: str
    run_id: str
    manifest: dict
    chunks: list
    events: list
    compiles: list
    final: Optional[dict]


def runlog_paths(paths) -> List[str]:
    """Expand files/directories/globs into a sorted runlog file list
    (directories scan for *.jsonl)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        elif os.path.exists(p):
            out.append(p)
        else:
            # Globs can match subdirectories; only files are streams.
            hits = [h for h in sorted(glob.glob(p)) if os.path.isfile(h)]
            if not hits:
                raise FileNotFoundError(f"no runlog at {p!r}")
            out.extend(hits)
    return out


def load_runs(paths) -> List[Run]:
    """Every run found in `paths` (files, dirs or globs), in (file,
    stream) order. Runs interleaved in one file — concurrent writers
    share the per-(tool, pid) stream — are separated by run id."""
    runs: List[Run] = []
    for path in runlog_paths(paths):
        by_id: dict = {}
        order: list = []
        for rec in read_runlog(path):
            rid = rec["run"]
            if rid not in by_id:
                by_id[rid] = Run(path=path, run_id=rid, manifest={},
                                 chunks=[], events=[], compiles=[],
                                 final=None)
                order.append(rid)
            run = by_id[rid]
            kind = rec["kind"]
            if kind == "manifest":
                run.manifest = rec
            elif kind == "chunk":
                run.chunks.append(rec)
            elif kind == "event":
                run.events.append(rec)
            elif kind == "compile":
                run.compiles.append(rec)
            elif kind == "final":
                run.final = rec
        runs.extend(by_id[rid] for rid in order)
    return runs


def _stall_windows(chunks) -> dict:
    """Consecutive-chunk windows where the gap failed to shrink by
    STALL_REL_TOL relative — {count, longest} (in chunks)."""
    windows, longest, cur = 0, 0, 0
    prev_gap = None
    for c in chunks:
        gap = c.get("gap")
        if gap is None:
            continue
        if prev_gap is not None and not (
                gap <= prev_gap * (1.0 - STALL_REL_TOL)):
            cur += 1
            if cur == 1:
                windows += 1
            longest = max(longest, cur)
        else:
            cur = 0
        prev_gap = gap
    return {"count": windows, "longest": longest}


def summarize_run(run: Run) -> dict:
    """Flat JSON-able summary of one run: identity, convergence
    diagnostics, throughput, per-phase breakdown, compile accounting."""
    man, fin = run.manifest, run.final or {}
    pairs = sum(c.get("pairs_delta", 0) for c in run.chunks)
    dev_s = sum(c.get("device_seconds", 0.0) for c in run.chunks)
    pps = [c["pairs_delta"] / c["device_seconds"]
           for c in run.chunks
           if c.get("device_seconds") and c.get("pairs_delta", 0) > 0]
    gaps = [c["gap"] for c in run.chunks if "gap" in c]
    phases = fin.get("phase_seconds") or {}
    out = {
        "path": run.path,
        "run": run.run_id,
        "tool": man.get("tool", "?"),
        "utc": man.get("utc"),
        "git_sha": (man.get("git_sha") or "")[:12] or None,
        "engine": man.get("engine"),
        # Replica identity (ISSUE 16): which engine of a ReplicaFleet
        # wrote this run log; None for standalone engines and the
        # fleet's own aggregate run.
        "replica": man.get("replica"),
        "replicas": fin.get("replicas") or man.get("replicas"),
        "n": man.get("n"), "d": man.get("d"),
        "n_devices": man.get("n_devices"),
        "chunks": len(run.chunks),
        "pairs": pairs,
        "device_seconds": round(dev_s, 6),
        "pairs_per_second": round(pairs / dev_s) if dev_s else None,
        "chunk_pairs_per_second": {
            "min": round(min(pps)), "max": round(max(pps)),
        } if pps else None,
        "gap_first": gaps[0] if gaps else None,
        "gap_last": gaps[-1] if gaps else None,
        "stalls": _stall_windows(run.chunks),
        "events": [e.get("name") for e in run.events],
        "compiles": len(run.compiles),
        "compile_seconds": round(sum(c.get("seconds", 0.0)
                                     for c in run.compiles), 6),
        "converged": fin.get("converged"),
        "iterations": fin.get("iterations"),
        "wall_seconds": fin.get("wall_seconds"),
        "aborted": bool(fin.get("aborted")) if fin else None,
        "finished": run.final is not None,
        "phase_seconds": phases or None,
        # Kernel-row cache accounting (ISSUE 9 satellite: the solver
        # caches were invisible here). Both the per-pair LRU and the
        # ooc block cache report through the same final-record fields;
        # None when the run carried no cache.
        "cache_hit_rate": fin.get("cache_hit_rate"),
        "cache_hits": fin.get("cache_hits"),
        "cache_lookups": fin.get("cache_lookups"),
        "cache_evictions": fin.get("cache_evictions"),
        "tiles_streamed": fin.get("tiles_streamed"),
        # Shrunken-stream accounting (ISSUE 19): the ooc solver's
        # active-set shrinking — active-view fraction of n, full-stream
        # reconstructions, and the tiles/bytes the live-tile skip never
        # streamed; None/absent when the run carried no shrinking.
        "ooc_shrink": fin.get("ooc_shrink"),
        "shrink_active_fraction": fin.get("shrink_active_fraction"),
        "shrink_reconstructions": fin.get("shrink_reconstructions"),
        "shrink_demoted": fin.get("shrink_demoted"),
        "tiles_skipped": fin.get("tiles_skipped"),
        "tile_bytes_skipped": fin.get("tile_bytes_skipped"),
        # Fault-tolerance accounting (ISSUE 13 satellite): counts of
        # the fault-story event records — injected/real transient
        # faults, retry attempts, safe-config demotions, journal
        # rehydrates — so a run's recovery history reads off the
        # report table.
        "fault_events": {
            name: sum(1 for e in run.events if e.get("name") == name)
            for name in ("fault", "retry", "demotion", "rehydrate",
                         "dispatch_failed", "resume")
        },
        # Serving-engine accounting (ISSUE 10 satellite): the v2
        # engine's final record carries its scheduler counters; None
        # for solver runs (and v1 serve runs, which predate them).
        "deadline_misses": fin.get("deadline_misses"),
        "expired": fin.get("expired"),
        "hot_swaps": fin.get("hot_swaps"),
        "dispatch_failures": fin.get("dispatch_failures"),
        "serve_requests": fin.get("requests") if man.get(
            "tool") == "serve" else None,
        # Network front-door accounting (ISSUE 15): the engine
        # snapshot's "net" sub-dict (connection / frame / verdict /
        # protocol-error counters) when a ServeServer was attached.
        "net": fin.get("net"),
        # Union-storage accounting (ISSUE 17): the engine snapshot's
        # per-model storage map and quantized-union count, so a
        # quantized serving run is distinguishable in the report table.
        "union_storage": fin.get("union_storage"),
        "quantized_unions": fin.get("quantized_unions"),
        "batch_occupancy_mean": ((fin.get("batch_occupancy") or {})
                                 .get("mean")),
        # Auto-gate provenance (ISSUE 14): the manifest's autotune
        # record — which DeviceProfile (if any) resolved the solve's
        # None-valued accelerator knobs, and what each decided.
        "autotune": man.get("autotune"),
    }
    # Continuous-learning accounting (ISSUE 18): `generation` events
    # from the cli learn loop — one per refreshed model generation,
    # carrying the warm-start seed size and pairs saved vs cold.
    gens = [e for e in run.events if e.get("name") == "generation"]
    out["generations"] = len(gens) if gens else None
    out["learn_pairs_saved"] = (sum(int(e.get("pairs_saved") or 0)
                                    for e in gens if e.get("gen"))
                                if gens else None)
    out["learn_seed_sv_last"] = (int(gens[-1].get("seed_sv") or 0)
                                 if gens else None)
    out["learn_estimated"] = (any(e.get("estimated") for e in gens)
                              if gens else None)
    return out


def _phases_of(summary: dict) -> dict:
    """A run's per-phase seconds, with the chunk-sum fallback for runs
    that carry no phase clock (serve runlogs): everything attributed to
    'solve' so diffs still have one honest bucket."""
    ph = summary.get("phase_seconds")
    if ph:
        return dict(ph)
    return {"solve": summary.get("device_seconds") or 0.0}


def diff_runs(a: dict, b: dict) -> dict:
    """Attribute the wall-clock movement from run-summary `a` (baseline)
    to `b` to the phase that moved. Deltas are ``b - a`` seconds per
    phase; the attribution names the phase with the largest
    |delta| and its share of the total movement."""
    pa, pb = _phases_of(a), _phases_of(b)
    phases = sorted(set(pa) | set(pb))
    deltas = {p: round(pb.get(p, 0.0) - pa.get(p, 0.0), 6)
              for p in phases}
    total_a = sum(pa.values())
    total_b = sum(pb.values())
    total_delta = total_b - total_a
    worst = max(phases, key=lambda p: abs(deltas[p])) if phases else None
    # Share of the GROSS movement (sum of |per-phase deltas|), not the
    # net: offsetting phases (setup +2s, solve -1.5s) are exactly the
    # case attribution exists for, and a net denominator would print
    # nonsense shares over 100% there.
    gross = sum(abs(d) for d in deltas.values())
    share = (abs(deltas[worst]) / gross
             if worst is not None and gross > 1e-12 else None)
    out = {
        "a": {"path": a["path"], "run": a["run"], "tool": a["tool"]},
        "b": {"path": b["path"], "run": b["run"], "tool": b["tool"]},
        "total_seconds_a": round(total_a, 6),
        "total_seconds_b": round(total_b, 6),
        "total_delta_seconds": round(total_delta, 6),
        "phase_deltas": deltas,
        "attributed_phase": worst,
        "attributed_share": round(share, 4) if share is not None else None,
        "pairs_per_second_a": a.get("pairs_per_second"),
        "pairs_per_second_b": b.get("pairs_per_second"),
        "compile_delta": (b.get("compiles", 0) or 0)
        - (a.get("compiles", 0) or 0),
    }
    ppa, ppb = a.get("pairs_per_second"), b.get("pairs_per_second")
    if ppa and ppb:
        out["pairs_per_second_delta"] = round(ppb / ppa - 1.0, 4)
    return out


def pick_run(runs: List[Run], run_id: Optional[str] = None,
             tool: Optional[str] = None) -> Run:
    """The run a diff side means: by explicit id when given, else the
    LAST finished run (streams append; the newest complete run is the
    one being compared), else the last run at all."""
    cand = [r for r in runs if tool is None
            or r.manifest.get("tool") == tool]
    if run_id is not None:
        for r in cand:
            if r.run_id == run_id:
                return r
        raise KeyError(f"run id {run_id!r} not found "
                       f"(have {[r.run_id for r in cand]})")
    # "Last" means chronologically newest, not last in lexical file
    # order (a dir can hold solve-400.jsonl written after
    # solve-5000.jsonl): order by the manifest's utc stamp (ISO-8601,
    # sorts lexically; stable sort keeps stream order within a second).
    def _utc(r):
        return r.manifest.get("utc") or ""

    finished = sorted((r for r in cand if r.final is not None),
                      key=_utc)
    if finished:
        return finished[-1]
    if not cand:
        raise ValueError("no runs found")
    return sorted(cand, key=_utc)[-1]


# ----------------------------------------------------------- rendering

def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


_REPORT_COLS = (
    ("tool", "tool"), ("run", "run"), ("engine", "engine"),
    ("n", "n"), ("d", "d"), ("chunks", "chunks"), ("pairs", "pairs"),
    ("device_s", "device_seconds"), ("pairs/s", "pairs_per_second"),
    ("gap last", "gap_last"), ("stalls", None), ("compiles", "compiles"),
    ("cache", None), ("shrink", None), ("serve", None), ("learn", None),
    ("faults", None),
    ("profile", None), ("phases", None), ("done", None),
)

#: faults-column legend: event name -> compact tag (ISSUE 13).
_FAULT_TAGS = (("fault", "f"), ("retry", "r"), ("demotion", "d"),
               ("resume", "c"), ("rehydrate", "h"),
               ("dispatch_failed", "x"))


def _report_row(s: dict) -> list:
    ph = s.get("phase_seconds")
    ph_txt = ("/".join(f"{k[:3]}={v:.3g}" for k, v in ph.items())
              if ph else "-")
    stalls = s["stalls"]
    done = ("conv" if s.get("converged")
            else "abort" if s.get("aborted")
            else "open" if not s.get("finished") else "stop")
    hr = s.get("cache_hit_rate")
    row = []
    for head, key in _REPORT_COLS:
        if key is not None:
            row.append(_fmt(s.get(key)))
        elif head == "stalls":
            row.append(f"{stalls['count']}(max {stalls['longest']})"
                       if stalls["count"] else "0")
        elif head == "cache":
            # The cache_hit_rate line (ISSUE 9 satellite): hit rate of
            # whichever kernel-row cache the run carried (per-pair LRU
            # or the ooc block cache), "-" when none.
            row.append(f"{100 * hr:.1f}%" if hr is not None else "-")
        elif head == "shrink":
            # Shrunken-stream column (ISSUE 19): active-view fraction,
            # full-stream reconstructions, and tiles the live-tile
            # skip never streamed (with the bytes they would have
            # cost); "-" for runs without ooc shrinking. A trailing
            # "dem" tags a run the endgame demoted back to the exact
            # full stream.
            if not s.get("ooc_shrink"):
                row.append("-")
            else:
                frac = s.get("shrink_active_fraction")
                txt = (f"act={frac:.2f} " if frac is not None else "")
                txt += (f"rec={s.get('shrink_reconstructions') or 0} "
                        f"skip={s.get('tiles_skipped') or 0}t")
                gb = (s.get("tile_bytes_skipped") or 0) / 2**30
                if gb >= 0.01:
                    txt += f"/{gb:.2f}GiB"
                if s.get("shrink_demoted"):
                    txt += " dem"
                row.append(txt)
        elif head == "serve":
            # Serving-engine column (ISSUE 10 satellite): deadline
            # misses / hot swaps / mean batch occupancy for v2 serve
            # runs, "-" for everything else. fail= appears only when
            # dispatches actually failed (ISSUE 13 watchdog); rej= /
            # perr= only when the network front door rejected frames
            # or saw protocol errors (ISSUE 15).
            if s.get("deadline_misses") is None:
                row.append("-")
            else:
                occ = s.get("batch_occupancy_mean")
                net = s.get("net") or {}
                # rep= tags a ReplicaFleet member's run with its
                # replica index (ISSUE 16); a fleet-of-N aggregate
                # run shows rep=xN instead.
                rep = ""
                if s.get("replica") is not None:
                    rep = f"rep={s['replica']} "
                elif (s.get("replicas") or 1) > 1:
                    rep = f"rep=x{s['replicas']} "
                # st= tags a run whose union storage is narrower than
                # f32 (ISSUE 17): one tag when every model agrees,
                # st=mixed when a multi-model engine splits.
                stores = set((s.get("union_storage") or {}).values())
                st = ""
                if stores and stores != {"f32"}:
                    st = (f"st={stores.pop()} " if len(stores) == 1
                          else "st=mixed ")
                row.append(
                    rep + st
                    + f"miss={s['deadline_misses']} "
                    f"swap={s.get('hot_swaps') or 0}"
                    + (f" fail={s['dispatch_failures']}"
                       if s.get("dispatch_failures") else "")
                    + (f" rej={net['rejected']}"
                       if net.get("rejected") else "")
                    + (f" perr={net['protocol_errors']}"
                       if net.get("protocol_errors") else "")
                    + (f" occ={occ:.2f}" if occ is not None else ""))
        elif head == "learn":
            # Continuous-learning column (ISSUE 18): generation count,
            # last seed SV size and pairs saved vs cold for cli learn
            # runs ("~" marks a rate-ESTIMATED cold baseline, not a
            # measured one); "-" for everything else.
            if s.get("generations") is None:
                row.append("-")
            else:
                est = "~" if s.get("learn_estimated") else ""
                row.append(
                    f"gen={s['generations']} "
                    f"seed={s.get('learn_seed_sv_last') or 0} "
                    f"saved={est}{s.get('learn_pairs_saved') or 0}")
        elif head == "profile":
            # Auto-gate provenance column (ISSUE 14): "-" for runs
            # that consulted no auto gate, "default" when the gates
            # fell back to the hand-measured defaults, else the
            # resolving DeviceProfile's basename — with "+knob" tags
            # for every gate the profile flipped ON.
            at = s.get("autotune")
            if not at or not at.get("gates"):
                row.append("-")
            else:
                gates = at["gates"]
                profs = {g.get("profile") for g in gates.values()
                         if g.get("source") == "profile"}
                if not profs:
                    row.append("default")
                else:
                    name = os.path.basename(next(iter(profs)))
                    on = [k for k, g in gates.items()
                          if g.get("source") == "profile"
                          and g.get("decision")]
                    row.append(name + "".join(f" +{k}" for k in on))
        elif head == "faults":
            # Fault-story column (ISSUE 13 satellite): compact tags,
            # e.g. "f=1 r=1" for one fault + one retry, "d=1" for a
            # safe-config demotion, "h=1" for a journal rehydrate;
            # "0" when the run saw no fault events.
            ev = s.get("fault_events") or {}
            parts = [f"{tag}={ev[name]}" for name, tag in _FAULT_TAGS
                     if ev.get(name)]
            row.append(" ".join(parts) if parts else "0")
        elif head == "phases":
            row.append(ph_txt)
        else:
            row.append(done)
    return row


def render_report(summaries: List[dict], md: bool = False) -> str:
    """The aggregate table over run summaries (one row per run), plus a
    one-line total. `md=True` renders GitHub-flavored markdown (the CI
    job-summary mode); default is an aligned text table."""
    heads = [h for h, _ in _REPORT_COLS]
    rows = [_report_row(s) for s in summaries]
    total_pairs = sum(s["pairs"] or 0 for s in summaries)
    total_dev = sum(s["device_seconds"] or 0 for s in summaries)
    total_compiles = sum(s["compiles"] or 0 for s in summaries)
    footer = (f"{len(summaries)} run(s): {total_pairs} pairs in "
              f"{total_dev:.3f} device-s"
              + (f" ({round(total_pairs / total_dev)}/s)"
                 if total_dev else "")
              + f", {total_compiles} compile(s)")
    if md:
        lines = ["| " + " | ".join(heads) + " |",
                 "|" + "---|" * len(heads)]
        lines += ["| " + " | ".join(r) + " |" for r in rows]
        return "\n".join(lines + ["", footer])
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(heads)]
    lines = ["  ".join(h.ljust(w) for h, w in zip(heads, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(c.ljust(w) for c, w in zip(r, widths))
              for r in rows]
    return "\n".join(lines + [footer])


def render_diff(d: dict) -> str:
    lines = [
        f"A: {d['a']['tool']} run {d['a']['run']} ({d['a']['path']})",
        f"B: {d['b']['tool']} run {d['b']['run']} ({d['b']['path']})",
        f"total: {d['total_seconds_a']:.4g}s -> "
        f"{d['total_seconds_b']:.4g}s "
        f"({d['total_delta_seconds']:+.4g}s)",
    ]
    for p, dv in sorted(d["phase_deltas"].items()):
        mark = " <-- attributed" if p == d["attributed_phase"] else ""
        lines.append(f"  {p:<10} {dv:+.4g}s{mark}")
    if d.get("pairs_per_second_delta") is not None:
        lines.append(f"pairs/s: {d['pairs_per_second_a']} -> "
                     f"{d['pairs_per_second_b']} "
                     f"({100 * d['pairs_per_second_delta']:+.1f}%)")
    if d.get("compile_delta"):
        lines.append(f"compiles: {d['compile_delta']:+d}")
    if d["attributed_phase"] is not None:
        share = (f" ({100 * d['attributed_share']:.0f}% of the gross "
                 "movement)" if d["attributed_share"] is not None else "")
        lines.append(f"attribution: phase "
                     f"'{d['attributed_phase']}'{share}")
    return "\n".join(lines)


def tail_records(path: str, n: int = 10) -> List[str]:
    """Last `n` records of one stream, one compact line per record."""
    if n <= 0:
        return []  # [-0:] would be the WHOLE stream
    out = []
    for rec in read_runlog(path)[-n:]:
        kind = rec["kind"]
        body = {k: v for k, v in rec.items()
                if k not in ("schema", "run", "kind", "config",
                             "metrics")}
        parts = " ".join(f"{k}={_fmt(v)}" for k, v in body.items()
                         if not isinstance(v, (dict, list)))
        out.append(f"[{rec['run']}] {kind:<8} {parts}")
    return out


# ----------------------------------------------------------------- CLI

def run_cli(argv=None) -> int:
    """``cli obs`` engine: report / diff / tail (argv forwarded
    verbatim from dpsvm_tpu/cli.py — one flag surface)."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="dpsvm-tpu obs",
        description="runlog analytics over the telemetry spine's JSONL "
                    "streams (dpsvm_tpu/obs)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser("report", help="aggregate run summaries "
                                       "(files, dirs or globs)")
    rp.add_argument("paths", nargs="+")
    rp.add_argument("--md", action="store_true",
                    help="GitHub-flavored markdown (the CI job-summary "
                         "mode)")
    rp.add_argument("--json", action="store_true",
                    help="machine-readable summaries (one JSON line "
                         "per run)")
    rp.add_argument("--tool", default=None,
                    help="restrict to one tool's runs (solve, "
                         "solve_mesh, fleet, serve, ...)")

    dp = sub.add_parser("diff", help="attribute A->B wall-clock "
                                     "movement to the phase that moved")
    dp.add_argument("run_a", help="baseline runlog (file/dir/glob)")
    dp.add_argument("run_b", help="candidate runlog (file/dir/glob)")
    dp.add_argument("--run-id-a", default=None)
    dp.add_argument("--run-id-b", default=None)
    dp.add_argument("--tool", default=None)
    dp.add_argument("--json", action="store_true")

    tp = sub.add_parser("tail", help="last N records of one stream")
    tp.add_argument("path")
    tp.add_argument("-n", type=int, default=10)

    args = ap.parse_args(argv)
    try:
        if args.cmd == "report":
            runs = load_runs(args.paths)
            if args.tool:
                runs = [r for r in runs
                        if r.manifest.get("tool") == args.tool]
            summaries = [summarize_run(r) for r in runs]
            if args.json:
                for s in summaries:
                    print(json.dumps(s))
            else:
                print(render_report(summaries, md=args.md))
            return 0
        if args.cmd == "diff":
            a = summarize_run(pick_run(load_runs([args.run_a]),
                                       args.run_id_a, args.tool))
            b = summarize_run(pick_run(load_runs([args.run_b]),
                                       args.run_id_b, args.tool))
            d = diff_runs(a, b)
            print(json.dumps(d) if args.json else render_diff(d))
            return 0
        lines = tail_records(args.path, args.n)
        print("\n".join(lines))
        return 0
    except BrokenPipeError:
        # `obs report ... | head` closes the pipe early — a normal way
        # to read a long table, not an error. Detach stdout so the
        # interpreter's shutdown flush doesn't re-raise.
        import os
        import sys

        try:
            sys.stdout.close()
        except Exception:
            os.close(1)
        return 0
    except (OSError, KeyError, ValueError) as e:
        # OSError covers FileNotFoundError AND e.g. IsADirectoryError
        # (`obs tail obs_runs/`) — every bad-path shape gets the
        # one-line error + exit-2 contract, never a traceback.
        import sys

        print(f"error: {e}", file=sys.stderr)
        return 2
