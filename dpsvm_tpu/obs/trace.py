"""Span/annotation layer of the telemetry spine (dpsvm_tpu/obs).

One primitive — ``span(name)`` — names a host-driven stage of work:
a solver chunk dispatch, a mesh sync, a serve bucket dispatch, a
runner build. On a device backend with an active ``jax.profiler``
trace the span additionally enters a ``TraceAnnotation``, so the name
shows up in the Perfetto/XPlane timeline next to the XLA ops it
brackets; on CPU (or with no device trace running) it degrades to a
host-side monotonic timeline: ``(name, t_start, duration)`` events
collected by the active :class:`TraceSession` and flushed as JSONL
records through the session's sink (normally the run log —
obs/runlog.py — so one file carries manifest + chunks + spans).

The ZERO-OVERHEAD contract: with no session active, ``span()`` returns
one shared no-op context manager — no allocation, no clock read, no
branch beyond the module-global check. Spans never touch the device:
they bracket host code around already-issued dispatches, so they can
never add dispatches, transfers or collectives (the tpulint budgets
pin this for the compiled programs themselves; see
docs/ARCHITECTURE.md "Observability").

Span naming convention: ``area/stage`` with the area one of
``solver`` / ``mesh`` / ``fleet`` / ``serve`` / ``bench`` /
``profile`` and the stage a short verb-less noun (``chunk``,
``sync``, ``bucket1024``, ``warm``, ``stage``). Nested spans are
allowed and appear nested in the device trace.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

# Events kept in memory per session before the oldest are dropped (a
# long-lived server must not grow a list per dispatch forever — the
# serve.py deque discipline). Drops are counted, never silent.
_MAX_EVENTS = 65536

# Stack of live sessions, innermost last. Spans attribute to the
# INNERMOST session live when the span was created — so two
# concurrently open runs in one process (e.g. bench_serve's two
# PredictServers) each collect their own spans instead of the second
# run's events landing in the first run's log under the wrong run id.
_STACK: list = []


class _NullSpan:
    """The shared disabled span: a no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """One named timed region, bound at creation to the session that
    was innermost then (stable attribution even if another session
    opens or closes while this span is running)."""

    __slots__ = ("name", "_t0", "_ann", "_sess")

    def __init__(self, name: str, annotation, session):
        self.name = name
        self._ann = annotation
        self._sess = session

    def __enter__(self):
        if self._ann is not None:
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._sess._emit(self.name, self._t0, dur)
        return False


class TraceSession:
    """One tracing window: optional on-device ``jax.profiler`` trace
    plus the host-side monotonic timeline every backend gets.

    ``sink(record_dict)``, when given, receives each span event as it
    completes (the run log passes its own record writer, so span
    events land in the same JSONL as chunk records). Without a sink
    events accumulate in ``self.events`` (bounded at ``_MAX_EVENTS``;
    ``self.dropped`` counts the overflow).

    Nesting/concurrency: sessions stack; each span attributes to the
    session that was INNERMOST when the span was created, so
    concurrently open runs each collect their own timeline. Only one
    ``jax.profiler`` device trace can run per process — the first live
    session with a ``trace_dir`` owns it; inner sessions' spans still
    appear in it as TraceAnnotations.
    """

    def __init__(self, trace_dir: Optional[str] = None,
                 sink: Optional[Callable] = None):
        self.trace_dir = trace_dir
        self.sink = sink
        self.events: list = []
        self.dropped = 0
        self._device_trace = False
        self._closed = False

    # -- lifecycle ----------------------------------------------------
    def __enter__(self):
        _STACK.append(self)
        if self.trace_dir and not any(s._device_trace for s in _STACK
                                      if s is not self):
            try:
                import jax

                jax.profiler.start_trace(self.trace_dir)
                self._device_trace = True
            except Exception:
                # No profiler backend (or one already running): the
                # host timeline is the degraded-mode contract.
                self._device_trace = False
        return self

    def __exit__(self, *exc):
        if self._closed:
            return False
        self._closed = True
        if self._device_trace:
            self._device_trace = False
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
        if self in _STACK:
            _STACK.remove(self)
        return False

    # -- event path ---------------------------------------------------
    def _emit(self, name: str, t0: float, dur: float) -> None:
        rec = {"kind": "span", "name": name,
               "t": round(t0, 6), "dur": round(dur, 6)}
        if self.sink is not None:
            self.sink(rec)
            return
        if len(self.events) >= _MAX_EVENTS:
            self.dropped += 1
            return
        self.events.append(rec)


def span(name: str):
    """Named span context manager bound to the innermost live session;
    the shared no-op when none is active (the strict zero-overhead
    mode)."""
    if not _STACK:
        return _NULL_SPAN
    sess = _STACK[-1]
    ann = None
    if any(s._device_trace for s in _STACK):
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
        except Exception:
            ann = None
    return _LiveSpan(name, ann, sess)


def active_session() -> Optional[TraceSession]:
    """The innermost live session (None when tracing is off)."""
    return _STACK[-1] if _STACK else None
