"""Structured run logs: one JSONL stream per process and kind, every
record self-describing and schema-versioned.

The repo's measurement artifacts (BENCH_r*.json, MULTICHIP_r*.json,
BENCH_SERVE_r*.json, TPU_SMOKE_r*.json) and its ad-hoc per-tool timing
all predate this module and each rolled its own JSON shape; the run
log is the ONE substrate they now share (ISSUE 7): a manifest record
(config snapshot, git sha, jax + device topology), per-chunk records
streamed as the host observes them, free-form event records (endgame
demotion, fault retries), span records from obs/trace.py, and a final
record with the run's result fields and the metrics-registry dump.

Record shapes (all carry ``schema`` = :data:`SCHEMA_VERSION`,
``run`` = the writer's run id, and ``kind``):

* ``manifest`` — opened-run header: ``utc``, ``tool``, ``git_sha``,
  ``jax``, ``backend``, ``device_kind``, ``n_devices``, ``config``
  (dataclass snapshot), plus caller metadata (n, d, engine, ...).
* ``chunk``   — one host observation of device progress: cumulative
  ``pairs``, per-chunk ``pairs_delta``, ``b_hi``/``b_lo``/``gap``,
  ``device_seconds`` (this chunk's dispatch->retired time, bounded by
  the loop's single block_until_ready), ``dispatch`` ordinal.
* ``event``   — named occurrences: ``{"name": ..., **fields}``.
* ``span``    — host timeline events from obs/trace.py.
* ``compile`` — one backend executable built while the run was live
  (obs/compilelog.py): ``entrypoint`` (the dispatch label that
  triggered it), ``shape`` (signature), ``seconds``.
* ``final``   — run result fields + ``metrics`` (registry snapshot).

Everything is computed from values the host ALREADY holds — writing a
run log adds zero device dispatches, transfers or collectives (the
tpulint budgets are checked with obs enabled in CI to pin this).

Files are per-process append-only (``<kind>-<pid>.jsonl`` under the
run-log directory) so concurrent runs never interleave partial lines;
records of one run share a ``run`` id. :func:`read_runlog` loads and
validates a stream; :func:`records_for` filters one run's records.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

# Version of BOTH the runlog record schema and the telemetry fields
# embedded in the benchmark artifacts (BENCH/MULTICHIP/SERVE/SMOKE
# *_r*.json "schema_version"). Bump on an incompatible shape change;
# readers (bench._latest_bench_artifact) skip records NEWER than what
# they understand, explicitly rather than by crashing.
SCHEMA_VERSION = 1

_RUN_COUNTER = 0


def _git_dir(root: str) -> str:
    """The actual git directory for `root`. In a worktree or submodule
    checkout ``.git`` is a FILE holding a ``gitdir: <path>`` pointer
    (relative paths resolve against root) — following it is what keeps
    manifests from logging sha "unknown" there."""
    dot_git = os.path.join(root, ".git")
    if os.path.isfile(dot_git):
        with open(dot_git) as fh:
            first = fh.readline().strip()
        if first.startswith("gitdir:"):
            target = first.split(":", 1)[1].strip()
            if not os.path.isabs(target):
                target = os.path.normpath(os.path.join(root, target))
            return target
    return dot_git


def git_sha(repo_root: Optional[str] = None) -> str:
    """Current commit sha, read from .git directly (no subprocess —
    run logs open on hot paths and in sandboxes without git). Handles
    ``.git``-as-file checkouts (worktrees/submodules) via the
    ``gitdir:`` pointer; a worktree's HEAD ref resolves against the
    parent repository's common dir."""
    root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        git_dir = _git_dir(root)
        with open(os.path.join(git_dir, "HEAD")) as fh:
            head = fh.read().strip()
        if head.startswith("ref:"):
            ref = head.split(None, 1)[1]
            # Worktree git dirs keep refs/packed-refs in the parent
            # repository's common dir (the `commondir` pointer file).
            common = git_dir
            common_file = os.path.join(git_dir, "commondir")
            if os.path.isfile(common_file):
                with open(common_file) as fh:
                    rel = fh.read().strip()
                common = (rel if os.path.isabs(rel)
                          else os.path.normpath(os.path.join(git_dir,
                                                             rel)))
            for base in (git_dir, common):
                ref_path = os.path.join(base, *ref.split("/"))
                if os.path.exists(ref_path):
                    with open(ref_path) as fh:
                        return fh.read().strip()
            packed = os.path.join(common, "packed-refs")
            with open(packed) as fh:
                for line in fh:
                    if line.strip().endswith(ref):
                        return line.split()[0]
            return "unknown"
        return head
    except OSError:
        return "unknown"


def config_snapshot(config) -> Optional[dict]:
    """JSON-able snapshot of a (frozen dataclass) config; None stays
    None. Non-JSON leaves (e.g. nested dataclasses) are stringified
    rather than dropped."""
    if config is None:
        return None
    if dataclasses.is_dataclass(config):
        d = dataclasses.asdict(config)
    elif isinstance(config, dict):
        d = dict(config)
    else:
        return {"repr": repr(config)}

    def _clean(v):
        if isinstance(v, dict):
            return {k: _clean(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [_clean(x) for x in v]
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        return repr(v)

    return _clean(d)


def device_topology() -> dict:
    """Backend/topology facts for the manifest record. Never forces a
    backend into existence on its own — callers open run logs after
    the solver already initialized jax."""
    try:
        import jax

        devs = jax.devices()
        return {
            "jax": jax.__version__,
            "backend": devs[0].platform,
            "device_kind": getattr(devs[0], "device_kind", ""),
            "n_devices": len(devs),
            "process_count": jax.process_count(),
        }
    except Exception:
        return {"jax": "unavailable", "backend": "none",
                "device_kind": "", "n_devices": 0, "process_count": 0}


def default_dir(obs_config=None) -> str:
    """Run-log directory resolution: explicit config beats the
    DPSVM_OBS_DIR env beats ./obs_runs."""
    if obs_config is not None and getattr(obs_config, "runlog_dir", None):
        return obs_config.runlog_dir
    return os.environ.get("DPSVM_OBS_DIR") or "obs_runs"


class RunLog:
    """Append-only JSONL writer for ONE run (manifest -> chunk/event/
    span stream -> final). Use as a context manager or call
    :meth:`finish` explicitly; both are idempotent."""

    def __init__(self, path: str, tool: str, config=None, meta=None):
        global _RUN_COUNTER
        _RUN_COUNTER += 1
        self.path = path
        self.run_id = f"{os.getpid():d}-{_RUN_COUNTER:d}"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "a")
        self._finished = False
        # dict-merge, caller meta last: a caller key (e.g. a solve's
        # mesh width as n_devices) overrides the topology default
        # instead of raising a duplicate-kwarg TypeError.
        manifest = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
                    "tool": tool, "git_sha": git_sha(),
                    **device_topology(),
                    "config": config_snapshot(config),
                    **(meta or {})}
        self.record("manifest", **manifest)

    @classmethod
    def open(cls, tool: str, config=None, meta=None,
             obs_config=None, directory: Optional[str] = None) -> "RunLog":
        """Open the per-process stream for `tool` under the resolved
        run-log directory (one file per (tool, pid); runs append)."""
        d = directory or default_dir(obs_config)
        return cls(os.path.join(d, f"{tool}-{os.getpid()}.jsonl"),
                   tool, config=config, meta=meta)

    def record(self, kind: str, **fields) -> None:
        if self._fh is None:
            return
        rec = {"schema": SCHEMA_VERSION, "run": self.run_id,
               "kind": kind, **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    # The trace-session sink signature (obs/trace.py): span dicts
    # arrive pre-shaped {"kind": "span", ...}.
    def span_sink(self, rec: dict) -> None:
        self.record(**rec)

    def finish(self, **fields) -> None:
        if self._finished or self._fh is None:
            return
        self._finished = True
        self.record("final", **fields)
        self._fh.close()
        self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


def read_runlog(path: str) -> list:
    """Parse + validate a runlog JSONL: every record must carry
    schema/run/kind; records with a NEWER schema than this reader are
    skipped (forward-compat contract shared with the bench artifact
    scan). Truncated trailing lines (writer killed mid-record) are
    dropped, matching the artifact readers' resilience."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if {"schema", "run", "kind"} - rec.keys():
                continue
            if rec["schema"] > SCHEMA_VERSION:
                continue
            out.append(rec)
    return out


def records_for(records: list, run_id: str, kind: Optional[str] = None):
    """One run's records (optionally one kind), in stream order."""
    return [r for r in records
            if r["run"] == run_id and (kind is None or r["kind"] == kind)]
