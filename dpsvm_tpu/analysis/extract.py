"""Per-entrypoint fact extraction: lower, compile, walk, collect.

A manifest entry is a list of Units — one Unit per EXECUTABLE the host
loop dispatches per logical step (so ``dispatches`` is itself a pinned
fact: e.g. the shard-local engine's whole sync window costs the chunk
runner plus the packed-observation pull, 2 dispatches — PR 4's
contract). Each unit lowers at canonical shapes on the CPU backend and
yields the fact families from hlo_facts; a unit that cannot even TRACE
(Python branching on a traced value) is itself reported as a
recompile-hazard fact instead of crashing the linter.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from dpsvm_tpu.analysis import hlo_facts


@dataclasses.dataclass
class Unit:
    """One lowerable executable of an entrypoint.

    lower      -- () -> jax.stages.Lowered at the canonical shapes
    make_jaxpr -- optional () -> ClosedJaxpr of the same call (for the
                  jaxpr-walk facts; skipped when tracing is the thing
                  under test)
    device_jaxpr -- optional () -> ClosedJaxpr of the DEVICE form of a
                  Pallas-ring entrypoint (interpret=False — traceable
                  anywhere, compilable only on TPU): adds the
                  ``device_form`` fact family (hlo_facts
                  device_form_facts) pinning zero XLA collective
                  primitives + the DMA-hop structure. Entries without
                  it keep their exact pre-existing fact set, so adding
                  this field changed no committed budget.
    """

    name: str
    lower: Callable
    make_jaxpr: Optional[Callable] = None
    device_jaxpr: Optional[Callable] = None


def _declared_donated(lowered) -> Optional[int]:
    """Leaf count of jit-level donated args, from Lowered.args_info
    (jax >= 0.4.31); None when the metadata is unavailable."""
    try:
        import jax

        return sum(bool(a.donated)
                   for a in jax.tree_util.tree_leaves(lowered.args_info))
    except Exception:
        return None


def unit_facts(unit: Unit) -> dict:
    """All fact families for one unit. Never raises for trace/compile
    failures — those become facts (`trace_error` / `compile_error`) so
    a hazard INTRODUCED by a refactor shows up as a budget drift naming
    the entrypoint, exactly like any other violated fact."""
    facts: dict = {"hazards": {"traced_branch": False}}
    try:
        lowered = unit.lower()
    except Exception as e:  # TracerBoolConversionError et al.
        kind = type(e).__name__
        facts["hazards"]["traced_branch"] = (
            "TracerBool" in kind or "Concretization" in kind)
        facts["trace_error"] = kind
        return facts
    try:
        compiled = lowered.compile()
        text = compiled.as_text()
    except Exception as e:
        facts["compile_error"] = type(e).__name__
        return facts

    facts["collectives"] = hlo_facts.collective_facts(text)
    facts["transfers"] = hlo_facts.transfer_facts(text)
    facts["dots"] = hlo_facts.dot_facts(text)
    facts["dtypes"] = hlo_facts.dtype_facts(text)
    facts["donation"] = hlo_facts.donation_facts(
        text, declared_donated=_declared_donated(lowered))
    # HBM-footprint accounting (ISSUE 8): argument/output/temp/alias
    # bytes from XLA's memory_analysis — the static contract pinning
    # the same numbers obs/compilelog makes visible at runtime.
    facts["memory"] = hlo_facts.memory_facts(compiled)
    if unit.make_jaxpr is not None:
        jx = unit.make_jaxpr()
        facts["hazards"].update(hlo_facts.jaxpr_facts(jx))
    if unit.device_jaxpr is not None:
        facts["device_form"] = hlo_facts.device_form_facts(
            unit.device_jaxpr())
    return facts


def entry_facts(units) -> dict:
    """Facts for one manifest entry: per-unit fact dicts plus the
    dispatch count (len(units) — the number of executables the host
    loop runs per logical step of this entrypoint)."""
    return {
        "dispatches": len(units),
        "units": {u.name: unit_facts(u) for u in units},
    }


def extract_entries(manifest: dict, names=None) -> dict:
    """{entry_name: facts} for the selected manifest entries (all when
    `names` is None). Each manifest value is a zero-arg builder
    returning [Unit, ...] — building is deferred so `--entries foo`
    pays only foo's trace/compile time."""
    selected = list(manifest) if names is None else list(names)
    unknown = [n for n in selected if n not in manifest]
    if unknown:
        raise KeyError(
            f"unknown manifest entries {unknown}; known: "
            f"{sorted(manifest)}")
    return {name: entry_facts(manifest[name]()) for name in selected}
