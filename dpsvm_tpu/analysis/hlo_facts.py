"""Fact primitives over compiled HLO text and jaxprs.

Everything here is a pure function of program TEXT or of a traced
jaxpr — no device work, no RNG, no wall clock — so the same program
always yields the same facts and a budget diff is meaningful. The
collective parser started life as tests/test_hlo_collectives.py's
``_collective_ops`` and moved here so the ad-hoc HLO pin tests and the
budget linter read one definition.
"""

from __future__ import annotations

import re

_DTYPE_BYTES = {"f32": 4, "s32": 4, "u32": 4, "pred": 1, "f64": 8,
                "s64": 8, "u64": 8, "bf16": 2, "f16": 2, "s16": 2,
                "u16": 2, "s8": 1, "u8": 1}

#: collective op kinds the linter accounts for. reduce-scatter shows up
#: as its own op name in modern XLA; permute/all-to-all would mean a
#: different distribution algorithm entirely.
COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                    "all-to-all", "collective-permute")

#: ops that move data across the host boundary inside a compiled
#: program — the "no per-row host round-trips" contract says every hot
#: entrypoint has ZERO of these.
TRANSFER_KINDS = ("infeed", "outfeed", "send", "recv")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    el = 1
    for d in dims.split(","):
        if d:
            el *= int(d)
    return el * _DTYPE_BYTES.get(dtype, 4)


def _op_def_re(kind: str) -> re.Pattern:
    """Regex matching the DEFINITION of a `kind` op — the op name right
    after `= <result shape>` — not mere mentions inside operand lists or
    metadata. Shapes may be tuples (combined collectives) and may carry
    a layout suffix: ``f32[8,2,256]{2,1,0} all-gather(...)``."""
    return re.compile(r"= *((?:\([^)]*\))|(?:[a-z0-9]+\[[^\]]*\]"
                      r"(?:\{[^}]*\})?)) *"
                      + re.escape(kind) + r"(?:-start)?\(")


def collective_ops(hlo_text: str, kind: str):
    """[(op_line, [(dtype, bytes), ...])] for every `kind` op defined in
    the text, parsing the RESULT shape(s). Async collectives lower to a
    start/done pair naming one exchange — only the `-start` (or the sync
    form) is counted; `-done` produces no result shape of its own in the
    texts we pin (and double-counting one exchange would corrupt the
    payload accounting)."""
    out = []
    done_re = re.compile(re.escape(kind) + r"-done\(")
    op_re = _op_def_re(kind)
    for line in hlo_text.splitlines():
        if done_re.search(line):
            continue
        m = op_re.search(line)
        if not m:
            continue
        sizes = [(dt, _shape_bytes(dt, dims))
                 for dt, dims in _SHAPE_RE.findall(m.group(1))]
        out.append((line.strip(), sizes))
    return out


def collective_facts(hlo_text: str) -> dict:
    """Per-kind dispatch count + per-result payload bytes (sorted) +
    total bytes, for every kind in COLLECTIVE_KINDS. Kinds absent from
    the program are recorded as explicit zeros so a budget diff names
    the fact that APPEARED, not just a missing key."""
    facts = {}
    for kind in COLLECTIVE_KINDS:
        ops = collective_ops(hlo_text, kind)
        payloads = sorted(s for _, sizes in ops for _, s in sizes)
        facts[kind] = {"count": len(ops), "payload_bytes": payloads,
                       "total_bytes": sum(payloads)}
    return facts


_CALLBACK_TARGET_RE = re.compile(
    r'custom_call_target="[^"]*callback[^"]*"')


def _op_def_count(hlo_text: str, kind: str) -> int:
    """Count definitions of `kind` ops by name, shape-agnostic —
    infeed's nested-tuple result ((...), token[]) defeats the strict
    shape parser collective_ops uses, and for the host-boundary
    contract the COUNT is the fact."""
    done = re.compile(re.escape(kind) + r"-done\(")
    op = re.compile(r"= .*\b" + re.escape(kind) + r"(?:-start)?\(")
    return sum(1 for line in hlo_text.splitlines()
               if op.search(line) and not done.search(line))


def transfer_facts(hlo_text: str) -> dict:
    """Host-boundary op counts: infeed/outfeed/send/recv, plus
    host_callbacks — jax host callbacks (io_callback / pure_callback /
    debug prints) lower to custom-calls whose target names contain
    "callback", NOT to infeed/outfeed, so a per-row host round-trip
    smuggled in through a callback is counted here. copy-start/copy-
    done pairs are device-side (async copies) and deliberately NOT
    counted; the contract is about host round-trips."""
    facts = {kind: _op_def_count(hlo_text, kind)
             for kind in TRANSFER_KINDS}
    facts["host_callbacks"] = len(_CALLBACK_TARGET_RE.findall(hlo_text))
    return facts


_DOT_DEF_RE = re.compile(
    r"= *([a-z0-9]+)\[([0-9,]*)\](?:\{[^}]*\})? *dot\(")


def dot_result_shapes(hlo_text: str):
    """[(dtype, (dims...)), ...] for every dot op defined in the text —
    the raw material for both the budget facts and the ad-hoc pin tests
    (tests/test_compacted.py counts kernel matmuls from these)."""
    out = []
    for line in hlo_text.splitlines():
        m = _DOT_DEF_RE.search(line)
        if m:
            dims = tuple(int(d) for d in m.group(2).split(",") if d)
            out.append((m.group(1), dims))
    return out


def dot_facts(hlo_text: str) -> dict:
    """Dot/GEMM structure: total count, max result rank, and the count
    of BATCHED products (result rank >= 3) — the stacked-ensemble shape
    the compacted inference contract forbids on kernel paths."""
    shapes = dot_result_shapes(hlo_text)
    ranks = [len(dims) for _, dims in shapes]
    return {"count": len(shapes),
            "max_result_rank": max(ranks, default=0),
            "batched_rank3plus": sum(1 for r in ranks if r >= 3)}


_CONVERT_RE = re.compile(
    r"= *([a-z0-9]+)\[[^\]]*\](?:\{[^}]*\})? *convert\(([a-z0-9]+)\[")


def dtype_facts(hlo_text: str) -> dict:
    """Dtype-promotion facts of the compiled program.

    f64 anywhere on a device path is a leak (the solvers' f64 legs are
    HOST paths by design); f32->bf16 converts are counted so a budget
    can pin exactly the INTENDED quantization points (e.g. the serving
    engine's bf16 union storage) and any new one is a drift.

    int8 quantization facts (the ISSUE 17 serving hot path) are
    reported only when s8 values appear in the program at all, so
    every pre-int8 budget stays byte-identical (i32->f32 converts by
    themselves are ordinary — e.g. obs counters re-widening — and
    must not sprout new fact keys across the manifest): f32->s8 is a
    query quantization point, s32->f32 the dequant fuse re-widening
    the i32-exact dot, s8->f32 a dequantized-operand read (e.g. the
    quantized-query norms), and s8->s32 the CPU harness's int8-dot
    emulation (0 where the MXU takes the s8 operands directly). An
    int8 value APPEARING in a non-int8 entry surfaces as a new fact
    key set — a drift, exactly as intended."""
    converts = _CONVERT_RE.findall(hlo_text)

    def _n(to, frm):
        return sum(1 for t, f in converts if t == to and f == frm)

    int8_facts = {}
    if "s8[" in hlo_text:
        int8_facts = {"f32_to_int8_converts": _n("s8", "f32"),
                      "int8_to_f32_converts": _n("f32", "s8"),
                      "i32_to_f32_converts": _n("f32", "s32"),
                      "int8_to_i32_converts": _n("s32", "s8")}
    return {
        "f64_result_ops": len(re.findall(r"= *f64\[", hlo_text)),
        "f64_present": "f64[" in hlo_text,
        "f32_to_bf16_converts": _n("bf16", "f32"),
        "bf16_to_f32_converts": _n("f32", "bf16"),
        "f32_to_f64_converts": _n("f64", "f32"),
        **int8_facts,
    }


_LAYOUT_HDR_RE = re.compile(r"entry_computation_layout=\{(\(.*?\))"
                            r"->(\(?.*?\)?)(?:, [a-z_]+=|$)")


def _header_shapes(group: str):
    return [(dt, dims) for dt, dims in _SHAPE_RE.findall(group)]


def donation_facts(hlo_text: str, declared_donated: int = None) -> dict:
    """Buffer-donation facts from the HloModule header.

    aliased_outputs -- entries in ``input_output_alias`` (what XLA
        actually committed to reusing);
    donatable -- inputs whose (dtype, dims) multiset-match some output:
        the ceiling on what donation COULD free;
    missed -- donatable minus aliased: donatable args not donated =
        extra HBM live-set, the fact the budget pins at 0 for the hot
        training loops;
    declared_donated -- the jit-level donate_argnums leaf count when the
        caller knows it (None when only text is available).
    """
    header = hlo_text.splitlines()[0] if hlo_text else ""
    # One `may-alias`/`must-alias` token per committed alias entry —
    # counting tokens sidesteps the nested-brace parse of the
    # input_output_alias map.
    aliased = (header.count("may-alias") + header.count("must-alias")
               if "input_output_alias" in header else 0)
    donatable = 0
    lm = _LAYOUT_HDR_RE.search(header)
    if lm:
        ins = _header_shapes(lm.group(1))
        outs = _header_shapes(lm.group(2))
        for shp in ins:
            if shp in outs:
                outs.remove(shp)
                donatable += 1
    facts = {"aliased_outputs": aliased, "donatable": donatable,
             "missed": max(0, donatable - aliased)}
    if declared_donated is not None:
        facts["declared_donated"] = declared_donated
    return facts


def memory_facts(compiled) -> dict:
    """Static memory accounting of one compiled executable, from XLA's
    own ``memory_analysis()`` (the one exception to this module's
    text-only rule: the numbers live on the compiled object, but they
    are exact, device-free properties of the program — deterministic
    for a fixed jax/XLA version, which is what lets budgets pin them).

    argument/output/temp/alias bytes are the components of the
    executable's HBM live-set: `argument` + `output` - `alias` + `temp`
    bounds what one dispatch holds beyond the operands themselves, so
    a budget drift here is a FOOTPRINT regression (a lost donation
    shows as alias_bytes collapsing; a new materialized intermediate
    as temp_bytes growing) — the runtime cost tpulint pins statically
    while obs/compilelog counts its compile-time sibling. Generated-
    code size is deliberately excluded (it varies with codegen details
    budgets should not couple to). ``{"unavailable": True}`` on
    backends/jax builds without the API — a recorded fact, so budgets
    regenerated there still diff cleanly."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return {"unavailable": True}
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
    except Exception:
        return {"unavailable": True}


#: jaxpr-level XLA collective primitives the device-form contract
#: counts (ISSUE 11): these are the ops a ring regression would
#: reintroduce per hop. Spelled as jax primitive names (the jaxpr view,
#: not HLO op names — the device form of a Pallas-ring program never
#: compiles on the CPU backend, so its contract is pinned at trace
#: level).
COLLECTIVE_PRIMITIVES = ("psum", "all_gather", "ppermute", "all_to_all",
                         "pmax", "pmin", "psum_scatter")


def device_form_facts(closed_jaxpr) -> dict:
    """Facts of the DEVICE form (interpret=False trace) of a
    Pallas-ring entrypoint, from a jaxpr walk recursing through
    while/cond/pjit AND pallas kernel jaxprs.

    xla_collectives -- per-primitive counts over COLLECTIVE_PRIMITIVES
        (explicit zeros, like collective_facts): the ring contract pins
        the exchange at zero XLA collectives per round — a stray
        per-hop psum/ppermute/all_gather smuggled back into the body
        DRIFTS here even though the interpret-mode compile (whose HLO
        facts necessarily contain the interpreter's DMA-emulation
        gathers) cannot see it;
    xla_collective_total -- their sum (the headline number);
    dma_starts -- dma_start primitives (local + remote ring hops): a
        hop converted to a collective, or an extra staging copy, moves
        this count.

    Counts are per-EQUATION (a DMA inside a fori body counts once) —
    static program structure, the thing budgets can pin."""
    counts = {k: 0 for k in COLLECTIVE_PRIMITIVES}
    dma = [0]
    seen: set = set()

    def visit(jx):
        for eqn in jx.eqns:
            nm = eqn.primitive.name
            if nm in counts:
                counts[nm] += 1
            elif nm == "dma_start":
                dma[0] += 1

    _walk_jaxpr(closed_jaxpr.jaxpr, seen, visit)
    return {"xla_collectives": counts,
            "xla_collective_total": sum(counts.values()),
            "dma_starts": dma[0]}


def _walk_jaxpr(jaxpr, seen, visit):
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    visit(jaxpr)
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                _walk_jaxpr(inner, seen, visit)
            elif hasattr(v, "eqns"):
                _walk_jaxpr(v, seen, visit)
            elif isinstance(v, (list, tuple)):
                for vv in v:
                    inner = getattr(vv, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        _walk_jaxpr(inner, seen, visit)
                    elif hasattr(vv, "eqns"):
                        _walk_jaxpr(vv, seen, visit)


def jaxpr_facts(closed_jaxpr) -> dict:
    """Recompile-hazard facts from a jaxpr walk (recursing through
    pjit/while/cond/scan sub-jaxprs).

    weak_in_avals -- weak-typed ENTRY avals: the caller passed a bare
        Python scalar as a traced arg, so a later int-vs-float call
        retraces and type-promotes differently — the budgets pin 0;
    weak_const_avals -- weak-typed captured constants (same promotion
        hazard, closure-side);
    f64_avals -- any float64 aval anywhere in the program (the jaxpr
        view of the f64-leak fact, catching leaks XLA folds away before
        the HLO text).
    """
    import numpy as np

    weak_consts = 0
    f64 = 0
    seen: set = set()

    def visit(jx):
        nonlocal weak_consts, f64
        for v in getattr(jx, "constvars", []):
            if getattr(v.aval, "weak_type", False):
                weak_consts += 1
        for eqn in jx.eqns:
            for var in eqn.outvars:
                dt = getattr(var.aval, "dtype", None)
                if dt is not None and dt == np.float64:
                    f64 += 1

    _walk_jaxpr(closed_jaxpr.jaxpr, seen, visit)
    return {
        "weak_in_avals": sum(bool(getattr(a, "weak_type", False))
                             for a in closed_jaxpr.in_avals),
        "weak_const_avals": weak_consts,
        "f64_avals": f64,
    }
