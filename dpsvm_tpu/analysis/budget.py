"""Budget IO, drift diffing, verdicts, and the lint runner.

A budget is one JSON file per manifest entry
(``dpsvm_tpu/analysis/budgets/<entry>.json``) holding the entry's full
fact tree. ``check`` re-extracts the facts and diffs them leaf-by-leaf
with a DENY-by-default verdict: any changed, added, or removed fact is
a DRIFT naming the entrypoint and the violated fact path. A budget may
carry an explicit ``"allow"`` list of fact-path prefixes whose drifts
are reported but tolerated (the escape hatch for facts known to vary
across XLA releases — empty everywhere today).

Regenerating after an INTENTIONAL structural change is
``python -m tools.tpulint --write-budgets`` (then commit the diff: the
budget delta IS the review artifact, see docs/ARCHITECTURE.md).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

BUDGET_DIR = Path(__file__).parent / "budgets"

PASS, DRIFT, MISSING, ERROR = "PASS", "DRIFT", "MISSING_BUDGET", "ERROR"
ORPHAN = "ORPHAN_BUDGET"


def budget_path(entry: str, budget_dir=None) -> Path:
    return Path(budget_dir or BUDGET_DIR) / f"{entry}.json"


def load_budget(entry: str, budget_dir=None):
    p = budget_path(entry, budget_dir)
    if not p.exists():
        return None
    with open(p) as fh:
        return json.load(fh)


def write_budget(entry: str, facts: dict, budget_dir=None) -> Path:
    import jax

    p = budget_path(entry, budget_dir)
    p.parent.mkdir(parents=True, exist_ok=True)
    # The facts are exact properties of lowered HLO, so they are coupled
    # to the jax/XLA release that generated them; the recorded version
    # lets in-suite consumers skip (rather than spuriously fail) under a
    # different jax, while the pinned CI tpulint job stays the gate.
    doc = {"entry": entry, "allow": [], "jax": jax.__version__,
           "facts": facts}
    with open(p, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return p


def budget_jax_version(budget_dir=None):
    """The jax version the committed budgets were generated under (None
    when no budget records one). A MIXED set — some files regenerated
    under a different jax than the rest, e.g. a partial
    ``--write-budgets --entries ...`` commit — is a hard error: every
    consumer (the in-suite skip gate, run_lint's version-skew notice)
    would otherwise key off whichever file happens to sort first."""
    seen = {}
    for p in sorted(Path(budget_dir or BUDGET_DIR).glob("*.json")):
        with open(p) as fh:
            v = json.load(fh).get("jax")
        if v:
            seen.setdefault(v, []).append(p.name)
    if len(seen) > 1:
        raise ValueError(
            "mixed jax versions across committed budgets — regenerate "
            "ALL of them under one jax (make lint_budgets): "
            + "; ".join(f"{v}: {', '.join(names)}"
                        for v, names in sorted(seen.items())))
    return next(iter(seen), None)


def orphan_budgets(entries, budget_dir=None):
    """Budget files with no manifest entry — a renamed/deleted
    entrypoint whose stale budget would otherwise ship green (the
    deny-by-default contract must cover the entry level too)."""
    known = set(entries)
    return [p.stem for p in sorted(Path(budget_dir or BUDGET_DIR)
                                   .glob("*.json"))
            if p.stem not in known]


def diff_facts(budgeted, observed, path=""):
    """Leaf-level [(fact_path, budgeted, observed)] differences, in
    deterministic path order. Missing vs extra keys are diffs too — a
    fact family that vanishes is as much a drift as one that changes."""
    diffs = []
    if isinstance(budgeted, dict) and isinstance(observed, dict):
        for k in sorted(set(budgeted) | set(observed)):
            sub = f"{path}.{k}" if path else k
            if k not in budgeted:
                diffs.append((sub, "<absent>", observed[k]))
            elif k not in observed:
                diffs.append((sub, budgeted[k], "<absent>"))
            else:
                diffs.extend(diff_facts(budgeted[k], observed[k], sub))
    elif budgeted != observed:
        diffs.append((path, budgeted, observed))
    return diffs


def check_entry(entry: str, observed: dict, budget_dir=None) -> dict:
    doc = load_budget(entry, budget_dir)
    if doc is None:
        return {"entry": entry, "verdict": MISSING, "diffs": [],
                "allowed": []}
    allow = tuple(doc.get("allow", []))
    diffs = diff_facts(doc.get("facts", {}), observed)
    denied = [d for d in diffs
              if not any(d[0].startswith(a) for a in allow)]
    allowed = [d for d in diffs
               if any(d[0].startswith(a) for a in allow)]
    return {"entry": entry, "verdict": DRIFT if denied else PASS,
            "diffs": denied, "allowed": allowed}


def drift_table(results) -> str:
    """The human-readable PASS/DRIFT summary (one row per entrypoint,
    then one line per violated fact)."""
    width = max([len(r["entry"]) for r in results] + [10])
    lines = [f"{'entrypoint':<{width}}  verdict",
             f"{'-' * width}  -------"]
    for r in results:
        note = ""
        if r["allowed"]:
            note = f"  ({len(r['allowed'])} allowed drift(s))"
        lines.append(f"{r['entry']:<{width}}  {r['verdict']}{note}")
    for r in results:
        for path, want, got in r["diffs"]:
            lines.append(f"  DRIFT {r['entry']}: {path}: "
                         f"budget={want!r} observed={got!r}")
        for path, want, got in r["allowed"]:
            lines.append(f"  allow {r['entry']}: {path}: "
                         f"budget={want!r} observed={got!r}")
        if r["verdict"] == MISSING:
            lines.append(f"  DRIFT {r['entry']}: no committed budget — "
                         f"run tools/tpulint.py --write-budgets")
        if r["verdict"] == ORPHAN:
            lines.append(f"  DRIFT {r['entry']}: budget file has no "
                         f"manifest entry — delete the stale JSON (or "
                         f"restore the entrypoint)")
    return "\n".join(lines)


def _force_cpu_backend() -> None:
    """The conftest.py dance: the budgets describe CPU-backend programs
    over 8 virtual devices, so force that platform regardless of any
    TPU the host may have attached. XLA_FLAGS must be set before the
    backend initializes; jax_platforms can still be flipped after
    import (this image's sitecustomize imports jax at startup)."""
    from dpsvm_tpu.analysis.manifest import DEVICE_COUNT

    import re

    flags = os.environ.get("XLA_FLAGS", "")
    # Replace (not skip) any pre-existing count: an inherited
    # --xla_force_host_platform_device_count=2 would otherwise survive
    # and dead-end require_devices() with advice the user already took.
    flags, n = re.subn(r"--xla_force_host_platform_device_count=\d+",
                       f"--xla_force_host_platform_device_count="
                       f"{DEVICE_COUNT}", flags)
    if not n:
        flags = (flags + f" --xla_force_host_platform_device_count="
                 f"{DEVICE_COUNT}").strip()
    os.environ["XLA_FLAGS"] = flags
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_lint(argv=None) -> int:
    """The engine behind ``python -m tools.tpulint`` and ``cli lint``.

    --check (default): extract facts for the manifest and diff against
    committed budgets; exit 0 only if every entry PASSes.
    --write-budgets: overwrite the budget files with observed facts.
    --entries a,b,c: restrict to a subset.
    """
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="static HLO/jaxpr contract linter (ISSUE 5): lower "
                    "the hot-entrypoint manifest on the CPU backend and "
                    "diff structured facts against committed budgets")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true", default=False,
                      help="diff facts against budgets (the default)")
    mode.add_argument("--write-budgets", action="store_true",
                      help="regenerate budget files from observed facts")
    ap.add_argument("--entries", default=None,
                    help="comma-separated manifest subset (default all)")
    ap.add_argument("--budgets-dir", default=None,
                    help=f"budget directory (default {BUDGET_DIR})")
    args = ap.parse_args(argv)

    _force_cpu_backend()
    from dpsvm_tpu.analysis.extract import extract_entries
    from dpsvm_tpu.analysis.manifest import MANIFEST, require_devices

    require_devices()
    names = args.entries.split(",") if args.entries else None
    observed = extract_entries(MANIFEST, names)

    if args.write_budgets:
        for entry, facts in observed.items():
            p = write_budget(entry, facts, args.budgets_dir)
            print(f"wrote {p}")
        if names is None:
            # Full regeneration knows the whole manifest: prune stale
            # budgets (a renamed/deleted entrypoint) so the very next
            # --check doesn't fail ORPHAN on the state this tool wrote.
            for e in orphan_budgets(MANIFEST, args.budgets_dir):
                p = budget_path(e, args.budgets_dir)
                p.unlink()
                print(f"removed stale {p} (no manifest entry)")
        return 0

    import jax

    gen = budget_jax_version(args.budgets_dir)
    if gen is not None and gen != jax.__version__:
        # Don't let a version skew masquerade as structural drift: the
        # facts are exact properties of lowered HLO, so diffs below may
        # be the jax/XLA release, not the repo. Still run the diff (it
        # is exact either way) but say why it may be noisy.
        print(f"NOTE: budgets were generated under jax {gen}; running "
              f"{jax.__version__} — DRIFTs below may reflect the "
              f"jax/XLA version, not a repo regression (bump the "
              f"tier1.yml pin and `make lint_budgets` together)")
    results = [check_entry(entry, facts, args.budgets_dir)
               for entry, facts in observed.items()]
    if names is None:
        # Full-manifest check: a committed budget whose entrypoint left
        # the manifest is lost coverage, not a silent no-op.
        results += [{"entry": e, "verdict": ORPHAN, "diffs": [],
                     "allowed": []}
                    for e in orphan_budgets(MANIFEST, args.budgets_dir)]
    print(drift_table(results))
    bad = [r for r in results if r["verdict"] != PASS]
    print(f"\ntpulint: {len(results) - len(bad)}/{len(results)} "
          f"entrypoints within budget")
    return 1 if bad else 0
