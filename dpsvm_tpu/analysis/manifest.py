"""The manifest of hot entrypoints tpulint lowers and budgets.

One entry per compiled program whose STRUCTURE the reproduction's wins
depend on (ISSUE 5): the single-chip block round (f32 and the
bf16-Gram storage variant), the fleet chain, the three mesh chunk
runners (global / pipelined / shard-local) plus the ring-exchange
forms of the global and shard-local runners (ISSUE 11 — dual
interpret/device_form views), compacted multiclass decision, the
serving bucket executors (f32 and the bf16 storage variant), and mesh
prediction. Shapes are canonical-small —
op structure is shape-independent (the test_pipelined.py discipline) —
so the whole manifest traces+compiles in seconds on the CPU backend.

Chunk-runner entries carry TWO units: the runner itself plus the packed
scalar observation pull (solver/smo.py ``_pack_obs``) — the host loop's
complete per-observation dispatch set, so ``dispatches`` pins PR 4's
2-dispatches-per-sync contract.

Every entry requires DEVICE_COUNT visible devices (the suite's 8
virtual CPU devices); `require_devices()` fails loudly otherwise rather
than silently lowering a different program.
"""

from __future__ import annotations

# Canonical shapes, shared with tests/test_hlo_collectives.py's
# small-shape pins so budgets and pin tests describe the SAME programs.
DEVICE_COUNT = 8
N, D, Q, INNER = 4096, 24, 64, 128
R_SYNC = 4
ROUNDS_PER_CHUNK = 4
C_BOUNDS = (5.0, 5.0)
EPS, TAU = 1e-3, 1e-12
GAMMA = 0.1
# Serving / inference shapes: S union rows, K submodel columns, M_PAD
# padded per-model SV slots, NB query rows per bucket.
S_UNION, K_MODELS, M_PAD, NB = 256, 10, 64, 64
# Coalesced multi-model bucket (serving v2, ISSUE 10): total decision
# columns when several registered models sharing one union answer from
# a single dispatch (e.g. a 10-column OvO head + a 5-column OvR head +
# a binary column stacked side by side).
K_COALESCED = 16
# Out-of-core tile shape (ops/ooc.ooc_fold_tile): rows per streamed
# tile. The entry's shapes are a pure function of (T_TILE, D, Q) —
# never of total n — which is the contract its budget exists to pin.
T_TILE = 512


def require_devices() -> None:
    import jax

    have = len(jax.devices())
    if have < DEVICE_COUNT:
        raise RuntimeError(
            f"tpulint needs {DEVICE_COUNT} devices for the mesh entries "
            f"but only {have} are visible. Run through "
            f"`python -m tools.tpulint` (which forces the CPU backend "
            f"with {DEVICE_COUNT} virtual devices) or set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={DEVICE_COUNT} "
            f"before jax initializes.")


def _kp():
    from dpsvm_tpu.ops.kernels import KernelParams

    return KernelParams("rbf", GAMMA)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _block_state(n):
    import jax.numpy as jnp

    from dpsvm_tpu.solver.block import BlockState

    return BlockState(
        alpha=_sds((n,), jnp.float32), f=_sds((n,), jnp.float32),
        b_hi=_sds((), jnp.float32), b_lo=_sds((), jnp.float32),
        pairs=_sds((), jnp.int32), rounds=_sds((), jnp.int32))


def _chunk_args(n):
    import jax.numpy as jnp

    return (_sds((n, D), jnp.float32), _sds((n,), jnp.float32),
            _sds((n,), jnp.float32), _sds((n,), jnp.float32),
            _sds((n,), jnp.bool_), _block_state(n),
            _sds((), jnp.int32))


def _obs_unit():
    """The packed-observation pull every chunk driver dispatches after
    the runner (solver/smo.py _pack_obs)."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.smo import _pack_obs

    args = (_sds((), jnp.int32), _sds((), jnp.float32),
            _sds((), jnp.float32))
    return Unit("pack_obs", lambda: _pack_obs.lower(*args))


def _jaxpr_of(fn, *args, **kw):
    import jax

    return lambda: jax.make_jaxpr(lambda *a: fn(*a, **kw))(*args)


def block_chunk_single():
    """Single-chip block-SMO chunk — the paper's one-GEMV-per-round
    contract on one chip, via the DONATED runner the solve driver
    dispatches."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.block import run_chunk_block_donated

    kw = dict(kp=_kp(), c=C_BOUNDS, eps=EPS, tau=TAU, q=Q,
              inner_iters=INNER, rounds_per_chunk=ROUNDS_PER_CHUNK,
              inner_impl="xla")
    args = _chunk_args(N)
    return [
        Unit("chunk",
             lambda: run_chunk_block_donated.lower(*args, **kw),
             _jaxpr_of(run_chunk_block_donated, *args, **kw)),
        _obs_unit(),
    ]


def block_chunk_fusedround(extra_pass: bool = False):
    """ONE-HBM-PASS fused round chunk (ISSUE 12, config.fused_round):
    the round body as two Pallas passes — gather+Gram+kernel-rows over
    X, fold+select over the O(n) vectors — with the subproblem dispatch
    between them (solver/block.py run_chunk_block_fusedround_donated).

    Dual fact views (the mesh_chunk_ring pattern): compiled facts come
    from the INTERPRET lowering (the CPU-testable form), while the
    ``device_form`` facts trace the interpret=False program and pin the
    kernel/DMA structure — zero XLA collectives, zero host callbacks,
    the donated carry (missed=0), and the dma_start count of the
    in-kernel row gather. Memory facts are a pure function of the
    canonical (N, D, Q) tile counts.

    ``extra_pass=True`` builds the MUTATED form the drift test uses
    (tests/test_tpulint.py, the ooc_fold_tile n-doubling discipline):
    the same chunk plus one re-materialized XLA kernel-row pass over X
    folded into f — exactly the extra HBM pass the one-pass contract
    forbids; its facts must DRIFT against the committed budget (the
    dot count and temp bytes move)."""
    import jax

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.block import (
        run_chunk_block_fusedround_donated)

    kw = dict(kp=_kp(), c=C_BOUNDS, eps=EPS, tau=TAU, q=Q,
              inner_iters=INNER, rounds_per_chunk=ROUNDS_PER_CHUNK,
              inner_impl="xla")
    args = _chunk_args(N)

    if extra_pass:
        from dpsvm_tpu.ops.kernels import kernel_rows

        def mutated(x, y, x_sq, k_diag, valid, state, mi, *,
                    interpret):
            st = run_chunk_block_fusedround_donated(
                x, y, x_sq, k_diag, valid, state, mi,
                interpret=interpret, **kw)
            # The deliberate extra pass: re-gather Q rows and stream X
            # through kernel_rows again (coefs from live state so XLA
            # cannot fold it away).
            qx = x[:Q]
            extra = kernel_rows(x, x_sq, qx, x_sq[:Q], _kp())
            return st._replace(f=st.f + st.alpha[:Q] @ extra)

        # Same donation declaration as the clean entry so the drift
        # isolates the extra pass, not a donation diff.
        m_i = jax.jit(mutated, donate_argnums=(5,),
                      static_argnames=("interpret",))
        return [
            Unit("chunk",
                 lambda: m_i.lower(*args, interpret=True),
                 _jaxpr_of(m_i, *args, interpret=True),
                 device_jaxpr=_jaxpr_of(m_i, *args, interpret=False)),
            _obs_unit(),
        ]

    return [
        Unit("chunk",
             lambda: run_chunk_block_fusedround_donated.lower(
                 *args, interpret=True, **kw),
             _jaxpr_of(run_chunk_block_fusedround_donated, *args,
                       interpret=True, **kw),
             device_jaxpr=_jaxpr_of(run_chunk_block_fusedround_donated,
                                    *args, interpret=False, **kw)),
        _obs_unit(),
    ]


def block_chunk_fused():
    """Fused fold+select chunk (the stock fused engine,
    config.fused_fold) via its DONATED runner — the ISSUE 12 donation
    satellite's budget: the single-chip fused variant now dispatches a
    donated carry like every other budgeted solver entry
    (donation.missed pinned 0). Same dual interpret/device_form views
    as block_chunk_fusedround (the fold_select pass is a Pallas
    kernel)."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.block import run_chunk_block_fused_donated

    kw = dict(kp=_kp(), c=C_BOUNDS, eps=EPS, tau=TAU, q=Q,
              inner_iters=INNER, rounds_per_chunk=ROUNDS_PER_CHUNK,
              inner_impl="xla")
    args = _chunk_args(N)
    return [
        Unit("chunk",
             lambda: run_chunk_block_fused_donated.lower(
                 *args, interpret=True, **kw),
             _jaxpr_of(run_chunk_block_fused_donated, *args,
                       interpret=True, **kw),
             device_jaxpr=_jaxpr_of(run_chunk_block_fused_donated,
                                    *args, interpret=False, **kw)),
        _obs_unit(),
    ]


def block_chunk_pipelined():
    """Single-chip PIPELINED chunk via its DONATED runner (ISSUE 12
    donation satellite — the mesh pipelined runner was budgeted since
    PR 5, the single-chip variant was not). pallas_select=False is the
    CPU-harness form (pure XLA), so one compiled view suffices."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.block import run_chunk_block_pipelined_donated

    kw = dict(kp=_kp(), c=C_BOUNDS, eps=EPS, tau=TAU, q=Q,
              inner_iters=INNER, rounds_per_chunk=ROUNDS_PER_CHUNK,
              inner_impl="xla")
    args = _chunk_args(N)
    return [
        Unit("chunk",
             lambda: run_chunk_block_pipelined_donated.lower(*args, **kw),
             _jaxpr_of(run_chunk_block_pipelined_donated, *args, **kw)),
        _obs_unit(),
    ]


def block_chunk_active():
    """Active-set (shrinking) chunk via its DONATED runner (ISSUE 12
    donation satellite). Pure XLA."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.block import run_chunk_block_active_donated

    kw = dict(kp=_kp(), c=C_BOUNDS, eps=EPS, tau=TAU, q=Q,
              inner_iters=INNER, rounds_per_chunk=ROUNDS_PER_CHUNK,
              m=2 * Q, k_rounds=2, inner_impl="xla")
    args = _chunk_args(N)
    return [
        Unit("chunk",
             lambda: run_chunk_block_active_donated.lower(*args, **kw),
             _jaxpr_of(run_chunk_block_active_donated, *args, **kw)),
        _obs_unit(),
    ]


def fleet_chunk():
    """Batched multi-problem SMO chunk (solver/fleet.py): the whole
    OvO/OvR fleet advances in ONE dispatch per chunk."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.fleet import FleetState, _run_fleet_chunk

    k, n = K_MODELS, 512
    state = FleetState(
        alpha=_sds((k, n), jnp.float32), f=_sds((k, n), jnp.float32),
        b_hi=_sds((k,), jnp.float32), b_lo=_sds((k,), jnp.float32),
        it=_sds((k,), jnp.int32), t=_sds((), jnp.int32))
    args = (_sds((n, D), jnp.float32), _sds((k, n), jnp.float32),
            _sds((n,), jnp.float32), _sds((k, n), jnp.bool_),
            _sds((k, 2), jnp.float32), state, _sds((), jnp.int32))
    kw = dict(kp=_kp(), eps=EPS, tau=TAU, chunk=256)
    return [Unit("chunk", lambda: _run_fleet_chunk.lower(*args, **kw),
                 _jaxpr_of(_run_fleet_chunk, *args, **kw))]


def _mesh(n_dev=DEVICE_COUNT):
    from dpsvm_tpu.parallel.mesh import make_data_mesh

    return make_data_mesh(n_dev)


def mesh_chunk():
    """Global mesh block chunk: ONE candidate all_gather pair + the
    (q, d) + (q, 5) working-set psum per round, nothing else."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.parallel.dist_block import make_block_chunk_runner

    runner = make_block_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER,
        rounds_per_chunk=1, inner_impl="xla", donate_state=True)
    args = _chunk_args(N)
    return [Unit("chunk", lambda: runner.lower(*args),
                 _jaxpr_of(runner, *args)),
            _obs_unit()]


def pipelined_chunk():
    """Pipelined mesh chunk (PR 2): same total psum payload as the
    plain round, split prefetched (overlappable) + (q, 2) handoff."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.parallel.dist_block import (
        make_block_pipelined_chunk_runner)

    runner = make_block_pipelined_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER, 1,
        inner_impl="xla", donate_state=True)
    args = _chunk_args(N)
    return [Unit("chunk", lambda: runner.lower(*args),
                 _jaxpr_of(runner, *args)),
            _obs_unit()]


def shardlocal_chunk():
    """Shard-parallel working sets (PR 4): one touched-rows all_gather
    plus one (2,) max-allreduce per R-round sync window — and exactly
    2 host dispatches per sync."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.parallel.dist_block import (
        make_block_shardlocal_chunk_runner)

    runner = make_block_shardlocal_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER,
        rounds_per_chunk=R_SYNC, sync_rounds=R_SYNC, inner_impl="xla",
        donate_state=True)
    args = _chunk_args(N)
    return [Unit("chunk", lambda: runner.lower(*args),
                 _jaxpr_of(runner, *args)),
            _obs_unit()]


def mesh_chunk_ring():
    """Ring-exchange global mesh chunk (ISSUE 11, config.ring_exchange):
    candidate exchange AND working-set recovery ride P-1 remote DMAs
    inside one Pallas kernel (ops/ring.py ring_gather), replacing the
    plain runner's 2 all_gathers + 2 psums per round.

    TWO fact views pin the contract: the compiled facts come from the
    INTERPRET lowering (the CPU-testable form — its HLO necessarily
    contains the jax interpreter's DMA-emulation collectives, recorded
    as such), while the ``device_form`` facts trace the interpret=False
    program and pin ZERO XLA collective primitives in the round body —
    a stray per-hop collective reintroduced by a refactor DRIFTS there
    (mutation-verified in tests/test_tpulint.py)."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.parallel.dist_block import make_block_chunk_runner

    kw = dict(rounds_per_chunk=1, inner_impl="xla", donate_state=True,
              ring_exchange=True)
    runner_i = make_block_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER, interpret=True,
        **kw)
    runner_d = make_block_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER, interpret=False,
        **kw)
    args = _chunk_args(N)
    return [Unit("chunk", lambda: runner_i.lower(*args),
                 _jaxpr_of(runner_i, *args),
                 device_jaxpr=_jaxpr_of(runner_d, *args)),
            _obs_unit()]


def shardlocal_chunk_ring():
    """Ring-exchange shard-local sync (ISSUE 11): the (R*q, d+3)
    touched-row window travels the ICI ring with each arriving hop
    folded IN-KERNEL (ops/ring.py ring_fold_window) — the device form
    keeps exactly ONE XLA collective per sync window (the (2,) stopping
    pmax handoff) and zero gathers; same interpret-vs-device dual view
    as mesh_chunk_ring."""
    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.parallel.dist_block import (
        make_block_shardlocal_chunk_runner)

    kw = dict(rounds_per_chunk=R_SYNC, sync_rounds=R_SYNC,
              inner_impl="xla", donate_state=True, ring_exchange=True)
    runner_i = make_block_shardlocal_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER, interpret=True,
        **kw)
    runner_d = make_block_shardlocal_chunk_runner(
        _mesh(), _kp(), C_BOUNDS, EPS, TAU, Q, INNER, interpret=False,
        **kw)
    args = _chunk_args(N)
    return [Unit("chunk", lambda: runner_i.lower(*args),
                 _jaxpr_of(runner_i, *args),
                 device_jaxpr=_jaxpr_of(runner_d, *args)),
            _obs_unit()]


def block_chunk_bf16gram():
    """bf16-Gram single-chip block chunk (ISSUE 11, config.bf16_gram
    with the perturbation bound accepting): the SAME donated runner as
    block_chunk_single lowered with X stored bfloat16. The budget pins
    the exact intended quantization structure — the bf16<->f32 convert
    counts (working-set rows widen for the replicated scalars exactly
    once per use site; dots accumulate f32 on the MXU) — so any NEW
    convert a refactor sneaks into the round body is a drift, the
    serve_bucket_bf16 discipline applied to training."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.solver.block import run_chunk_block_donated

    kw = dict(kp=_kp(), c=C_BOUNDS, eps=EPS, tau=TAU, q=Q,
              inner_iters=INNER, rounds_per_chunk=ROUNDS_PER_CHUNK,
              inner_impl="xla")
    n = N
    state = _block_state(n)
    args = (_sds((n, D), jnp.bfloat16), _sds((n,), jnp.float32),
            _sds((n,), jnp.float32), _sds((n,), jnp.float32),
            _sds((n,), jnp.bool_), state, _sds((), jnp.int32))
    return [
        Unit("chunk",
             lambda: run_chunk_block_donated.lower(*args, **kw),
             _jaxpr_of(run_chunk_block_donated, *args, **kw)),
        _obs_unit(),
    ]


def ooc_fold_tile(n_total: int = N):
    """Out-of-core per-tile fold (ISSUE 9): the ONE program dispatched
    per streamed tile of the ooc round. Its budget pins the whole
    out-of-core contract statically:

    * transfers: zero in-program host round-trips — the per-tile H2D
      is exactly ONE device_put of the (T_TILE, D) tile outside the
      program, whose size the memory facts' argument_bytes records;
    * collectives: zero (single-chip by construction);
    * donation: the gradient slice is donated into the folded output
      (declared_donated covers f_tile + err_tile) — missed stays 0;
    * memory: argument/output/temp bytes are a function of
      (T_TILE, D, Q) ONLY. ``n_total`` is accepted and deliberately
      never reaches any shape (the tile clamp is its only use) so the
      n-independence is mutation-testable: tests/test_tpulint.py
      rebuilds this entry with n_total doubled and asserts the facts
      are byte-identical to the committed budget.
    """
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.ops.ooc import ooc_fold_tile as fold

    t = min(T_TILE, n_total)  # a tile never exceeds the data
    args = (_sds((t, D), jnp.float32), _sds((t,), jnp.float32),
            _sds((t,), jnp.float32), None,
            _sds((Q, D), jnp.float32), _sds((Q,), jnp.float32),
            _sds((Q,), jnp.float32))
    kw = dict(kp=_kp(), want_dots=True, compensated=False)
    return [Unit("fold_tile", lambda: fold.lower(*args, **kw),
                 _jaxpr_of(fold, *args, **kw))]


def ooc_fold_tile_shrink(n_total: int = N, masked: bool = False):
    """SHRUNKEN-stream per-tile fold (ISSUE 19): the program an
    in-cycle ooc round dispatches per LIVE tile. It is the SAME
    ops/ooc.ooc_fold_tile program as the ooc_fold_tile entry, lowered
    at the shrunken round's variant point: ``want_dots=False`` — the
    block cache never refreshes mid-cycle (a partial dot row would
    poison the full-width LRU), so the in-cycle program must not
    materialize the (Q, T) dots.

    The budget pins the skip contract statically: a skipped tile is a
    DISPATCH THAT NEVER HAPPENS, not a masked kernel — so this
    program's facts stay a pure function of (T_TILE, D, Q), zero
    collectives, zero transfers, donated gradient slice, and
    ``n_total`` never reaches a shape (n-doubling must be
    byte-identical, the ooc_fold_tile discipline).

    ``masked=True`` builds the REJECTED alternative the drift test
    uses (tests/test_tpulint.py): one program folding every tile of a
    device-resident (n_total, D) X under a live-tile mask. Its
    argument bytes are n-sized — exactly the out-of-core violation the
    budget exists to catch — so its facts must DRIFT."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.ops.ooc import fold_tile_body
    from dpsvm_tpu.ops.ooc import ooc_fold_tile as fold

    t = min(T_TILE, n_total)  # a tile never exceeds the data

    if masked:
        tiles = n_total // t

        def masked_fold(x_full, x_sq_full, f_full, qx, qsq, coef,
                        live):
            def body(i, f_full):
                s = i * t
                x_t = lax.dynamic_slice(x_full, (s, 0), (t, D))
                xsq_t = lax.dynamic_slice(x_sq_full, (s,), (t,))
                f_t = lax.dynamic_slice(f_full, (s,), (t,))
                f_n, _, _ = fold_tile_body(x_t, xsq_t, f_t, None, qx,
                                           qsq, coef, _kp(),
                                           want_dots=False,
                                           compensated=False)
                f_n = jnp.where(live[i], f_n, f_t)
                return lax.dynamic_update_slice(f_full, f_n, (s,))

            return lax.fori_loop(0, tiles, body, f_full)

        m_j = jax.jit(masked_fold, donate_argnums=(2,))
        margs = (_sds((n_total, D), jnp.float32),
                 _sds((n_total,), jnp.float32),
                 _sds((n_total,), jnp.float32),
                 _sds((Q, D), jnp.float32), _sds((Q,), jnp.float32),
                 _sds((Q,), jnp.float32), _sds((tiles,), jnp.bool_))
        return [Unit("fold_tile", lambda: m_j.lower(*margs),
                     _jaxpr_of(m_j, *margs))]

    args = (_sds((t, D), jnp.float32), _sds((t,), jnp.float32),
            _sds((t,), jnp.float32), None,
            _sds((Q, D), jnp.float32), _sds((Q,), jnp.float32),
            _sds((Q,), jnp.float32))
    kw = dict(kp=_kp(), want_dots=False, compensated=False)
    return [Unit("fold_tile", lambda: fold.lower(*args, **kw),
                 _jaxpr_of(fold, *args, **kw))]


def ooc_mesh_fold(extra_psum: bool = False):
    """Mesh out-of-core stream programs (ISSUE 19,
    parallel/dist_block.py make_ooc_mesh_programs): two units pin the
    mesh stream's collective budget statically.

    * ``fold`` — one stream step's per-device local fold: ZERO
      collectives. Each device folds only its own shard's tile; a
      stray per-tile collective reintroduced by a refactor is exactly
      the regression this unit DRIFTs on (``extra_psum=True`` builds
      that mutated form for tests/test_tpulint.py — the same fold
      body plus one per-step psum).
    * ``select`` — the round's ONLY collectives: the candidate
      all_gather pair inside the distributed selection plus ONE
      (Q, 5) psum replicating the working-set scalars. The (q, q)
      subproblem runs replicated outside these programs, so the whole
      round's collective budget is what this unit records."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.parallel.dist_block import make_ooc_mesh_programs
    from dpsvm_tpu.parallel.mesh import DATA_AXIS, mesh_shard_map

    mesh = _mesh()
    n_loc = N // DEVICE_COUNT
    tile = min(T_TILE, n_loc)
    progs = make_ooc_mesh_programs(mesh, _kp(), C_BOUNDS, Q, n_loc,
                                   tile, selection="mvp",
                                   compensated=False)

    fold_args = (_sds((DEVICE_COUNT * tile, D), jnp.float32),
                 _sds((N,), jnp.float32), _sds((N,), jnp.float32),
                 _sds((Q, D), jnp.float32), _sds((Q,), jnp.float32),
                 _sds((Q,), jnp.float32), _sds((), jnp.int32))
    sel_args = (_sds((N,), jnp.float32), _sds((N,), jnp.float32),
                _sds((N,), jnp.float32), _sds((N,), jnp.float32),
                _sds((N,), jnp.float32), _sds((N,), jnp.bool_))

    if extra_psum:
        from dpsvm_tpu.ops.ooc import fold_tile_body

        shard = PartitionSpec(DATA_AXIS)
        rep = PartitionSpec()

        def _mut_body(x_blk, x_sq_loc, f_loc, qx, qsq, coef, j):
            s = j * tile
            f_t = lax.dynamic_slice(f_loc, (s,), (tile,))
            xsq_t = lax.dynamic_slice(x_sq_loc, (s,), (tile,))
            f_n, _, _ = fold_tile_body(x_blk, xsq_t, f_t, None, qx,
                                       qsq, coef, _kp(),
                                       want_dots=False,
                                       compensated=False)
            # The stray per-step collective the fold budget forbids.
            leak = lax.psum(jnp.sum(f_n), DATA_AXIS)
            f_n = f_n + 0.0 * leak
            return lax.dynamic_update_slice(f_loc, f_n, (s,))

        mut = jax.jit(mesh_shard_map(
            _mut_body, mesh=mesh,
            in_specs=(shard, shard, shard, rep, rep, rep, rep),
            out_specs=shard, check=False), donate_argnums=(2,))
        return [Unit("fold", lambda: mut.lower(*fold_args),
                     _jaxpr_of(mut, *fold_args)),
                Unit("select", lambda: progs["select"].lower(*sel_args),
                     _jaxpr_of(progs["select"], *sel_args))]

    return [Unit("fold", lambda: progs["fold"].lower(*fold_args),
                 _jaxpr_of(progs["fold"], *fold_args)),
            Unit("select", lambda: progs["select"].lower(*sel_args),
                 _jaxpr_of(progs["select"], *sel_args))]


def warm_f_rebuild(n_total: int = N):
    """Warm-start gradient reconstruction (ISSUE 18): the programs that
    rebuild f = K (alpha*y) - y from a repaired seed in ONE streamed
    pass over X before a warm-started solve.

    Two units pin the two engine forms:

    * ``fold_tile`` — the single-chip/ooc streamed form: the SAME
      ops/ooc.ooc_fold_tile program as the ooc_fold_tile entry, lowered
      at the warm path's variant point (want_dots=False — the rebuild
      folds seed-block kernel rows into f and never materializes dots)
      and the warm path's (Q_BLOCK,) fixed query-block width
      (solver/warmstart.py zero-pads the seed tail with INERT zero
      coefficients so compiles are a pure function of (T_TILE, D,
      Q_BLOCK)). Zero collectives, donated f carry (missed=0), and —
      like the ooc entry — ``n_total`` reaches only the tile clamp, so
      the memory facts' n-independence at fixed tile shape is
      mutation-testable by n-doubling.
    * ``mesh`` — the sharded rebuild (warmstart._warm_fold_mesh_factory):
      each device contributes its local rows to the seed block through
      ONE psum of the packed (Q_BLOCK, d+2) [qx | qsq | coef] operand,
      then folds the local kernel rows into its donated f shard — one
      collective per seed block, nothing else. Lowered at the canonical
      (N, D) sharded shapes (a one-shot rebuild over the resident
      shards is inherently n-sized; the n-independence claim is scoped
      to the streamed fold_tile form).
    """
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.ops.ooc import ooc_fold_tile as fold
    from dpsvm_tpu.solver.warmstart import (Q_BLOCK,
                                            _warm_fold_mesh_factory)

    t = min(T_TILE, n_total)  # a tile never exceeds the data
    tile_args = (_sds((t, D), jnp.float32), _sds((t,), jnp.float32),
                 _sds((t,), jnp.float32), None,
                 _sds((Q_BLOCK, D), jnp.float32),
                 _sds((Q_BLOCK,), jnp.float32),
                 _sds((Q_BLOCK,), jnp.float32))
    tile_kw = dict(kp=_kp(), want_dots=False, compensated=False)

    _, mapped = _warm_fold_mesh_factory(DEVICE_COUNT, _kp(), D,
                                        q_block=Q_BLOCK)
    mesh_args = (_sds((N, D), jnp.float32), _sds((N,), jnp.float32),
                 _sds((N,), jnp.float32), _sds((N, Q_BLOCK), jnp.float32),
                 _sds((N,), jnp.float32))
    return [
        Unit("fold_tile", lambda: fold.lower(*tile_args, **tile_kw),
             _jaxpr_of(fold, *tile_args, **tile_kw)),
        Unit("mesh", lambda: mapped.lower(*mesh_args),
             _jaxpr_of(mapped, *mesh_args)),
    ]


def compacted_decision():
    """Shared-SV compacted multiclass decision (PR 3): ONE feature-dim
    kernel matmul per query block, NO rank-3 stacked product."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.models.multiclass import _compacted_batch_factory

    batch = _compacted_batch_factory()
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), jnp.float32),
            _sds((K_MODELS, M_PAD), jnp.float32),
            _sds((K_MODELS, M_PAD), jnp.int32),
            _sds((K_MODELS,), jnp.float32))
    kw = dict(kp=_kp())
    return [Unit("batch", lambda: batch.lower(*args, **kw),
                 _jaxpr_of(batch, *args, **kw))]


def _serve_bucket_units(dtype_str, k=K_MODELS):
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.serve import _dense_batch_factory

    batch = _dense_batch_factory()
    sv_dt = jnp.bfloat16 if dtype_str == "bfloat16" else jnp.float32
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), sv_dt),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION, k), jnp.float32),
            _sds((k,), jnp.float32))
    kw = dict(kp=_kp())
    return [Unit("batch", lambda: batch.lower(*args, **kw),
                 _jaxpr_of(batch, *args, **kw))]


def serve_bucket():
    """PredictServer single-device bucket executor, f32 union storage:
    one dense (nb, S) kernel matmul + the K @ C contraction."""
    return _serve_bucket_units("float32")


def serve_bucket_bf16():
    """Same executor with bf16 union storage: the budget pins EXACTLY
    the intended quantization points (queries round through the storage
    dtype once; norms re-widen once) — any additional f32<->bf16
    convert is a drift."""
    return _serve_bucket_units("bfloat16")


def serve_bucket_int8():
    """Quantized serving hot path (ISSUE 17): the int8 bucket
    executor. The budget pins the EXACT convert structure of the
    calibrated quantization algebra — queries quantize to int8 once
    on device (one f32->int8 convert), the union rows arrive int8
    (no staging convert in the traced graph), ONE kernel matmul runs
    int8 x int8 -> int32 on the MXU (i32-exact), and the dequant fuse
    re-widens once (one i32->f32 convert) against the f32 row-scale
    outer product. Any extra convert — a second rounding of the
    queries, a dequant of the union before the dot — is a drift. The
    memory facts pin the 4x union argument-bytes cut: the (S, D)
    union argument is int8 (1 byte/elt vs serve_bucket's 4), plus the
    (S,) f32 scales."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.serve import _dense_batch_int8_factory

    batch = _dense_batch_int8_factory()
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), jnp.int8),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION, K_MODELS), jnp.float32),
            _sds((K_MODELS,), jnp.float32))
    kw = dict(kp=_kp())
    return [Unit("batch", lambda: batch.lower(*args, **kw),
                 _jaxpr_of(batch, *args, **kw))]


def serve_coalesced_bucket():
    """Serving v2 coalesced multi-model bucket (ISSUE 10): the SAME
    dense executor as serve_bucket, lowered at the stacked
    (S, K_COALESCED) coefficient shape a union group dispatches when
    several registered models share one compacted union / kernel
    family (serving/dispatch.py UnionGroup). The budget pins the
    engine-side contract statically: ONE (nb, S) kernel matmul
    regardless of how many models' columns ride the dispatch, zero
    collectives, zero host-callback transfers, and memory facts that
    scale only with K_total — a scheduler change that snuck a
    per-model matmul (or a host round-trip) into the coalesced path
    would drift this budget."""
    return _serve_bucket_units("float32", k=K_COALESCED)


def serve_mesh_bucket():
    """Union-sharded mesh serving executor: partial (nb, k) columns
    combined by ONE psum."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.serve import _mesh_serve_executor

    _, mapped = _mesh_serve_executor(DEVICE_COUNT, _kp(), "float32")
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), jnp.float32),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION, K_MODELS), jnp.float32),
            _sds((K_MODELS,), jnp.float32))
    return [Unit("batch", lambda: mapped.lower(*args),
                 _jaxpr_of(mapped, *args))]


def serve_mesh_bucket_int8():
    """Mesh-sharded int8 serving executor (ISSUE 17): the quantized
    union's row blocks AND their f32 scales shard together over the
    data axis; each device runs the LOCAL int8 x int8 -> i32 dot and
    dequant fuse, and the partial decision columns combine through the
    SAME single psum as serve_mesh_bucket — quantization must add
    converts, never collectives. Budget pins one local kernel matmul,
    one psum, zero host callbacks, and the int8 local union shard in
    the memory facts."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.serve import _mesh_serve_executor

    _, mapped = _mesh_serve_executor(DEVICE_COUNT, _kp(), "int8")
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), jnp.int8),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION, K_MODELS), jnp.float32),
            _sds((K_MODELS,), jnp.float32))
    return [Unit("batch", lambda: mapped.lower(*args),
                 _jaxpr_of(mapped, *args))]


def serve_mesh_group():
    """Mesh-sharded v2 union group (ISSUE 16): the engine_core
    UnionGroup mesh variant's bucket dispatch, lowered through the v2
    engine's own import path at the COALESCED multi-model column
    width a union group actually dispatches. The budget pins the
    sharded serving contract statically: ONE (nb, S_local) kernel
    matmul over the LOCAL union shard + ONE psum combining partial
    decision columns — per dispatch, regardless of how many models'
    columns ride it — zero host callbacks, zero other collectives.
    A change that snuck a second all-reduce (e.g. psumming the kernel
    block instead of the contracted columns) or a host round-trip
    into the sharded path would drift this budget. Same executor
    family as serve_mesh_bucket (the v1 PredictServer lowering at
    K_MODELS); this entry is the v2 engine's shape."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.serving.engine_core import _mesh_serve_executor

    _, mapped = _mesh_serve_executor(DEVICE_COUNT, _kp(), "float32")
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), jnp.float32),
            _sds((S_UNION,), jnp.float32),
            _sds((S_UNION, K_COALESCED), jnp.float32),
            _sds((K_COALESCED,), jnp.float32))
    return [Unit("batch", lambda: mapped.lower(*args),
                 _jaxpr_of(mapped, *args))]


def mesh_predict():
    """SV-row-sharded mesh decision (predict.decision_function_mesh):
    per-shard kernel rows + ONE psum of partial decision sums."""
    import jax.numpy as jnp

    from dpsvm_tpu.analysis.extract import Unit
    from dpsvm_tpu.predict import _mesh_decision_executor

    _, mapped = _mesh_decision_executor(DEVICE_COUNT, _kp())
    args = (_sds((NB, D), jnp.float32), _sds((S_UNION, D), jnp.float32),
            _sds((S_UNION,), jnp.float32), _sds((S_UNION,), jnp.float32))
    return [Unit("batch", lambda: mapped.lower(*args),
                 _jaxpr_of(mapped, *args))]


MANIFEST = {
    "block_chunk_single": block_chunk_single,
    "block_chunk_fusedround": block_chunk_fusedround,
    "block_chunk_fused": block_chunk_fused,
    "block_chunk_pipelined": block_chunk_pipelined,
    "block_chunk_active": block_chunk_active,
    "fleet_chunk": fleet_chunk,
    "mesh_chunk": mesh_chunk,
    "pipelined_chunk": pipelined_chunk,
    "shardlocal_chunk": shardlocal_chunk,
    "mesh_chunk_ring": mesh_chunk_ring,
    "shardlocal_chunk_ring": shardlocal_chunk_ring,
    "block_chunk_bf16gram": block_chunk_bf16gram,
    "ooc_fold_tile": ooc_fold_tile,
    "ooc_fold_tile_shrink": ooc_fold_tile_shrink,
    "ooc_mesh_fold": ooc_mesh_fold,
    "warm_f_rebuild": warm_f_rebuild,
    "compacted_decision": compacted_decision,
    "serve_bucket": serve_bucket,
    "serve_bucket_bf16": serve_bucket_bf16,
    "serve_bucket_int8": serve_bucket_int8,
    "serve_coalesced_bucket": serve_coalesced_bucket,
    "serve_mesh_bucket": serve_mesh_bucket,
    "serve_mesh_bucket_int8": serve_mesh_bucket_int8,
    "serve_mesh_group": serve_mesh_group,
    "mesh_predict": mesh_predict,
}
