"""tpulint: static analysis of the repo's compile-time contracts.

The paper's contribution is structural: one gather per SMO sync, kernel
rows as dense GEMVs, no per-row host round-trips. Those are facts about
LOWERED PROGRAMS, not runtime samples — so they are checkable on every
CI run with no TPU attached. This package extracts structured facts
from the jaxpr + compiled HLO of a manifest of hot entrypoints
(`manifest.py`), diffs them against checked-in budgets
(`budgets/*.json`, `budget.py`), and surfaces the verdict via
``python -m tools.tpulint`` / ``cli lint``.

Modules:
  hlo_facts -- pure fact primitives over HLO text / jaxprs
  extract   -- per-entry orchestration (lower, compile, walk, collect)
  manifest  -- the canonical entrypoints and shapes
  budget    -- budget IO, drift diffing, verdicts, the lint runner
"""

from dpsvm_tpu.analysis.hlo_facts import (  # noqa: F401
    collective_facts,
    collective_ops,
    donation_facts,
    dot_facts,
    dot_result_shapes,
    dtype_facts,
    jaxpr_facts,
    transfer_facts,
)
