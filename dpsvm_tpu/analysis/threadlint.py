"""threadlint — committed concurrency contracts for the threaded host
layer (the tpulint discipline, pointed at locks instead of HLO).

`concurrency_facts.extract_concurrency_facts` produces four fact
families over the threaded modules; this module diffs them against the
checked-in contracts in ``dpsvm_tpu/analysis/contracts/*.json`` and
enforces the built-in rules:

GUARDED_BY   an attribute reachable from a thread entry point with an
             unguarded (non-``__init__``) write is a violation.
ORDER        a cycle in the acquired-while-holding graph (including a
             non-reentrant self-acquire) is a violation.
LIFECYCLE    a ``Thread(...)`` without a ``dpsvm-`` name, or neither
             daemonized nor joined, is a violation.
SEAM         a cross-thread handoff with no entry in the committed
             handoff→seam map is a violation.

Discipline is deny-by-default, exactly like the HLO budgets: ANY fact
drift fails unless an ``allow`` entry covers it, and every allow entry
carries a one-line ``reason`` (the committed record of why a finding
is a false positive). Regeneration (``--write-contracts``) preserves
the allow lists and the seam map, prunes entries whose subjects no
longer exist, and is byte-deterministic — run it twice, get identical
files. Unlike the budgets there is NO version stamp: these facts are
properties of the Python source alone, so the contracts never need
regeneration for a jax pin bump.

Usage (all equivalent surfaces):
    python -m tools.tpulint --threads --check
    python -m tools.tpulint --threads --write-contracts
    cli lint --threads --check
    make lint            # runs the check among the other linters
    make lint_contracts  # regenerates the contracts

Importable without jax: when the ``dpsvm_tpu`` package import fails
(no jax in a minimal CI job), the fact extractor is loaded straight
from the sibling file.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

try:
    from dpsvm_tpu.analysis import concurrency_facts as _cf
except Exception:  # pragma: no cover - jax-less environments
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "dpsvm_threadlint_facts",
        Path(__file__).resolve().parent / "concurrency_facts.py")
    _cf = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_cf)

CONTRACT_DIR = Path(__file__).parent / "contracts"
FAMILIES = ("guarded_by", "lock_order", "thread_lifecycle",
            "seam_coverage")

PASS = "PASS"
DRIFT = "DRIFT"
VIOLATION = "VIOLATION"
MISSING = "MISSING_CONTRACT"
ABSENT = "<absent>"


# ------------------------------------------------------------------
# contract IO
# ------------------------------------------------------------------
def contract_path(family: str, contracts_dir=None) -> Path:
    base = Path(contracts_dir) if contracts_dir else CONTRACT_DIR
    return base / f"{family}.json"


def load_contract(family: str, contracts_dir=None):
    p = contract_path(family, contracts_dir)
    if not p.exists():
        return None
    return json.loads(p.read_text())


def write_contract(family: str, contract: dict, contracts_dir=None
                   ) -> Path:
    p = contract_path(family, contracts_dir)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(contract, indent=2, sort_keys=True) + "\n")
    return p


# ------------------------------------------------------------------
# diffing (the budget.py leaf-diff semantics, stdlib-only copy so a
# jax-less environment never has to import the HLO side)
# ------------------------------------------------------------------
def diff_facts(expected, actual, prefix="") -> list:
    diffs = []
    if isinstance(expected, dict) and isinstance(actual, dict):
        for key in sorted(set(expected) | set(actual)):
            sub = f"{prefix}.{key}" if prefix else str(key)
            if key not in expected:
                diffs.append((sub, ABSENT, actual[key]))
            elif key not in actual:
                diffs.append((sub, expected[key], ABSENT))
            else:
                diffs.extend(diff_facts(expected[key], actual[key],
                                        sub))
        return diffs
    if expected != actual:
        diffs.append((prefix, expected, actual))
    return diffs


# ------------------------------------------------------------------
# built-in rules
# ------------------------------------------------------------------
def violations_for(family: str, facts: dict, contract) -> list:
    """[(path, message)] for the family's rule set. Paths share the
    allow-prefix namespace with drift paths."""
    fam = facts[family]
    out = []
    if family == "guarded_by":
        for attr, f in fam["attrs"].items():
            if f["writes_unguarded"] and f["thread_roots"]:
                out.append((
                    f"guarded_by.attrs.{attr}",
                    f"{f['writes_unguarded']} unguarded write(s); "
                    f"reachable from {', '.join(f['thread_roots'])}"))
    elif family == "lock_order":
        for cyc in fam["cycles"]:
            out.append((f"lock_order.cycles.{cyc}",
                        "acquired-while-holding cycle "
                        "(potential deadlock)"))
    elif family == "thread_lifecycle":
        for site, t in fam["threads"].items():
            if not t["named_ok"]:
                out.append((
                    f"thread_lifecycle.threads.{site}.name",
                    f"thread name {t['name']!r} lacks the mandatory "
                    "'dpsvm-' prefix"))
            if not (t["daemon"] or t["joined"]):
                out.append((
                    f"thread_lifecycle.threads.{site}.join",
                    "thread is neither daemonized nor provably "
                    "joined on a close/drain path"))
    elif family == "seam_coverage":
        seam_map = (contract or {}).get("map", {})
        seams = set(fam["seams"])
        for h in fam["handoffs"]:
            entry = seam_map.get(h)
            if entry is None:
                out.append((
                    f"seam_coverage.handoffs.{h}",
                    "cross-thread handoff with no entry in the "
                    "committed handoff->seam map"))
            elif "seam" in entry and entry["seam"] not in seams:
                out.append((
                    f"seam_coverage.map.{h}.seam",
                    f"mapped to unknown seam {entry['seam']!r} "
                    f"(known: {sorted(seams)})"))
        for h in seam_map:
            if h not in fam["handoffs"]:
                out.append((
                    f"seam_coverage.map.{h}",
                    "seam-map entry for a handoff that no longer "
                    "exists (regenerate to prune)"))
    return out


def _allowed(path: str, allow: list):
    for entry in allow:
        if path.startswith(entry.get("path", "\x00")):
            return entry
    return None


def check_family(family: str, facts: dict, contract) -> dict:
    """Verdict record for one family against its loaded contract."""
    if contract is None:
        return {"family": family, "verdict": MISSING, "denied": [],
                "allowed": [], "message":
                f"no committed contract (run --write-contracts and "
                f"commit {contract_path(family).name})"}
    allow = contract.get("allow", [])
    denied, allowed = [], []
    for path, exp, act in diff_facts(contract.get("facts", {}),
                                     facts[family]):
        rec = (f"{family}.{path}" if not path.startswith(family)
               else path, f"expected {exp!r}", f"actual {act!r}")
        entry = _allowed(rec[0], allow)
        (allowed if entry else denied).append(
            rec + ((entry.get("reason", ""),) if entry else ()))
    has_drift = bool(denied)
    for path, msg in violations_for(family, facts, contract):
        entry = _allowed(path, allow)
        if entry:
            allowed.append((path, msg, "",
                            entry.get("reason", "")))
        else:
            denied.append((path, msg, ""))
    if not denied:
        verdict = PASS
    elif has_drift:
        verdict = DRIFT
    else:
        verdict = VIOLATION
    return {"family": family, "verdict": verdict, "denied": denied,
            "allowed": allowed, "message": ""}


# ------------------------------------------------------------------
# runner
# ------------------------------------------------------------------
def _report(results, facts, verbose_allowed=False) -> list:
    lines = [f"threadlint: {len(FAMILIES)} contract families over "
             f"{len(set(_cf.THREADED_MODULES))} threaded modules "
             f"({len(facts['guarded_by']['locks'])} locks, "
             f"{len(facts['guarded_by']['attrs'])} shared attrs, "
             f"{len(facts['thread_lifecycle']['threads'])} thread "
             f"sites, {len(facts['seam_coverage']['handoffs'])} "
             "handoffs)"]
    for r in results:
        n_allow = len(r["allowed"])
        suffix = f"  ({n_allow} allow-listed)" if n_allow else ""
        lines.append(f"  {r['family']:<17} {r['verdict']}{suffix}")
        if r["message"]:
            lines.append(f"    {r['message']}")
        for rec in r["denied"]:
            lines.append(f"    FAIL {rec[0]}: "
                         + "; ".join(x for x in rec[1:] if x))
        if verbose_allowed:
            for rec in r["allowed"]:
                lines.append(f"    allow {rec[0]}: {rec[-1]}")
    return lines


def run_check(root=None, sources=None, contracts_dir=None,
              verbose_allowed=False):
    """(exit_code, report_lines, results). The API the tests drive —
    `sources` overrides module texts so deliberate mutations never
    touch the tree."""
    facts = _cf.extract_concurrency_facts(root=root, sources=sources)
    results = [check_family(f, facts,
                            load_contract(f, contracts_dir))
               for f in FAMILIES]
    code = 0 if all(r["verdict"] == PASS for r in results) else 1
    return code, _report(results, facts, verbose_allowed), results


def write_contracts(root=None, sources=None, contracts_dir=None
                    ) -> list:
    """Regenerate all four contracts from current facts. Allow lists
    and the seam map survive regeneration (pruned to subjects that
    still exist); everything else is replaced. Byte-deterministic."""
    facts = _cf.extract_concurrency_facts(root=root, sources=sources)
    written = []
    for family in FAMILIES:
        prev = load_contract(family, contracts_dir) or {}
        contract = {"facts": facts[family],
                    "allow": sorted(prev.get("allow", []),
                                    key=lambda e: e.get("path", ""))}
        if family == "seam_coverage":
            live = set(facts[family]["handoffs"])
            contract["map"] = {h: e
                               for h, e in prev.get("map", {}).items()
                               if h in live}
        written.append(write_contract(family, contract,
                                      contracts_dir))
    return written


def run_threadlint(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint --threads",
        description="static concurrency contracts (threadlint)")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="diff current facts against the committed "
                           "contracts (default)")
    mode.add_argument("--write-contracts", action="store_true",
                      help="regenerate contracts from current facts "
                           "(allow lists and the seam map survive); "
                           "commit the JSON diff")
    ap.add_argument("--contracts-dir", default=None,
                    help="override the contracts directory (tests)")
    ap.add_argument("--show-allowed", action="store_true",
                    help="also print allow-listed findings with "
                         "their reasons")
    args = ap.parse_args(argv)

    if args.write_contracts:
        for p in write_contracts(contracts_dir=args.contracts_dir):
            print(f"wrote {p}")
        return 0
    code, lines, _results = run_check(
        contracts_dir=args.contracts_dir,
        verbose_allowed=args.show_allowed)
    print("\n".join(lines))
    return code


if __name__ == "__main__":
    sys.exit(run_threadlint())
