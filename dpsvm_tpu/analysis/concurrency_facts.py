"""threadlint fact extraction: static concurrency facts, pure AST.

The serving fabric (wire front door, replica fleet, registry hot swap,
async dispatcher, watchdog, metrics exporter) is a threaded system
whose correctness was previously proven only dynamically — faults
harness, loadgen chaos legs, scrape-during-close race tests. This
module gives it the tpulint treatment: extract structured facts from
the SOURCE of the threaded modules and let `threadlint.py` diff them
against committed contracts. Four fact families:

guarded_by        every threading.Lock/RLock/Condition object, its
                  `with` regions, and which ``self._x`` attributes are
                  written inside vs. outside them; plus which THREAD
                  ENTRY POINTS (thread targets, signal handlers,
                  ``__del__``, metrics-render callbacks) can reach a
                  function that touches each attribute.
lock_order        the acquired-while-holding directed graph across
                  modules (direct `with` nesting plus a one-pass
                  call-graph expansion), its cycles (potential
                  deadlock), and a canonical topological order.
thread_lifecycle  every ``threading.Thread(...)`` creation site: the
                  (normalized) name literal, whether it carries the
                  mandatory ``dpsvm-`` prefix, and whether the thread
                  is provably daemonized or joined somewhere in its
                  module (the loadgen zero-thread-leak assert, made
                  static).
seam_coverage     cross-thread handoff points (queue puts, event sets)
                  cross-referenced against the ``testing/faults.py``
                  SEAM names, so a new handoff without a fault seam is
                  flagged.

Everything here is stdlib-only ON PURPOSE: unlike the HLO budgets
(whose facts are properties of a pinned jax's lowering), these facts
are properties of the Python source alone, so the contracts carry no
version stamp and the CI job needs no jax install.

Analysis scope and honesty notes (also in ARCHITECTURE.md):

* Lock references resolve through ``self``-attributes of the current
  class, constructor-typed attributes/locals (``self.x = Cls()`` /
  ``x = Cls()``), module-level names, and — as a last resort — a
  globally UNIQUE attribute name. Unresolvable `with` items are
  ignored (never guessed).
* Calls resolve the same way; calls with ambiguous names and untyped
  receivers are SKIPPED, so the lock-order graph can miss edges but
  does not invent them — a missed edge costs coverage, an invented one
  would cost false deadlock reports.
* Writes inside ``__init__`` are construction-time (happens-before
  publication) and counted separately, not as unguarded writes.
"""

from __future__ import annotations

import ast
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]

# The threaded surface of the repo. Order is cosmetic (facts are
# sorted); membership is the contract — a new threaded module must be
# added here to be linted, and the ARCHITECTURE.md section says so.
THREADED_MODULES = (
    "dpsvm_tpu/cli.py",
    "dpsvm_tpu/obs/export.py",
    "dpsvm_tpu/serve.py",
    "dpsvm_tpu/serving/dispatch.py",
    "dpsvm_tpu/serving/engine_core.py",
    "dpsvm_tpu/serving/registry.py",
    "dpsvm_tpu/serving/replicas.py",
    "dpsvm_tpu/serving/scheduler.py",
    "dpsvm_tpu/serving/server.py",
    "dpsvm_tpu/testing/faults.py",
    "dpsvm_tpu/utils/native.py",
)

FAULTS_MODULE = "dpsvm_tpu/testing/faults.py"

_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}
_EVENT_CTORS = {"Event"}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_HANDOFF_PUTS = {"put", "put_nowait"}

# Method names shared with stdlib containers/primitives. These never
# resolve through the globally-unique-name fallback (a dict's .get()
# must not be mistaken for ModelRegistry.get — that invents a
# self-deadlock edge); typed receivers still resolve them.
_GENERIC_METHODS = frozenset({
    "get", "put", "put_nowait", "get_nowait", "set", "pop", "popitem",
    "append", "extend", "add", "discard", "remove", "update", "clear",
    "copy", "keys", "values", "items", "setdefault", "join", "split",
    "strip", "acquire", "release", "wait", "notify", "notify_all",
    "start", "read", "write", "send", "recv", "close", "open", "index",
    "count", "sort", "encode", "decode", "format",
})


def _attr_chain(node):
    """('self', '_stats', 'bump') for ``self._stats.bump`` — or None
    for anything that is not a pure Name/Attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _name_literal(node):
    """Normalize a Thread ``name=`` value: string constants verbatim,
    f-strings as the constant parts with ``*`` for formatted fields
    (``f"dpsvm-net-writer-{cid}"`` -> ``dpsvm-net-writer-*``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant):
                out.append(str(part.value))
            else:
                out.append("*")
        return "".join(out)
    if isinstance(node, ast.IfExp):
        # name=("dpsvm-net-pump" if n == 1 else f"dpsvm-net-pump-{i}")
        a = _name_literal(node.body)
        b = _name_literal(node.orelse)
        if a is None or b is None:
            return None
        if a == b:
            return a
        common = ""
        for ca, cb in zip(a, b):
            if ca != cb:
                break
            common += ca
        return common + "*"
    return None


def _walk_no_defs(node):
    """ast.walk that does not descend into nested function/lambda
    bodies (those run on their own schedule, under their own held-lock
    state)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


class _Module:
    def __init__(self, key: str, tree: ast.Module):
        self.key = key
        self.stem = Path(key).stem
        self.tree = tree
        self.classes: dict = {}          # class name -> ClassDef
        self.aliases: dict = {}          # local alias -> module key
        self.module_locks: dict = {}     # name -> kind
        self.event_names: set = set()    # attr tails / locals = Event()
        self.queue_names: set = set()
        self.joined_tails: set = set()   # receiver tails with .join(
        self.fns: list = []              # _Fn scans


class _Fn:
    def __init__(self, module: _Module, cls, qual: str):
        self.module = module
        self.cls = cls                   # class name or None
        self.qual = qual                 # "Cls.meth" / "fn" / nested
        self.id = f"{module.key}::{qual}"
        self.is_init = qual.endswith("__init__")
        self.writes = []                 # (attr_id, held tuple, is_init)
        self.reads = set()               # attr ids (self attrs)
        self.raw_name_reads = set()
        self.global_decls = set()
        self.calls = []                  # (chain, held tuple)
        self.acquires = set()            # lock ids acquired directly
        self.nested_edges = set()        # (held, acquired)
        self.thread_sites = []
        self.signal_handlers = []
        self.render_fns = []             # chains passed to MetricsExporter
        self.handoffs = []               # (tail, method)
        self.local_types = {}            # var -> class name
        self.nested_defs = {}            # name -> fn id


class _Extractor:
    def __init__(self, sources: dict):
        self.sources = sources
        self.modules: dict = {}
        self.lock_registry: dict = {}    # lock id -> {kind, module}
        self.locks_by_tail: dict = {}    # attr name -> set(lock ids)
        self.class_index: dict = {}      # class name -> module key
        self.methods: dict = {}          # (cls, name) -> fn id
        self.fn_index: dict = {}         # fn id -> _Fn
        self.fns_by_name: dict = {}      # bare name -> [fn id]
        self.attr_types: dict = {}       # (cls, attr) -> class name
        self.attr_types_by_tail: dict = {}  # attr -> set(class name)
        self.seams: list = []

    # ------------------------------------------------------- pass A
    def declare(self):
        for key in sorted(set(THREADED_MODULES)):
            tree = ast.parse(self.sources[key], filename=key)
            mod = _Module(key, tree)
            self.modules[key] = mod
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    mod.classes[node.name] = node
                    self.class_index.setdefault(node.name, key)
                elif isinstance(node, (ast.Import, ast.ImportFrom)):
                    self._declare_import(mod, node)
                elif isinstance(node, ast.Assign):
                    self._declare_module_assign(mod, node)
            # constructor-typed attrs + lock/event/queue decls live in
            # method bodies; a flat walk is enough for declarations.
            for cls in mod.classes.values():
                for sub in ast.walk(cls):
                    if isinstance(sub, ast.Assign):
                        self._declare_self_assign(mod, cls.name, sub)
            for sub in ast.walk(tree):
                if isinstance(sub, ast.Call):
                    chain = _attr_chain(sub.func)
                    if chain and chain[-1] == "join":
                        if len(chain) >= 2:
                            mod.joined_tails.add(chain[-2])
        if FAULTS_MODULE in self.modules:
            self.seams = self._parse_seams(self.modules[FAULTS_MODULE])

    def _declare_import(self, mod: _Module, node):
        if isinstance(node, ast.Import):
            for a in node.names:
                path = a.name.replace(".", "/") + ".py"
                if path in self.sources and (a.asname
                                             or "." not in a.name):
                    mod.aliases[a.asname or a.name] = path
        else:
            base = (node.module or "").replace(".", "/")
            for a in node.names:
                path = f"{base}/{a.name}.py" if base else f"{a.name}.py"
                if path in self.sources:
                    mod.aliases[a.asname or a.name] = path

    def _ctor_name(self, value):
        if not isinstance(value, ast.Call):
            return None
        chain = _attr_chain(value.func)
        return chain[-1] if chain else None

    def _declare_module_assign(self, mod: _Module, node: ast.Assign):
        ctor = self._ctor_name(node.value)
        if ctor is None:
            return
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if ctor in _LOCK_CTORS:
                lock_id = f"{mod.stem}.{tgt.id}"
                mod.module_locks[tgt.id] = _LOCK_CTORS[ctor]
                self.lock_registry[lock_id] = {
                    "kind": _LOCK_CTORS[ctor], "module": mod.key}
                self.locks_by_tail.setdefault(tgt.id, set()).add(lock_id)
            elif ctor in _EVENT_CTORS:
                mod.event_names.add(tgt.id)
            elif ctor in _QUEUE_CTORS:
                mod.queue_names.add(tgt.id)

    def _declare_self_assign(self, mod: _Module, cls: str,
                             node: ast.Assign):
        ctor = self._ctor_name(node.value)
        if ctor is None:
            return
        for tgt in node.targets:
            chain = _attr_chain(tgt)
            if chain is None or len(chain) != 2 or chain[0] != "self":
                continue
            attr = chain[1]
            if ctor in _LOCK_CTORS:
                lock_id = f"{cls}.{attr}"
                self.lock_registry[lock_id] = {
                    "kind": _LOCK_CTORS[ctor], "module": mod.key}
                self.locks_by_tail.setdefault(attr, set()).add(lock_id)
            elif ctor in _EVENT_CTORS:
                mod.event_names.add(attr)
            elif ctor in _QUEUE_CTORS:
                mod.queue_names.add(attr)
            elif ctor in self.class_index:
                self.attr_types[(cls, attr)] = ctor
                self.attr_types_by_tail.setdefault(attr, set()).add(ctor)

    def _parse_seams(self, mod: _Module) -> list:
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "SEAMS":
                        consts = [n.value for n in ast.walk(node.value)
                                  if isinstance(n, ast.Constant)
                                  and isinstance(n.value, str)]
                        return sorted(set(consts))
        return []

    # ------------------------------------------------------- pass B
    def scan(self):
        for key in sorted(self.modules):
            mod = self.modules[key]
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._scan_function(mod, None, node.name, node)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self._scan_function(
                                mod, node.name,
                                f"{node.name}.{sub.name}", sub)

    def _register(self, fn: _Fn):
        self.fn_index[fn.id] = fn
        fn.module.fns.append(fn)
        bare = fn.qual.rsplit(".", 1)[-1]
        self.fns_by_name.setdefault(bare, []).append(fn.id)
        if fn.cls is not None and fn.qual == f"{fn.cls}.{bare}":
            self.methods[(fn.cls, bare)] = fn.id

    def _scan_function(self, mod: _Module, cls, qual, node) -> _Fn:
        fn = _Fn(mod, cls, qual)
        self._register(fn)
        self._visit_stmts(fn, node.body, held=(), loop_iters={})
        return fn

    # -- statement walker (tracks the held-lock stack) --------------
    def _visit_stmts(self, fn: _Fn, stmts, held, loop_iters):
        for st in stmts:
            self._visit_stmt(fn, st, held, loop_iters)

    def _visit_stmt(self, fn: _Fn, st, held, loop_iters):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested_qual = f"{fn.qual}.<locals>.{st.name}"
            nested = self._scan_function(fn.module, fn.cls, nested_qual,
                                         st)
            fn.nested_defs[st.name] = nested.id
            return
        if isinstance(st, ast.Global):
            fn.global_decls.update(st.names)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in st.items:
                self._scan_expr(fn, item.context_expr, held)
                chain = _attr_chain(item.context_expr)
                lock = self._resolve_lock(fn, chain) if chain else None
                if lock is not None:
                    self._note_acquire(fn, lock, held)
                    acquired.append(lock)
            self._visit_stmts(fn, st.body, held + tuple(acquired),
                              loop_iters)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._scan_expr(fn, st.test, held)
            self._visit_stmts(fn, st.body, held, loop_iters)
            self._visit_stmts(fn, st.orelse, held, loop_iters)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(fn, st.iter, held)
            iters = dict(loop_iters)
            it_chain = _attr_chain(st.iter)
            if isinstance(st.target, ast.Name) and it_chain:
                iters[st.target.id] = it_chain[-1]
            self._visit_stmts(fn, st.body, held, iters)
            self._visit_stmts(fn, st.orelse, held, loop_iters)
            return
        if isinstance(st, ast.Try):
            self._visit_stmts(fn, st.body, held, loop_iters)
            for h in st.handlers:
                self._visit_stmts(fn, h.body, held, loop_iters)
            self._visit_stmts(fn, st.orelse, held, loop_iters)
            self._visit_stmts(fn, st.finalbody, held, loop_iters)
            return
        # leaf statement: writes + expression scan
        if isinstance(st, ast.Assign):
            n_sites = len(fn.thread_sites)
            for tgt in st.targets:
                self._note_write_target(fn, tgt, held)
            self._note_typing(fn, st, held)
            self._scan_expr(fn, st.value, held)
            if len(fn.thread_sites) > n_sites:
                tail = None
                if len(st.targets) == 1:
                    chain = _attr_chain(st.targets[0])
                    if chain:
                        tail = chain[-1]
                for site in fn.thread_sites[n_sites:]:
                    site["stored"] = tail
            return
        if isinstance(st, ast.AugAssign):
            self._note_write_target(fn, st.target, held)
            self._scan_expr(fn, st.value, held)
            return
        if isinstance(st, ast.AnnAssign):
            self._note_write_target(fn, st.target, held)
            if st.value is not None:
                self._scan_expr(fn, st.value, held)
            return
        self._scan_expr(fn, st, held, loop_iters)

    def _note_acquire(self, fn: _Fn, lock: str, held):
        fn.acquires.add(lock)
        kind = self.lock_registry.get(lock, {}).get("kind")
        for h in held:
            if h == lock and kind == "RLock":
                continue  # reentrant re-acquire is the point of RLock
            fn.nested_edges.add((h, lock))

    # -- write / read / call collection ------------------------------
    def _attr_id_of_target(self, fn: _Fn, node):
        # self.X  /  self.X[...]  /  global NAME  /  NAME[...]
        if isinstance(node, ast.Subscript):
            node = node.value
        chain = _attr_chain(node)
        if chain is None:
            return None
        if len(chain) == 2 and chain[0] == "self" and fn.cls:
            return f"{fn.cls}.{chain[1]}"
        if len(chain) == 1 and chain[0] in fn.global_decls:
            return f"{fn.module.stem}.{chain[0]}"
        return None

    def _note_write_target(self, fn: _Fn, tgt, held):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._note_write_target(fn, el, held)
            return
        attr_id = self._attr_id_of_target(fn, tgt)
        if attr_id is not None:
            fn.writes.append((attr_id, tuple(sorted(set(held))),
                              fn.is_init))

    def _note_typing(self, fn: _Fn, st: ast.Assign, held):
        values = [st.value]
        if isinstance(st.value, ast.IfExp):
            # stop = stop_event if stop_event is not None else Event()
            values = [st.value.body, st.value.orelse]
        ctors = [c for c in map(self._ctor_name, values)
                 if c is not None]
        for ctor in ctors:
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    if ctor in self.class_index:
                        fn.local_types[tgt.id] = ctor
                    if ctor in _EVENT_CTORS:
                        fn.module.event_names.add(tgt.id)
                    if ctor in _QUEUE_CTORS:
                        fn.module.queue_names.add(tgt.id)

    def _scan_expr(self, fn: _Fn, node, held, loop_iters=None):
        loop_iters = loop_iters or {}
        for sub in _walk_no_defs(node):
            if isinstance(sub, ast.Call):
                self._note_call(fn, sub, held, loop_iters)
            elif isinstance(sub, ast.Attribute) and isinstance(
                    sub.ctx, ast.Load):
                chain = _attr_chain(sub)
                if chain and len(chain) == 2 and chain[0] == "self" \
                        and fn.cls:
                    fn.reads.add(f"{fn.cls}.{chain[1]}")
            elif isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, ast.Load):
                fn.raw_name_reads.add(sub.id)

    def _note_call(self, fn: _Fn, call: ast.Call, held, loop_iters):
        chain = _attr_chain(call.func)
        if chain is None:
            return
        tail = chain[-1]
        # threading.Thread(...) creation sites
        if tail == "Thread" and (len(chain) == 1
                                 or chain[-2] == "threading"):
            self._note_thread_site(fn, call)
            return
        # signal.signal(SIG, handler)
        if chain == ("signal", "signal") and len(call.args) >= 2:
            hchain = _attr_chain(call.args[1])
            if hchain:
                fn.signal_handlers.append(hchain)
            return
        # MetricsExporter(render_fn, ...): the render callback runs on
        # the exporter's daemon HTTP thread — a thread entry point.
        if tail == "MetricsExporter":
            rarg = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "render_fn":
                    rarg = kw.value
            rchain = _attr_chain(rarg) if rarg is not None else None
            if rchain:
                fn.render_fns.append(rchain)
            return
        # cross-thread handoffs
        if tail in _HANDOFF_PUTS and len(chain) >= 2:
            fn.handoffs.append((chain[-2], tail))
        elif tail == "set" and len(chain) >= 2 \
                and chain[-2] in fn.module.event_names:
            fn.handoffs.append((chain[-2], "set"))
        # lock.acquire() outside `with` (no region tracking — the lock
        # still participates in the order graph)
        if tail == "acquire" and len(chain) >= 2:
            lock = self._resolve_lock(fn, chain[:-1])
            if lock is not None:
                self._note_acquire(fn, lock, held)
        # thread joins via loop vars: `for th in self._threads: th.join()`
        if tail == "join" and len(chain) == 2 \
                and chain[0] in loop_iters:
            fn.module.joined_tails.add(loop_iters[chain[0]])
        fn.calls.append((chain, tuple(sorted(set(held)))))

    def _note_thread_site(self, fn: _Fn, call: ast.Call):
        site = {"name": None, "daemon": False, "target": None,
                "stored": None}
        for kw in call.keywords:
            if kw.arg == "name":
                site["name"] = _name_literal(kw.value)
            elif kw.arg == "daemon":
                if isinstance(kw.value, ast.Constant):
                    site["daemon"] = bool(kw.value.value)
            elif kw.arg == "target":
                tchain = _attr_chain(kw.value)
                site["target"] = tchain
        fn.thread_sites.append(site)

    # -- resolution ---------------------------------------------------
    def _resolve_lock(self, fn: _Fn, chain):
        if not chain:
            return None
        tail = chain[-1]
        if len(chain) >= 2 and chain[0] == "self" and fn.cls:
            if len(chain) == 2:
                lock_id = f"{fn.cls}.{tail}"
                if lock_id in self.lock_registry:
                    return lock_id
            else:
                owner = self._type_of_tail(fn, chain[-2])
                if owner:
                    lock_id = f"{owner}.{tail}"
                    if lock_id in self.lock_registry:
                        return lock_id
        if len(chain) == 1:
            if tail in fn.module.module_locks:
                return f"{fn.module.stem}.{tail}"
        if len(chain) >= 2 and chain[0] != "self":
            owner = self._type_of_tail(fn, chain[-2])
            if owner:
                lock_id = f"{owner}.{tail}"
                if lock_id in self.lock_registry:
                    return lock_id
        # globally-unique attribute name, last resort
        cands = self.locks_by_tail.get(tail, set())
        if len(cands) == 1:
            return next(iter(cands))
        return None

    def _type_of_tail(self, fn: _Fn, name):
        if name in fn.local_types:
            return fn.local_types[name]
        if fn.cls and (fn.cls, name) in self.attr_types:
            return self.attr_types[(fn.cls, name)]
        cands = self.attr_types_by_tail.get(name, set())
        if len(cands) == 1:
            return next(iter(cands))
        return None

    def _resolve_call(self, fn: _Fn, chain):
        tail = chain[-1]
        recv = chain[:-1]
        if not recv:
            if tail in fn.nested_defs:
                return fn.nested_defs[tail]
            same = f"{fn.module.key}::{tail}"
            if same in self.fn_index:
                return same
            if tail in self.class_index:  # Cls(...) -> Cls.__init__
                return self.methods.get((tail, "__init__"))
            return None
        if recv == ("self",) and fn.cls:
            hit = self.methods.get((fn.cls, tail))
            if hit:
                return hit
        if len(recv) == 1 and recv[0] in fn.module.aliases:
            target = f"{fn.module.aliases[recv[0]]}::{tail}"
            if target in self.fn_index:
                return target
        owner = self._type_of_tail(fn, recv[-1]) if recv[-1] != "self" \
            else fn.cls
        if owner:
            hit = self.methods.get((owner, tail))
            if hit:
                return hit
        if tail in _GENERIC_METHODS:
            return None  # container-method name: typed receivers only
        cands = self.fns_by_name.get(tail, [])
        if len(cands) == 1:
            return cands[0]
        return None  # ambiguous: skip, never guess

    # -- global analysis ---------------------------------------------
    def resolve_calls(self):
        for fn in self.fn_index.values():
            fn.resolved_calls = []
            fn.union_callees = set()
            for chain, held in fn.calls:
                callee = self._resolve_call(fn, chain)
                if callee is not None:
                    fn.resolved_calls.append((callee, held))
                    continue
                # Reachability (and ONLY reachability) tolerates a
                # small ambiguous fan-out: `obj.render_openmetrics()`
                # through an untyped receiver reaches every definer.
                # Lock-order edges never use these — a missed edge
                # costs coverage, an invented one costs a false
                # deadlock report.
                tail = chain[-1]
                if len(chain) >= 2 and tail not in _GENERIC_METHODS:
                    cands = self.fns_by_name.get(tail, [])
                    if 1 < len(cands) <= 4:
                        fn.union_callees.update(cands)

    def may_acquire(self) -> dict:
        acq = {fid: set(fn.acquires)
               for fid, fn in self.fn_index.items()}
        changed = True
        while changed:
            changed = False
            for fid, fn in self.fn_index.items():
                for callee, _held in fn.resolved_calls:
                    extra = acq.get(callee, set()) - acq[fid]
                    if extra:
                        acq[fid].update(extra)
                        changed = True
        return acq

    def lock_edges(self, acq: dict) -> set:
        edges = set()
        for fn in self.fn_index.values():
            edges.update(fn.nested_edges)
            for callee, held in fn.resolved_calls:
                for h in held:
                    kind = self.lock_registry.get(h, {}).get("kind")
                    for lock in acq.get(callee, ()):
                        if lock == h and kind == "RLock":
                            continue
                        edges.add((h, lock))
        return edges

    def thread_roots(self) -> dict:
        """root label -> set of root fn ids."""
        roots: dict = {}

        def add(label, fid):
            if fid is not None and fid in self.fn_index:
                roots.setdefault(label, set()).add(fid)

        for fn in self.fn_index.values():
            for site in fn.thread_sites:
                target = site["target"]
                fid = self._resolve_call(fn, target) if target else None
                name = site["name"] or (target[-1] if target else "?")
                add(f"thread:{name}", fid)
            for hchain in fn.signal_handlers:
                add(f"signal:{hchain[-1]}",
                    self._resolve_call(fn, hchain))
            for rchain in fn.render_fns:
                add(f"metrics-render:{fn.module.stem}",
                    self._resolve_call(fn, rchain))
            if fn.qual.endswith("__del__") and fn.cls:
                add(f"del:{fn.cls}", fn.id)
        return roots

    def inherited_held(self) -> dict:
        """Called-with-held inference: a function whose EVERY known
        (resolved) call site runs with lock L held counts as executing
        under L — the ``_form_locked`` / ``_drop_ref`` /
        ``_journal_snapshot_locked`` idiom, where the public method
        takes the lock and delegates. Standard optimistic meet: start
        callees at the full lock set, narrow by intersection over
        call sites (each site contributing its literal held set plus
        its caller's own inherited set) until fixpoint. Functions with
        no known callers (public API, thread targets) inherit
        nothing."""
        all_locks = frozenset(self.lock_registry)
        callers: dict = {}
        for fid, fn in self.fn_index.items():
            for callee, held in fn.resolved_calls:
                callers.setdefault(callee, []).append((fid, held))
        inherited = {fid: (all_locks if fid in callers else frozenset())
                     for fid in self.fn_index}
        changed = True
        while changed:
            changed = False
            for fid, sites in callers.items():
                new = None
                for caller, held in sites:
                    eff = frozenset(held) | inherited[caller]
                    new = eff if new is None else (new & eff)
                if new != inherited[fid]:
                    inherited[fid] = new
                    changed = True
        return inherited

    def reachable(self, root_fids) -> set:
        seen = set(root_fids)
        stack = list(root_fids)
        while stack:
            fid = stack.pop()
            fn = self.fn_index[fid]
            nxt = {c for c, _h in fn.resolved_calls}
            nxt.update(fn.union_callees)
            for callee in nxt:
                if callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
        return seen


# ------------------------------------------------------------------
# graph helpers
# ------------------------------------------------------------------
def find_cycles(edges) -> list:
    """Strongly-connected components of size > 1, plus self-loops, as
    deterministic ' -> '-joined strings."""
    graph: dict = {}
    nodes = set()
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    index = {}
    low = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)

    cycles = []
    for scc in sccs:
        if len(scc) > 1:
            cyc = sorted(scc)
            cycles.append(" -> ".join(cyc + [cyc[0]]))
    for a, b in edges:
        if a == b:
            cycles.append(f"{a} -> {a}")
    return sorted(set(cycles))


def topological_order(edges) -> list:
    """Deterministic Kahn order (lexicographic tie-break). Nodes on
    cycles are omitted — the order is only meaningful when the graph
    is acyclic, which the ORDER contract enforces."""
    nodes = set()
    succ: dict = {}
    indeg: dict = {}
    for a, b in edges:
        nodes.add(a)
        nodes.add(b)
        if b not in succ.setdefault(a, set()):
            succ[a].add(b)
            indeg[b] = indeg.get(b, 0) + 1
    ready = sorted(n for n in nodes if indeg.get(n, 0) == 0)
    order = []
    while ready:
        n = ready.pop(0)
        order.append(n)
        for m in sorted(succ.get(n, ())):
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
        ready.sort()
    return order


# ------------------------------------------------------------------
# public entry
# ------------------------------------------------------------------
def load_sources(root=None, overrides=None) -> dict:
    root = Path(root) if root is not None else REPO_ROOT
    sources = {}
    for key in sorted(set(THREADED_MODULES)):
        if overrides and key in overrides:
            sources[key] = overrides[key]
        else:
            sources[key] = (root / key).read_text()
    return sources


def extract_concurrency_facts(root=None, sources=None) -> dict:
    """The four fact families over the threaded modules. ``sources``
    may override module texts (tests inject deliberate mutations
    without touching the tree)."""
    ex = _Extractor(load_sources(root, sources))
    ex.declare()
    ex.scan()
    ex.resolve_calls()
    acq = ex.may_acquire()
    edges = ex.lock_edges(acq)
    roots = ex.thread_roots()

    # ---- guarded_by ----
    root_reach = {label: ex.reachable(fids)
                  for label, fids in roots.items()}
    touched_by: dict = {}
    for fn in ex.fn_index.values():
        touched = set(fn.reads)
        touched.update(a for a, _h, _i in fn.writes)
        # global reads resolve late: a bare-name read of something any
        # function in this module global-writes counts as a touch.
        mod_globals = {a.split(".", 1)[1]
                       for f2 in fn.module.fns
                       for a, _h, _i in f2.writes
                       if a.startswith(f"{fn.module.stem}.")}
        touched.update(f"{fn.module.stem}.{n}"
                       for n in fn.raw_name_reads & mod_globals)
        touched_by[fn.id] = touched

    inherited = ex.inherited_held()
    attr_facts: dict = {}
    for fn in ex.fn_index.values():
        for attr_id, held, is_init in fn.writes:
            eff = frozenset(held) | inherited.get(fn.id, frozenset())
            f = attr_facts.setdefault(attr_id, {
                "locks": set(), "writes_guarded": 0,
                "writes_unguarded": 0, "writes_init": 0,
                "thread_roots": set()})
            if is_init:
                f["writes_init"] += 1
            elif eff:
                f["writes_guarded"] += 1
                f["locks"].update(eff)
            else:
                f["writes_unguarded"] += 1
    for label in sorted(root_reach):
        fids = root_reach[label]
        for fid in fids:
            for attr_id in touched_by.get(fid, ()):
                if attr_id in attr_facts:
                    attr_facts[attr_id]["thread_roots"].add(label)
    guarded_by = {
        "locks": {lid: dict(sorted(meta.items()))
                  for lid, meta in sorted(ex.lock_registry.items())},
        "attrs": {
            a: {"locks": sorted(f["locks"]),
                "writes_guarded": f["writes_guarded"],
                "writes_unguarded": f["writes_unguarded"],
                "writes_init": f["writes_init"],
                "thread_roots": sorted(f["thread_roots"])}
            for a, f in sorted(attr_facts.items())
            if f["writes_guarded"] or f["writes_unguarded"]},
    }

    # ---- lock_order ----
    edge_strs = sorted(f"{a} -> {b}" for a, b in edges)
    lock_order = {
        "edges": edge_strs,
        "cycles": find_cycles(edges),
        "order": topological_order(edges),
    }

    # ---- thread_lifecycle ----
    threads: dict = {}
    for fid in sorted(ex.fn_index):
        fn = ex.fn_index[fid]
        for i, site in enumerate(fn.thread_sites):
            sid = fid if len(fn.thread_sites) == 1 else f"{fid}#{i + 1}"
            name = site["name"]
            threads[sid] = {
                "name": name,
                "named_ok": bool(name) and name.startswith("dpsvm-"),
                "daemon": site["daemon"],
                "joined": bool(site["stored"]
                               and site["stored"]
                               in fn.module.joined_tails),
                "target": ".".join(site["target"] or ("?",)),
            }
    thread_lifecycle = {"threads": threads}

    # ---- seam_coverage ----
    handoffs = sorted({
        f"{fn.module.key}::{fn.qual}::{tail}.{meth}"
        for fn in ex.fn_index.values()
        for tail, meth in fn.handoffs})
    seam_coverage = {"seams": ex.seams, "handoffs": handoffs}

    return {
        "guarded_by": guarded_by,
        "lock_order": lock_order,
        "thread_lifecycle": thread_lifecycle,
        "seam_coverage": seam_coverage,
    }
