"""dpsvm_tpu.testing — deterministic fault-injection harness.

The production seams (solver loops, checkpoint writes, registry
loads, the serving dispatcher) import :mod:`dpsvm_tpu.testing.faults`
lazily at their hook sites; disarmed, every hook is a cheap host-side
no-op with zero HLO effect (the committed tpulint budgets pin that).
"""

from dpsvm_tpu.testing import faults  # noqa: F401

__all__ = ["faults"]
