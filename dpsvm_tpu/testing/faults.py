"""Deterministic fault-injection harness (ISSUE 13).

Every fault-tolerance behavior in this repo is PROVEN by an injected
fault, not hoped for: the retry wrapper, the checkpoint write
discipline, ooc resume, the registry's corrupted-swap refusal, the
serving watchdog and the non-finite demotion path all carry a named
SEAM — a host-side hook this module arms. The old ad-hoc monkeypatch
fault tests (tests/test_fault_recovery.py's ``inject_fault`` fixture)
migrate onto these seams, and the same seams drive ``make
faults_smoke`` and the loadgen chaos leg.

Design constraints, in priority order:

* **Zero HLO effect when disarmed.** Every seam is pure host code on a
  host-driven boundary (a chunk dispatch, a ``device_put``, a
  checkpoint write, an npz load, a scalar observation) — arming or
  disarming the harness can never change a compiled program, which is
  why the committed tpulint budgets stay byte-identical with the
  harness importable everywhere (the PR 6 obs discipline).
* **Deterministic.** A :class:`FaultPlan` fires on exact ARRIVAL
  COUNTS at a seam (the N-th chunk dispatch, the T-th tile put), never
  on wall clock or randomness; byte corruption is seeded so two runs
  of one plan corrupt identically.
* **Cheap when disarmed.** The hot-path check is one module attribute
  read + a truthiness test (``_PLAN`` is None unless a plan is
  installed or ``DPSVM_FAULTS`` is set).

Activation
----------
Programmatic (tests)::

    from dpsvm_tpu.testing import faults
    with faults.install(faults.FaultPlan.parse("dispatch@3")):
        solve(...)

Environment (subprocess / CLI chaos runs)::

    DPSVM_FAULTS="ooc_tile_put@2" python -m dpsvm_tpu.cli train --ooc ...

Spec grammar: comma-separated ``seam[@N][xK]`` — fire on the N-th
arrival at that seam (1-based, default 1) and keep firing for K
consecutive arrivals (default 1). ``DPSVM_FAULTS_SEED`` seeds byte
corruption (default 0).

Seams
-----
=================  ====================================================
``dispatch``       chunk/round dispatch in the single-chip, mesh and
                   ooc host loops raises a transient
                   ``JaxRuntimeError("UNAVAILABLE: ...")`` — the
                   run_with_fault_retry recovery class.
``ooc_tile_put``   the ooc tile stream's host->HBM ``device_put``
                   raises the same transient class at tile-put T.
``ckpt_truncate``  a checkpoint write is truncated mid-save and the
                   writer dies (raises) BEFORE the atomic rename —
                   the preemption the tmp+rename discipline exists
                   for; the previous checkpoint must survive intact.
``swap_corrupt``   a registry model load reads a deterministically
                   corrupted copy of the file — the swap must be
                   refused (ModelLoadError) with the live version
                   still serving.
``serve_dispatch`` a serving bucket dispatch raises — the engine must
                   fail that batch with explicit 'failed' verdicts
                   and keep serving.
``serve_stall``    a serving batch's materialization stalls past the
                   dispatch watchdog (sleeps ``STALL_SECONDS`` in the
                   waiting thread) — the watchdog must bound it.
``nonfinite_obs``  the chunk-boundary host observation reads NaN —
                   the graceful-degradation sentinel's trigger.
``net_accept``     the front door drops an incoming connection at
                   accept time without a frame (accept-queue overflow
                   / SYN drop seen from the client as an immediate
                   EOF) — the connect-retry recovery class.
``net_conn_drop``  a client connection dies mid-flight: the request
                   frame was fully sent, the socket closes before the
                   verdict is read — the server's verdict becomes
                   undeliverable; accounting must still close.
``net_read_stall`` the client stalls ``NET_STALL_SECONDS`` before
                   reading its verdict — a slow reader whose cost the
                   server's bounded writes must contain.
``net_partial_write``  the client writes only HALF a request frame and
                   closes — the server must kill only that connection
                   (truncated-frame accounting), never wedge.
=================  ====================================================

Firing records accumulate on ``plan.fired`` (a Counter) so tests can
assert the fault really happened — a fault test whose fault never
fired proves nothing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
import threading
import time
from collections import Counter
from typing import List, Optional

#: every seam name a spec may arm (typos fail loudly at parse time).
SEAMS = frozenset({
    "dispatch", "ooc_tile_put", "ckpt_truncate", "swap_corrupt",
    "serve_dispatch", "serve_stall", "nonfinite_obs",
    "net_accept", "net_conn_drop", "net_read_stall",
    "net_partial_write", "lock_stall",
})

#: how long a fired ``serve_stall`` sleeps (long enough to trip any
#: sane dispatch watchdog, short enough that the daemon worker thread
#: dies quickly after the test). Tests may monkeypatch.
STALL_SECONDS = 5.0

#: how long a fired ``net_read_stall`` client stalls before reading its
#: verdict (a slow reader, not a dead one — shorter than STALL_SECONDS
#: because the stall rides INSIDE a chaos leg's wall clock; the server
#: must be provably unaffected, so nothing waits on it). Tests and the
#: loadgen chaos leg may monkeypatch.
NET_STALL_SECONDS = 0.5

#: how long a fired ``lock_stall`` holds its caller's lock. The seam
#: is CALLED INSIDE a critical section (ModelRegistry.get), so this
#: bounds seeded lock contention: long enough that every contending
#: thread provably blocks on the lock, short enough that a smoke leg's
#: wall clock stays sane. Tests may monkeypatch.
LOCK_STALL_SECONDS = 0.25

_SPEC_RE = re.compile(r"^(?P<seam>[a-z_]+)(@(?P<at>\d+))?(x(?P<times>\d+))?$")


class FaultInjected(RuntimeError):
    """A non-device injected fault (e.g. the checkpoint-write
    truncation). Device-shaped seams raise jax.errors.JaxRuntimeError
    instead so they exercise the REAL recovery classification."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed seam: fire on arrivals [at, at + times)."""

    seam: str
    at: int = 1
    times: int = 1

    def __post_init__(self):
        if self.seam not in SEAMS:
            raise ValueError(
                f"unknown fault seam {self.seam!r} (have "
                f"{sorted(SEAMS)})")
        if self.at < 1 or self.times < 1:
            raise ValueError(
                f"fault spec {self.seam}@{self.at}x{self.times}: "
                "@N and xK must be >= 1 (arrivals are 1-based)")

    def covers(self, arrival: int) -> bool:
        return self.at <= arrival < self.at + self.times


class FaultPlan:
    """A deterministic set of armed seams with per-seam arrival
    counters. Thread-safe: serving seams fire from pump/admin threads
    concurrently."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self.arrivals: Counter = Counter()
        self.fired: Counter = Counter()
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """Parse the ``DPSVM_FAULTS`` grammar: comma-separated
        ``seam[@N][xK]`` tokens."""
        specs = []
        for tok in (t.strip() for t in (text or "").split(",")):
            if not tok:
                continue
            m = _SPEC_RE.match(tok)
            if m is None:
                raise ValueError(
                    f"bad fault spec {tok!r} (grammar: seam[@N][xK], "
                    f"seams: {sorted(SEAMS)})")
            specs.append(FaultSpec(
                seam=m.group("seam"),
                at=int(m.group("at") or 1),
                times=int(m.group("times") or 1)))
        return cls(specs, seed=seed)

    @property
    def armed(self) -> bool:
        return bool(self.specs)

    def arrive(self, seam: str) -> bool:
        """Count one arrival at `seam`; True when an armed spec covers
        this arrival (the caller then injects its fault)."""
        with self._lock:
            self.arrivals[seam] += 1
            n = self.arrivals[seam]
            hit = any(s.seam == seam and s.covers(n) for s in self.specs)
            if hit:
                self.fired[seam] += 1
            return hit


# ------------------------------------------------------- active plan
# _PLAN is the installed plan (tests); _ENV_CACHE memoizes the parsed
# DPSVM_FAULTS value so the disarmed hot path is one env read + a
# string compare.
_PLAN: Optional[FaultPlan] = None
_ENV_CACHE: tuple = ("", None)  # (env string, FaultPlan | None)
# Guards writes to the two module globals above. arrive() runs on pump
# and watchdog threads; the lock keeps a racing first-touch env parse
# single-flight (threadlint guarded-by contract: faults._PLAN and
# faults._ENV_CACHE are protected by faults._plan_lock). The disarmed
# hot path stays lock-free — a plain tuple read.
_plan_lock = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    """The armed plan, or None (the overwhelmingly common case)."""
    global _ENV_CACHE
    if _PLAN is not None:
        return _PLAN if _PLAN.armed else None
    env = os.environ.get("DPSVM_FAULTS", "")
    if not env:
        return None
    if env != _ENV_CACHE[0]:
        seed = int(os.environ.get("DPSVM_FAULTS_SEED", "0"))
        with _plan_lock:
            if env != _ENV_CACHE[0]:  # single-flight parse
                _ENV_CACHE = (env, FaultPlan.parse(env, seed=seed))
    return _ENV_CACHE[1]


@contextlib.contextmanager
def install(plan: Optional[FaultPlan]):
    """Install `plan` as the process-wide active plan for the scope
    (tests). Nesting replaces; exit restores the previous plan."""
    global _PLAN
    with _plan_lock:
        prev = _PLAN
        _PLAN = plan
    try:
        yield plan
    finally:
        with _plan_lock:
            _PLAN = prev


def arrive(seam: str) -> bool:
    """The universal seam check: False-fast when nothing is armed."""
    plan = active_plan()
    return plan is not None and plan.arrive(seam)


# ------------------------------------------------------- seam actions

def device_fault(seam: str, detail: str = "") -> None:
    """Raise the transient device-runtime fault class when `seam`
    fires (the exact classification run_with_fault_retry retries:
    UNAVAILABLE is the tunneled-runtime preemption marker)."""
    if arrive(seam):
        import jax

        raise jax.errors.JaxRuntimeError(
            f"UNAVAILABLE: injected fault at seam {seam!r}"
            + (f" ({detail})" if detail else ""))


def damage_checkpoint(tmp_path: str) -> None:
    """The ``ckpt_truncate`` seam: truncate the staged tmp file to half
    its bytes and die before the atomic rename — exactly what a
    preemption mid-save leaves behind. The save_checkpoint except path
    must unlink the wreck and leave the previous checkpoint intact."""
    if arrive("ckpt_truncate"):
        size = os.path.getsize(tmp_path)
        with open(tmp_path, "r+b") as fh:
            fh.truncate(size // 2)
        raise FaultInjected(
            f"injected preemption mid-checkpoint-save ({tmp_path}: "
            f"{size} -> {size // 2} bytes, rename never ran)")


def corrupt_bytes(data: bytes, seed: int = 0,
                  mode: str = "truncate") -> bytes:
    """Deterministically corrupt an npz payload. ``truncate`` cuts the
    byte stream inside the member data (a partial copy / killed
    writer); ``flip`` XORs a seeded sample of bytes past the zip local
    header (bit rot / torn write). Same (data, seed, mode) -> same
    output, always != input for len(data) > 64."""
    import numpy as np

    if mode == "truncate":
        # Keep the zip local-file header so np.load starts parsing and
        # fails INSIDE a member read — the lazy-decompression case the
        # registry's eager validation exists for.
        return data[:max(64, int(len(data) * 0.6))]
    if mode == "flip":
        rng = np.random.default_rng(seed)
        arr = np.frombuffer(data, np.uint8).copy()
        # Flip past the zip local header when the payload is big enough
        # to have one worth preserving; tiny payloads flip anywhere
        # (the != guarantee only holds above 64 bytes either way).
        lo = 64 if len(arr) > 64 else 0
        idx = rng.integers(lo, max(lo + 1, len(arr)),
                           size=min(32, max(1, len(arr))))
        idx = idx[idx < len(arr)]
        arr[idx] ^= 0xFF
        return arr.tobytes()
    raise ValueError(f"unknown corruption mode {mode!r}")


def corrupt_npz_file(src: str, dst: Optional[str] = None,
                     seed: int = 0, mode: str = "truncate") -> str:
    """Write a deterministically corrupted copy of `src` (the chaos
    legs' bad-swap input). Returns the written path."""
    with open(src, "rb") as fh:
        data = fh.read()
    if dst is None:
        root, ext = os.path.splitext(src)
        dst = f"{root}.corrupt{ext or '.npz'}"
    bad = corrupt_bytes(data, seed=seed, mode=mode)
    with open(dst, "wb") as fh:
        fh.write(bad)
    return dst


def maybe_corrupt_model(path: str) -> str:
    """The ``swap_corrupt`` seam: when fired, the registry load reads a
    corrupted sibling copy instead of `path`, so the REAL
    validate/reject path is what gets exercised (never a mocked
    error). Returns `path` unchanged when disarmed."""
    if not isinstance(path, str) or not arrive("swap_corrupt"):
        return path
    plan = active_plan()
    seed = plan.seed if plan is not None else 0
    import tempfile

    dst = os.path.join(tempfile.mkdtemp(prefix="dpsvm_fault_"),
                       os.path.basename(path))
    return corrupt_npz_file(path, dst, seed=seed)


def poison_obs(b_hi: float, b_lo: float):
    """The ``nonfinite_obs`` seam: the chunk-boundary host observation
    reads NaN — what a numerics blowup in the carried gradient looks
    like from the host. Identity when disarmed."""
    if arrive("nonfinite_obs"):
        return float("nan"), float("nan")
    return b_hi, b_lo


def serve_stall() -> None:
    """The ``serve_stall`` seam: called from the dispatcher's WAITING
    thread (never the pump thread) so a fired stall models a wedged
    device dispatch the watchdog must bound."""
    if arrive("serve_stall"):
        time.sleep(STALL_SECONDS)


def lock_stall() -> None:
    """The ``lock_stall`` seam: seeded lock CONTENTION. It is called
    inside ModelRegistry.get's critical section, so a fired stall
    holds ModelRegistry._lock for ``LOCK_STALL_SECONDS`` while every
    other registry caller (submits routing a model, an admin thread
    preparing a swap, a scrape labelling queue depth) blocks on the
    lock. The dynamic companion of threadlint's static ORDER contract:
    with the committed acquired-while-holding graph acyclic, a held
    lock can delay the fabric but never wedge it — the faults_smoke
    leg pins exactly that (bounded wall clock, no failed verdicts)."""
    if arrive("lock_stall"):
        time.sleep(LOCK_STALL_SECONDS)


# The network seams (ISSUE 15). net_accept fires in the SERVER's accept
# path; the other three fire in the CLIENT (serving/client.py), because
# the behaviors they model — a killed connection, a slow reader, a
# truncated send — are things the wire does TO the server: arming them
# in the client exercises the server's real read/write/accounting
# paths, never a mock.

def net_accept_drop() -> bool:
    """True when the ``net_accept`` seam fires: the server drops this
    incoming connection without a frame."""
    return arrive("net_accept")


def net_conn_drop() -> bool:
    """True when the ``net_conn_drop`` seam fires: the client must
    close its socket after the send, before reading the verdict."""
    return arrive("net_conn_drop")


def net_partial_write() -> bool:
    """True when the ``net_partial_write`` seam fires: the client must
    send only half the frame bytes and close."""
    return arrive("net_partial_write")


def net_read_stall() -> None:
    """The ``net_read_stall`` seam: the client sleeps before reading
    its verdict — a slow reader the server must not block on."""
    if arrive("net_read_stall"):
        time.sleep(NET_STALL_SECONDS)
