"""Command-line entry points: ``svmtrain`` and ``svmtest``.

Flag-compatible with the reference CLI so its run recipes port directly:

* train flags mirror svmTrainMain.cpp:46-58 (-a/--num-att, -x/--num-ex,
  -c/--cost, -g/--gamma, -e/--epsilon, -n/--max-iter, -f/--file-path,
  -m/--model, -s/--cache-size), with the reference's required-shape flags
  made OPTIONAL (shapes are inferred from the file — the improvement
  SURVEY.md section 5.6 calls for). Defaults match (eps 0.001, C 1,
  max-iter 150000) except gamma, where the reference's default is the
  integer-division bug B1 (always 0); ours is 1/num_features.
* test flags mirror seq_test.cpp:54-62 (-a, -x, -g, -f, -m).
* ``mpirun -np N ./svmTrain`` becomes ``svmtrain --num-devices N`` (or no
  flag: every visible device) — one process drives the whole mesh.

Usage:
    python -m dpsvm_tpu.cli train -f train.csv -m model.txt -c 10 -g 0.125
    python -m dpsvm_tpu.cli test  -f test.csv  -m model.txt
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def _build_train_parser(sub) -> argparse.ArgumentParser:
    p = sub.add_parser("train", help="train an SVM with modified SMO")
    p.add_argument("-f", "--file-path", required=True,
                   help="training data: reference CSV (label,f1,...,fd) or "
                        "sparse LIBSVM format (label idx:val ...)")
    p.add_argument("--format", choices=["auto", "csv", "libsvm"],
                   default="auto",
                   help="input format (default auto: LIBSVM rows are "
                        "recognized by their idx:val tokens)")
    p.add_argument("-m", "--model", required=True, help="output model path (.txt or .npz)")
    # LibSVM's -s svm_type role (the reference trains C-SVC only).
    p.add_argument("-t", "--svm-type", default="c-svc",
                   choices=["c-svc", "nu-svc", "eps-svr", "nu-svr", "one-class"],
                   help="problem type (default c-svc; svr/one-class models "
                        "save as .npz)")
    p.add_argument("--nu", type=float, default=0.5,
                   help="nu for nu-svc / nu-svr / one-class (default 0.5)")
    p.add_argument("-p", "--svr-epsilon", type=float, default=0.1,
                   help="epsilon-SVR tube width (LibSVM -p; default 0.1)")
    p.add_argument("-a", "--num-att", type=int, default=None,
                   help="number of features (inferred from file if omitted)")
    p.add_argument("-x", "--num-ex", type=int, default=None,
                   help="number of training examples (inferred if omitted)")
    p.add_argument("-c", "--cost", type=float, default=1.0, help="C parameter (default 1)")
    p.add_argument("-g", "--gamma", type=float, default=None,
                   help="RBF gamma (default 1/num_features)")
    p.add_argument("-e", "--epsilon", type=float, default=1e-3,
                   help="convergence tolerance (default 0.001)")
    p.add_argument("-n", "--max-iter", type=int, default=150_000)
    p.add_argument("-s", "--cache-size", type=int, default=0,
                   help="kernel-row cache lines per device (default 0 = off; "
                        "on the MXU a fresh kernel-row matvec is cheaper than "
                        "the cache bookkeeping — see SVMConfig.cache_lines)")
    p.add_argument("--kernel",
                   choices=["rbf", "linear", "poly", "sigmoid",
                            "precomputed"],
                   default="rbf",
                   help="kernel family (precomputed = LibSVM -t 4: the "
                        "training file's feature columns ARE the square "
                        "(n, n) Gram matrix; the model saves SV indices "
                        "as .npz, and the test file must hold "
                        "K(test, train) rows)")
    p.add_argument("--selection", choices=["mvp", "second_order"], default="mvp",
                   help="working-set rule: mvp = reference-parity maximal "
                        "violating pair; second_order = LibSVM-style WSS2")
    p.add_argument("--engine", choices=["xla", "pallas", "block"], default="xla",
                   help="single-chip compute engine (pallas = fused "
                        "update+select TPU kernel; block = blockwise "
                        "decomposition with on-core subproblem solve — "
                        "the fastest path)")
    p.add_argument("--working-set-size", type=int, default=128,
                   help="block engine: working-set height q (default 128)")
    p.add_argument("--inner-iters", type=int, default=0,
                   help="block engine: pair updates per block "
                        "(default 0 = working-set-size)")
    p.add_argument("--pair-batch", type=int, default=1,
                   choices=[1, 2, 4, 8],
                   help="pair updates per inner-loop trip (mvp only — "
                        "see SVMConfig.pair_batch). 2/4 batch the block "
                        "subproblem's disjoint stale-ranked pairs; on "
                        "--engine xla, 2/4/8 select the micro-batched "
                        "per-pair executor (8 is xla-only)")
    p.add_argument("--fleet-size", type=int, default=16,
                   help="multiclass submodels trained per batched fleet "
                        "dispatch sequence (solver/fleet.py; power of "
                        "two, 1 = sequential solves; applies to the "
                        "OvR/OvO reduction on a single chip)")
    p.add_argument("--fused-round", choices=["auto", "on", "off"],
                   default="auto",
                   help="block engine: ONE-HBM-pass round body — the "
                        "working-set gather, (q,n) kernel rows and "
                        "(q,q) Gram block ride one Pallas streaming "
                        "pass over X, and the fold contraction + next-"
                        "round selection one pass over f "
                        "(SVMConfig.fused_round; ops/pallas_round.py). "
                        "Bit-identical trajectories to the fused-fold "
                        "engine. auto = the measured gate (solver/"
                        "block.py fused_round_pays, currently off)")
    p.add_argument("--pipeline-rounds", choices=["auto", "on", "off"],
                   default="auto",
                   help="block engine: software-pipeline the rounds — "
                        "next round's selection/gather/Gram issued from "
                        "the pre-fold gradient, overlapping the serial "
                        "subproblem chain (stale selection, exact "
                        "updates; SVMConfig.pipeline_rounds). auto = "
                        "the measured gate (solver/block.py "
                        "pipeline_pays)")
    p.add_argument("--local-working-sets", type=int, default=0,
                   help="mesh block engine: 0 = auto (measured gate, "
                        "currently off), 1 = one global working set per "
                        "round (the exact current engine), >= 2 = shard-"
                        "parallel working sets — every chip solves a "
                        "subproblem selected from its OWN shard "
                        "concurrently, reconciling at syncs, with an "
                        "automatic endgame demotion to the exact global "
                        "runner (SVMConfig.local_working_sets)")
    p.add_argument("--sync-rounds", type=int, default=1,
                   help="shard-parallel working sets: local select/"
                        "solve/fold rounds between cross-shard syncs "
                        "(Cascade-style; needs --local-working-sets "
                        ">= 2; default 1)")
    p.add_argument("--ring-exchange", choices=["auto", "on", "off"],
                   default="auto",
                   help="mesh block engine: route the per-round/per-"
                        "window candidate exchange through a Pallas "
                        "ICI ring of remote DMAs instead of all_gather "
                        "+ psum — bit-identical trajectories, zero XLA "
                        "collectives in the device-form round body "
                        "(SVMConfig.ring_exchange; ops/ring.py). auto "
                        "= the measured gate (solver/block.py "
                        "ring_pays, currently off)")
    p.add_argument("--bf16-gram", action="store_true",
                   help="store X in bfloat16 (f32 MXU accumulation — "
                        "half the Gram-pass HBM reads) ONLY when the "
                        "per-problem perturbation bound accepts "
                        "(C * p90|dK| <= 0.1); refusals stay float32 "
                        "and say so loudly in stats "
                        "(SVMConfig.bf16_gram)")
    p.add_argument("--ooc", action="store_true",
                   help="out-of-core training (block engine): X stays "
                        "in HOST memory and the per-round gradient fold "
                        "streams over double-buffered host->HBM tiles, "
                        "so trainable n is bounded by host memory, not "
                        "HBM (SVMConfig.ooc; solver/ooc.py)")
    p.add_argument("--ooc-tile-rows", type=int, default=8192,
                   help="--ooc: rows per streamed X tile (the H2D "
                        "double-buffer unit; default 8192)")
    p.add_argument("--ooc-cache-lines", type=int, default=0,
                   help="--ooc: lines of the HBM kernel-dot-row cache "
                        "keyed by training-row index (scatter-refresh "
                        "LRU; a round whose whole working set hits "
                        "skips the tile stream entirely). 0 = off; "
                        "must be >= --working-set-size")
    p.add_argument("--ooc-shrink", choices=["auto", "on", "off"],
                   default="auto",
                   help="--ooc: shrunken tile stream — in-cycle rounds "
                        "keep a static-shape active view of the m "
                        "most-violating rows and stream ONLY the tiles "
                        "intersecting it, with periodic full-stream "
                        "reconstruction + endgame demotion so the final "
                        "model meets the unshrunken convergence "
                        "criterion (SVMConfig.ooc_shrink; auto = the "
                        "autotune 'ooc_shrink' gate decides; single-"
                        "chip only)")
    p.add_argument("--active-set-size", type=int, default=0,
                   help="block engine: shrink per-round work to the m "
                        "most-violating rows, reconciling the full "
                        "gradient in batches (0 = off; single-chip, "
                        "mesh, and single-chip --ooc, where m sizes the "
                        "shrunken tile stream's active view)")
    p.add_argument("--reconcile-rounds", type=int, default=8,
                   help="block engine shrinking: rounds between full-"
                        "gradient reconciliations (default 8)")
    p.add_argument("--degree", type=int, default=3)
    p.add_argument("--coef0", type=float, default=0.0)
    p.add_argument("-b", "--probability", type=int, choices=[0, 1],
                   default=0,
                   help="1 = fit Platt probability calibration on the "
                        "training decision values after training (LibSVM "
                        "-b; c-svc/nu-svc only; the model saves as .npz "
                        "— the reference text format cannot carry it)")
    p.add_argument("--multiclass", choices=["ovr", "ovo"], default="ovr",
                   help="reduction for >2-class (or non-±1-labelled) "
                        "training files: one-vs-rest (k models) or "
                        "LibSVM-style one-vs-one pairwise voting "
                        "(k(k-1)/2 models); c-svc only, model saves as "
                        ".npz")
    p.add_argument("-w1", "--weight-pos", type=float, default=1.0,
                   help="C multiplier for the +1 class (LibSVM -w1)")
    p.add_argument("-w-1", "--weight-neg", type=float, default=1.0,
                   dest="weight_neg",
                   help="C multiplier for the -1 class (LibSVM -w-1)")
    p.add_argument("--backend",
                   choices=["auto", "single", "mesh", "reference", "native"],
                   default="auto")
    p.add_argument("--num-devices", type=int, default=None,
                   help="devices in the data mesh (default: all visible)")
    # Multi-host bring-up (the reference's `mpirun --hostfile hf` role,
    # Makefile:74): every host runs the same command with its own
    # --process-id; jax.distributed wires the DCN coordination.
    p.add_argument("--coordinator-address", default=None,
                   help="host:port of process 0 for multi-host pods "
                        "(enables jax.distributed.initialize)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total processes in the multi-host job")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's index in the multi-host job")
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32",
                   help="X storage dtype (bfloat16 halves kernel-row bandwidth)")
    p.add_argument("--chunk-iters", type=int, default=2048)
    p.add_argument("--checkpoint", default=None, help="solver checkpoint path")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="iterations between checkpoints (0 = off)")
    p.add_argument("--checkpoint-keep", type=int, default=1,
                   help="rotating checkpoint generations to keep "
                        "(path, path.1, ...): a checkpoint corrupted "
                        "by the very fault being recovered from still "
                        "leaves an older restorable one; --resume "
                        "falls back to the newest loadable generation "
                        "with a loud warning (default 1 = overwrite "
                        "in place)")
    p.add_argument("--retry-faults", type=int, default=2,
                   help="automatic retries on transient device faults, "
                        "resuming from --checkpoint when set (default 2; "
                        "use 0 on multi-host pods and relaunch with "
                        "--resume instead)")
    p.add_argument("--resume", action="store_true",
                   help="resume from --checkpoint if it exists")
    p.add_argument("--metrics-jsonl", default=None,
                   help="write per-chunk metrics records to this JSONL file")
    p.add_argument("--trace-dir", "--profile-dir", dest="profile_dir",
                   default=None,
                   help="capture a jax.profiler device trace (Perfetto/"
                        "XPlane) into this directory; with --obs the "
                        "solver owns the capture and its spans appear "
                        "named in it")
    p.add_argument("--obs", action="store_true",
                   help="enable the telemetry spine (dpsvm_tpu/obs): "
                        "a schema-versioned JSONL run log (manifest/"
                        "chunk/event/span/final records), registry "
                        "metrics and trace spans. Zero device effect — "
                        "chunk cadence, dispatches and compiled HLO "
                        "are unchanged (tpulint-pinned)")
    p.add_argument("--obs-dir", default=None,
                   help="run-log directory for --obs (default obs_runs; "
                        "env DPSVM_OBS_DIR)")
    p.add_argument("-v", "--cross-validate", type=int, default=0,
                   metavar="N",
                   help="LibSVM svm-train -v: N-fold cross-validation "
                        "(N >= 2) — prints held-out accuracy "
                        "(classifiers) or MSE + squared correlation "
                        "(SVR) and writes NO model file")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def _build_test_parser(sub) -> argparse.ArgumentParser:
    p = sub.add_parser("test", help="evaluate a trained model on a CSV")
    p.add_argument("-f", "--file-path", required=True,
                   help="test data (CSV or sparse LIBSVM format)")
    p.add_argument("--format", choices=["auto", "csv", "libsvm"],
                   default="auto",
                   help="input format (default auto: LIBSVM rows are "
                        "recognized by their idx:val tokens)")
    p.add_argument("-m", "--model", required=True, help="model path (.txt or .npz)")
    p.add_argument("-a", "--num-att", type=int, default=None)
    p.add_argument("-x", "--num-ex", type=int, default=None)
    p.add_argument("-g", "--gamma", type=float, default=None,
                   help="override the model file's gamma")
    p.add_argument("-b", "--probability", type=int, choices=[0, 1],
                   default=0,
                   help="1 = report calibrated-probability metrics "
                        "(model must have been trained with -b 1)")
    p.add_argument("-o", "--output", default=None,
                   help="write per-row predictions here, one per line "
                        "(labels for classifiers/one-class/precomputed, "
                        "values for SVR; with -b 1: 'label p(+1)' with "
                        "the label from p >= 0.5, LibSVM svm-predict "
                        "-b 1 style)")
    p.add_argument("--precision", choices=["auto", "float32", "float64"],
                   default="auto",
                   help="binary decision evaluation precision (default "
                        "auto: consult predict.decision_risk and route "
                        "extreme-|coef| models to the exact host float64 "
                        "path — the PARITY.md 59%%-sign-agreement footgun "
                        "made opt-out; float32 forces the device path)")
    return p


def _build_serve_parser(sub) -> argparse.ArgumentParser:
    p = sub.add_parser(
        "serve",
        help="persistent prediction server (compacted SV union resident "
             "on device, bucketed micro-batching; serve.py)")
    p.add_argument("-m", "--model", default=None,
                   help="model path (.npz multiclass bundle or binary "
                        "model, .txt binary); v1 single-model server — "
                        "use --registry for the v2 multi-model engine")
    p.add_argument("--registry", action="append", metavar="NAME=PATH",
                   default=None,
                   help="register NAME -> model file on the v2 serving "
                        "engine (dpsvm_tpu/serving: model registry "
                        "with zero-downtime hot swap, deadline-aware "
                        "continuous batching, async dispatch); "
                        "repeatable. stdin rows may prefix 'NAME|' to "
                        "route; a line 'swap NAME=PATH' hot-swaps a "
                        "model mid-stream")
    p.add_argument("--listen", default=None, metavar="HOST:PORT",
                   help="v2 engine: NETWORK FRONT DOOR (ISSUE 15) — "
                        "serve the length-prefixed binary frame "
                        "protocol (dpsvm_tpu/serving/wire.py) on this "
                        "TCP endpoint instead of stdin: persistent "
                        "connections, client deadline budgets "
                        "propagated into the EDF scheduler, admission "
                        "rejects with retry hints, per-connection "
                        "read/write bounds; SIGTERM performs a "
                        "graceful drain (finish or shed in-flight "
                        "work by its own deadline, flush verdicts, "
                        "GOODBYE, close). Port 0 = ephemeral, printed "
                        "at startup")
    p.add_argument("--replicas", type=int, default=1,
                   help="--listen: run N v2 engine replicas behind "
                        "the one front door (ISSUE 16 ReplicaFleet): "
                        "per-replica pump threads route the shared "
                        "inbox to whichever replica has room, "
                        "register/swap apply to every replica in "
                        "lockstep over the shared --journal, and "
                        "replicas drain individually for rolling "
                        "restarts (default 1)")
    p.add_argument("--admission-max-rows", type=int, default=None,
                   help="--listen: queued-row saturation bound — a "
                        "request arriving past it is REJECTED "
                        "immediately with a retry_after_ms hint "
                        "instead of buffered (default: max_pending)")
    p.add_argument("--conn-timeout-ms", type=float, default=None,
                   help="--listen: per-connection read AND write "
                        "timeout override (read bounds slow-loris / "
                        "half-open peers, write bounds stalled "
                        "readers; defaults 30000/10000)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="v2 engine: default per-request deadline — "
                        "requests finishing past it count as deadline "
                        "misses, requests expiring in queue are shed "
                        "with an explicit verdict (default: none)")
    p.add_argument("--dispatch-timeout-ms", type=float, default=None,
                   help="v2 engine: dispatch WATCHDOG — a batch not "
                        "materialized within this bound is failed "
                        "with explicit per-request 'failed' verdicts "
                        "(per-model serve_dispatch_failures counter) "
                        "and the engine keeps serving; a wedged "
                        "device dispatch can never hang the pump "
                        "(default: unbounded)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="v2 engine: registry journal — atomically "
                        "rewritten on every register/swap with the "
                        "live {name -> model path + version} set; a "
                        "restarted engine pointed at the same journal "
                        "REPLAYS it through the normal validate-stage-"
                        "warm path and serves the exact pre-crash "
                        "model set (default: no journal)")
    p.add_argument("--buckets", default="16,64,256,1024,4096",
                   help="comma-separated power-of-two query buckets "
                        "(pre-compiled at startup), or 'auto' to "
                        "resolve through the DeviceProfile "
                        "serve_buckets verdict: the default ladder, "
                        "with the engine's occupancy-driven "
                        "suggestion auto-applied between legs only "
                        "where the profile measured that right-"
                        "sizing pays on this device")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32",
                   help="legacy SV-union storage dtype (subsumed by "
                        "--union-storage, which wins when given)")
    p.add_argument("--union-storage",
                   choices=["f32", "bf16", "int8", "auto"],
                   default=None,
                   help="SV-union storage: f32; bf16 (half footprint, "
                        "f32 accumulation, warn-if-risky); int8 "
                        "(calibrated per-row symmetric quantization, "
                        "~4x footprint cut, int8 MXU dot with f32 "
                        "dequant — REFUSED with a loud warning and a "
                        "wider fallback when the calibrated "
                        "perturbation bound rejects this model); "
                        "auto (narrowest storage the bound accepts, "
                        "silent). Default: derived from --dtype")
    p.add_argument("--precision", choices=["auto", "float32", "float64"],
                   default="auto",
                   help="per-submodel evaluation routing (auto = "
                        "decision_risk-gated host float64 for extreme-"
                        "|coef| submodels)")
    p.add_argument("--num-devices", type=int, default=1,
                   help="shard the SV union over this many devices "
                        "(psum-combined partial columns; default 1)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve an OpenMetrics/Prometheus text endpoint "
                        "(GET /metrics) on this port: counters, "
                        "latency summaries, SLO attainment, compile "
                        "count (0 = ephemeral port, printed at "
                        "startup; default: no endpoint)")
    p.add_argument("--metrics-host", default="127.0.0.1",
                   help="bind address for --metrics-port (default "
                        "loopback — the endpoint is plaintext and "
                        "unauthenticated; 0.0.0.0 exposes it to "
                        "remote Prometheus scrapes)")
    p.add_argument("--slo-ms", type=float, default=50.0,
                   help="request-latency objective for the exported "
                        "serve_slo_attainment gauge (default 50 ms)")
    p.add_argument("--server-bench", action="store_true",
                   help="run the offered-load micro-benchmark (through-"
                        "put + p50/p95/p99 latency per bucket) instead "
                        "of serving stdin")
    p.add_argument("--requests", type=int, default=512,
                   help="--server-bench: number of requests (default 512)")
    p.add_argument("--request-sizes", default="1,2,4,8,16,32,64,128",
                   help="--server-bench: comma list request row counts "
                        "are drawn from")
    p.add_argument("--group", type=int, default=8,
                   help="--server-bench: requests arriving together "
                        "(shared flush dispatches; default 8)")
    p.add_argument("--obs", action="store_true",
                   help="enable the telemetry spine: a serve run log "
                        "(manifest + final histogram snapshot JSONL) "
                        "and trace spans around bucket dispatches")
    p.add_argument("--obs-dir", default=None,
                   help="run-log directory for --obs (default obs_runs; "
                        "env DPSVM_OBS_DIR)")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def _build_lint_parser(sub) -> argparse.ArgumentParser:
    # Listed here only so `dpsvm-tpu --help` shows the subcommand;
    # main() forwards `lint ...` argv verbatim to the ONE flag
    # definition (dpsvm_tpu/analysis/budget.run_lint, the same parser
    # behind `python -m tools.tpulint`) before this parser ever runs.
    return sub.add_parser(
        "lint", add_help=False,
        help="tpulint: static HLO/jaxpr contract check of the hot-"
             "entrypoint manifest against committed budgets "
             "(dpsvm_tpu/analysis; no TPU needed; flags as in "
             "`python -m tools.tpulint --help`; add --threads for "
             "the threadlint concurrency contracts)")


def _build_obs_parser(sub) -> argparse.ArgumentParser:
    # Same forwarding pattern as `lint`: main() hands `obs ...` argv
    # verbatim to dpsvm_tpu/obs/analyze.run_cli — one flag surface.
    return sub.add_parser(
        "obs", add_help=False,
        help="runlog analytics (dpsvm_tpu/obs/analyze): `obs report "
             "<paths>` aggregates run summaries (--md for CI job "
             "summaries), `obs diff A B` attributes a regression to "
             "the phase that moved, `obs tail <path>` shows the last "
             "records of a stream; no jax or device needed")


def _build_autotune_parser(sub) -> argparse.ArgumentParser:
    # Same forwarding pattern as `lint`/`obs`: main() hands
    # `autotune ...` argv verbatim to dpsvm_tpu/autotune.run_cli —
    # one flag surface.
    return sub.add_parser(
        "autotune", add_help=False,
        help="measured device profiling for the solver's auto gates "
             "(dpsvm_tpu/autotune): `autotune run` probes this device "
             "kind and persists a committed DeviceProfile JSON (the "
             "make autotune target), `autotune show` prints the "
             "active profile + decisions, `autotune diff A B` "
             "compares two profiles; flags as in `python -m "
             "dpsvm_tpu.cli autotune run --help`")


def _build_learn_parser(sub):
    # Forwarding stub only (the lint/obs/autotune discipline): main()
    # hands the `learn ...` argv verbatim to dpsvm_tpu/learn.run_cli —
    # one flag surface.
    return sub.add_parser(
        "learn", add_help=False,
        help="continuous-learning loop (dpsvm_tpu/learn): ingest a row "
             "stream, retrain each increment warm-started from the "
             "previous generation's support vectors "
             "(solver/cascade.py), and publish every refreshed "
             "generation into a live serving registry via hot swap; "
             "`learn --smoke` is the CI shape; flags as in `python -m "
             "dpsvm_tpu.cli learn --help`")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["learn"]:
        # Forwarded verbatim so `cli learn` and the library surface
        # share one flag set (learn._build_parser owns the flags).
        from dpsvm_tpu.learn import run_cli

        return run_cli(argv[1:])
    if argv[:1] == ["autotune"]:
        # Forwarded verbatim (the lint/obs discipline) so `cli
        # autotune` and the library surface share one flag set.
        from dpsvm_tpu.autotune import run_cli

        return run_cli(argv[1:])
    if argv[:1] == ["lint"]:
        # Forward verbatim so `cli lint` and `python -m tools.tpulint`
        # share one flag surface (budget.run_lint's parser) — no
        # re-declared flags to drift out of sync. `--threads` flips to
        # the threadlint surface (concurrency contracts), same as the
        # tools entrypoint.
        rest = argv[1:]
        if "--threads" in rest:
            from dpsvm_tpu.analysis.threadlint import run_threadlint

            rest.remove("--threads")
            return run_threadlint(rest)
        from dpsvm_tpu.analysis.budget import run_lint

        return run_lint(rest)
    if argv[:1] == ["obs"]:
        # Same forwarding discipline for the runlog-analytics surface
        # (dpsvm_tpu/obs/analyze.run_cli owns the flags). Pure JSONL
        # reader — no jax import, so it works without a backend.
        from dpsvm_tpu.obs.analyze import run_cli

        return run_cli(argv[1:])
    parser = argparse.ArgumentParser(
        prog="dpsvm-tpu", description="TPU-native distributed SVM trainer")
    sub = parser.add_subparsers(dest="command", required=True)
    _build_train_parser(sub)
    _build_test_parser(sub)
    _build_serve_parser(sub)
    _build_lint_parser(sub)
    _build_obs_parser(sub)
    _build_autotune_parser(sub)
    _build_learn_parser(sub)
    p = sub.add_parser("smoke", help="device/mesh environment smoke test")
    p.add_argument("--num-devices", type=int, default=None)
    args = parser.parse_args(argv)
    if args.command == "train":
        return _cmd_train(args)
    if args.command == "smoke":
        return _cmd_smoke(args)
    if args.command == "serve":
        return _cmd_serve(args)
    return _cmd_test(args)


def _cmd_smoke(args) -> int:
    """Environment bring-up check — the role of the reference's
    mpi_sample.cpp / testblas.c (per-host MPI spawn + known 3x3 matvec):
    enumerate devices, run a known matvec on each, and verify a mesh psum.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from dpsvm_tpu.parallel.mesh import (DATA_AXIS, make_data_mesh,
                                         mesh_shard_map)

    devices = jax.devices()
    print(f"platform={devices[0].platform} devices={len(devices)}")
    a = jnp.asarray(np.arange(9, dtype=np.float32).reshape(3, 3))
    v = jnp.asarray(np.array([1.0, 2.0, 3.0], np.float32))
    want = np.array([8.0, 26.0, 44.0], np.float32)
    ok = True
    for d in devices:
        got = np.asarray(jax.device_put(a, d) @ jax.device_put(v, d))
        good = np.allclose(got, want)
        ok &= good
        print(f"  {d}: matvec {'OK' if good else 'FAIL ' + str(got)}")
    n = args.num_devices or len(devices)
    mesh = make_data_mesh(n)
    psum = jax.jit(mesh_shard_map(
        lambda x: jax.lax.psum(x, DATA_AXIS), mesh=mesh,
        in_specs=P(DATA_AXIS), out_specs=P()))
    got = np.asarray(psum(jnp.ones((n,), jnp.float32)))
    good = np.allclose(got, n)
    ok &= good
    print(f"  mesh({n}) psum {'OK' if good else 'FAIL ' + str(got)}")
    return 0 if ok else 1


def _cmd_train(args) -> int:
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.loader import load_data
    from dpsvm_tpu.train import train
    from dpsvm_tpu.utils.metrics import MetricsLogger, profile_trace

    if args.coordinator_address or args.num_processes or args.process_id is not None:
        from dpsvm_tpu.parallel.mesh import initialize_multihost
        initialize_multihost(args.coordinator_address, args.num_processes,
                             args.process_id)

    if args.svm_type in ("nu-svc", "nu-svr", "one-class"):
        # These duals fix their own selection rule / box; an explicitly
        # requested incompatible flag must fail loudly, not be silently
        # replaced (their trainers override selection/c/weights).
        if args.selection != "mvp":
            print(f"error: --selection {args.selection} is not applicable "
                  f"to {args.svm_type} (per-class nu selection is fixed)",
                  file=sys.stderr)
            return 2
        if args.svm_type in ("nu-svc", "nu-svr") and args.engine == "pallas":
            # Checked here too (the trainer raises the same constraint) so
            # the user gets a clean exit-code-2 error before the CSV is
            # loaded and the initial-gradient matvec runs.
            print(f"error: --engine pallas is not applicable to "
                  f"{args.svm_type} (per-class nu selection; use "
                  "--engine xla or block)", file=sys.stderr)
            return 2
        if args.svm_type in ("nu-svc", "one-class") and (
                args.weight_pos != 1.0 or args.weight_neg != 1.0):
            print(f"error: -w1/-w-1 are not applicable to {args.svm_type} "
                  "(the nu box is fixed at [0, 1])", file=sys.stderr)
            return 2

    if args.probability and args.svm_type not in ("c-svc", "nu-svc"):
        print(f"error: -b 1 (Platt probability) applies to classifiers "
              f"only, not {args.svm_type}", file=sys.stderr)
        return 2

    if args.kernel == "precomputed":
        # LibSVM -t 4: the training file's features ARE the Gram matrix.
        if args.svm_type != "c-svc":
            print("error: --kernel precomputed supports c-svc only (the "
                  "other duals would need transformed Gram sub-matrices)",
                  file=sys.stderr)
            return 2
        if args.probability:
            print("error: -b 1 is not supported with --kernel precomputed",
                  file=sys.stderr)
            return 2
        if args.backend in ("reference", "native"):
            print("error: --kernel precomputed needs the single or mesh "
                  "backend", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    regression = args.svm_type in ("eps-svr", "nu-svr")
    try:
        x, y = load_data(args.file_path, args.num_ex, args.num_att,
                         float_labels=regression, fmt=args.format)
    except ValueError as e:
        # Clean one-line diagnostic instead of a traceback (e.g. an SVR
        # task fed a LIBSVM-format file, or a mis-sniffed format).
        print(f"error: could not load {args.file_path} "
              f"(format={args.format}): {e}\n"
              f"hint: pass --format csv|libsvm to override auto-detection",
              file=sys.stderr)
        return 2
    if not args.quiet:
        print(f"loaded {x.shape[0]} examples x {x.shape[1]} features "
              f"in {time.perf_counter() - t0:.2f}s")

    try:
        from dpsvm_tpu.config import ObsConfig

        config = SVMConfig(
            c=args.cost, gamma=args.gamma, epsilon=args.epsilon,
            max_iter=args.max_iter, cache_lines=args.cache_size,
            kernel=args.kernel, degree=args.degree, coef0=args.coef0,
            weight_pos=args.weight_pos, weight_neg=args.weight_neg,
            selection=args.selection, engine=args.engine,
            working_set_size=args.working_set_size,
            inner_iters=args.inner_iters,
            pair_batch=args.pair_batch,
            fleet_size=args.fleet_size,
            fused_round={"auto": None, "on": True,
                         "off": False}[args.fused_round],
            pipeline_rounds={"auto": None, "on": True,
                             "off": False}[args.pipeline_rounds],
            local_working_sets=(None if args.local_working_sets == 0
                                else args.local_working_sets),
            sync_rounds=args.sync_rounds,
            ring_exchange={"auto": None, "on": True,
                           "off": False}[args.ring_exchange],
            bf16_gram=args.bf16_gram,
            active_set_size=args.active_set_size,
            reconcile_rounds=args.reconcile_rounds,
            ooc=args.ooc, ooc_tile_rows=args.ooc_tile_rows,
            ooc_cache_lines=args.ooc_cache_lines,
            ooc_shrink={"auto": None, "on": True,
                        "off": False}[args.ooc_shrink],
            dtype=args.dtype, chunk_iters=args.chunk_iters,
            checkpoint_every=args.checkpoint_every,
            checkpoint_keep=args.checkpoint_keep,
            retry_faults=args.retry_faults, verbose=not args.quiet,
            # With --obs the SOLVER owns the device-trace capture (its
            # spans then appear named inside it); without it the CLI's
            # profile_trace wrapper below keeps the old behavior.
            obs=ObsConfig(enabled=args.obs,
                          trace_dir=args.profile_dir if args.obs else None,
                          runlog_dir=args.obs_dir))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    # Non-±1 classification labels route through the OvR/OvO reduction
    # (LibSVM's svm-train trains arbitrary-labelled multiclass files the
    # same transparent way; the reference is binary-only). Two arbitrary
    # labels also route here: the model must predict the ORIGINAL labels.
    if args.svm_type in ("c-svc", "nu-svc") and not regression:
        classes = np.unique(y)
        if len(classes) < 2:
            print("error: training data holds a single class",
                  file=sys.stderr)
            return 2
        if not set(classes.tolist()) <= {-1, 1}:
            return _train_multiclass_cli(args, x, y, config)

    if args.cross_validate:
        return _cross_validate(args, x, y, config)

    if args.kernel == "precomputed":
        return _train_precomputed(args, x, y, config)

    logger = MetricsLogger(
        sink=None if args.quiet else sys.stderr,
        jsonl_path=args.metrics_jsonl,
        lookups_per_iter=0 if args.engine == "block" else 2)
    with profile_trace(None if args.obs else args.profile_dir):
        if args.svm_type == "c-svc":
            model, result = train(
                x, y, config, backend=args.backend, num_devices=args.num_devices,
                callback=logger, checkpoint_path=args.checkpoint,
                resume=args.resume)
        elif args.svm_type == "nu-svc":
            from dpsvm_tpu.models.nusvm import train_nusvc
            model, result = train_nusvc(
                x, y, nu=args.nu, config=config, backend=args.backend,
                num_devices=args.num_devices, callback=logger,
                checkpoint_path=args.checkpoint, resume=args.resume)
        elif args.svm_type == "eps-svr":
            from dpsvm_tpu.models.svr import train_svr
            model, result = train_svr(
                x, y, config, svr_epsilon=args.svr_epsilon,
                backend=args.backend, num_devices=args.num_devices,
                callback=logger,
                checkpoint_path=args.checkpoint, resume=args.resume)
        elif args.svm_type == "nu-svr":
            from dpsvm_tpu.models.nusvm import train_nusvr
            model, result = train_nusvr(
                x, y, nu=args.nu, config=config, backend=args.backend,
                num_devices=args.num_devices, callback=logger,
                checkpoint_path=args.checkpoint, resume=args.resume)
        else:  # one-class
            from dpsvm_tpu.models.oneclass import train_oneclass
            model, result = train_oneclass(
                x, nu=args.nu, config=config, backend=args.backend,
                num_devices=args.num_devices, callback=logger,
                checkpoint_path=args.checkpoint, resume=args.resume)
    logger.close()

    if result.converged:
        print(f"converged at iteration {result.iterations}")
    else:
        print(f"stopped at max-iter {result.iterations} without converging")
    print(f"training took {result.train_seconds:.2f}s")
    if result.stats.get("obs_runlog"):
        print(f"run log: {result.stats['obs_runlog']} "
              f"(run {result.stats['obs_run_id']})")
    print(f"b: {result.b:.6f}")
    print(f"support vectors: {result.n_sv}")
    if result.stats.get("cache_lookups"):
        print(f"cache hit rate: {result.stats['cache_hit_rate']:.3f}")

    if args.svm_type in ("c-svc", "nu-svc"):
        from dpsvm_tpu.predict import accuracy
        print(f"train accuracy: {accuracy(model, x, y):.4f}")
    elif args.svm_type in ("eps-svr", "nu-svr"):
        resid = np.asarray(model.predict(x)) - y
        print(f"train RMSE: {float(np.sqrt(np.mean(resid ** 2))):.6f}")
    else:
        inlier = float(np.mean(model.predict(x) > 0))
        print(f"train inlier fraction: {inlier:.4f} (nu={args.nu})")

    if args.probability:
        from dpsvm_tpu.models.platt import fit_platt_cv
        from dpsvm_tpu.predict import decision_function

        # LibSVM-style 5-fold CV refits: in-sample decision values are
        # margin-biased and overfit the sigmoid (see fit_platt_cv). The
        # folds must refit the SAME dual, so nu-svc passes its trainer.
        if args.svm_type == "nu-svc":
            from dpsvm_tpu.models.nusvm import train_nusvc

            def train_fn(xf, yf, cfg, backend, num_devices,
                         _t=train_nusvc, _nu=args.nu):
                return _t(xf, yf, nu=_nu, config=cfg, backend=backend,
                          num_devices=num_devices)
        else:
            train_fn = None
        model.prob_a, model.prob_b = fit_platt_cv(
            x, y, config, backend=args.backend,
            num_devices=args.num_devices, train_fn=train_fn)
        from dpsvm_tpu.models.platt import platt_probability

        dec = np.asarray(decision_function(model, x), np.float64)
        p = np.clip(platt_probability(dec, model.prob_a, model.prob_b),
                    1e-15, 1 - 1e-15)
        t = (y > 0).astype(np.float64)
        print(f"platt calibration: A={model.prob_a:.6f} "
              f"B={model.prob_b:.6f} "
              f"train log-loss={float(-np.mean(t * np.log(p) + (1 - t) * np.log(1 - p))):.4f}")
        if not args.model.endswith(".npz"):
            args.model += ".npz"
            print("note: probability models use the .npz format (the "
                  "reference text format cannot carry the calibration)")

    if args.svm_type in ("eps-svr", "nu-svr", "one-class") \
            and not args.model.endswith(".npz"):
        args.model += ".npz"
        print(f"note: {args.svm_type} models use the .npz format")
    model.save(args.model)
    print(f"model saved to {args.model}")
    return 0


def _train_multiclass_cli(args, x, y, config) -> int:
    """Train a >2-class (or non-±1-labelled) file via the OvR/OvO
    reduction (models/multiclass.py) and save the .npz bundle the test
    command dispatches on. LibSVM's svm-train handles such files the
    same transparent way (one-vs-one); the reference is binary-only."""
    classes = np.unique(y)
    blockers = [
        ("-t nu-svc", args.svm_type != "c-svc"),
        ("-b 1", bool(args.probability)),
        ("--kernel precomputed", args.kernel == "precomputed"),
        ("--checkpoint/--resume", bool(args.checkpoint or args.resume)),
        ("--metrics-jsonl", bool(args.metrics_jsonl)),
        ("--profile-dir", bool(args.profile_dir)),
        # -w1/-w-1 would apply to a DIFFERENT original class in every
        # OvR/OvO submodel (the +-1 remapping rotates) — scrambled
        # semantics, so refuse rather than silently mis-weight.
        ("-w1/-w-1", args.weight_pos != 1.0 or args.weight_neg != 1.0),
    ]
    bad = [f for f, hit in blockers if hit]
    if bad:
        print(f"error: multiclass training ({len(classes)} labels "
              f"{classes.tolist()[:6]}{'...' if len(classes) > 6 else ''}) "
              f"does not compose with {', '.join(bad)}; it trains plain "
              "binary C-SVC submodels", file=sys.stderr)
        return 2
    if args.cross_validate:
        # LibSVM's svm-train -v supports multiclass files (stratified CV
        # over the reduction); refusing here was a parity gap (ADVICE
        # round-4). Same contract as the binary path: throwaway fold
        # refits, LibSVM's output line, no model file.
        return _cross_validate_multiclass(args, x, y, config)
    from dpsvm_tpu.models.multiclass import train_multiclass

    if not args.quiet:
        k = len(classes)
        if k == 2:
            # train_multiclass collapses 2 classes to the single ovo
            # pair regardless of the requested strategy.
            plan = "1 binary submodel (2 non-±1 labels)"
        else:
            n_models = k if args.multiclass == "ovr" else k * (k - 1) // 2
            plan = f"{n_models} {args.multiclass} binary submodels"
        print(f"multiclass: {k} classes -> {plan}")
    t0 = time.perf_counter()
    model, results = train_multiclass(
        x, y, config, strategy=args.multiclass, backend=args.backend,
        num_devices=args.num_devices, verbose=not args.quiet)
    wall = time.perf_counter() - t0
    dev_s = sum(r.train_seconds for r in results)
    conv = sum(r.converged for r in results)
    print(f"training took {wall:.2f}s ({dev_s:.2f}s device; "
          f"{conv}/{len(results)} submodels converged)")
    from dpsvm_tpu.models.multiclass import accuracy_multiclass
    print(f"train accuracy: {accuracy_multiclass(model, x, y):.4f}")
    if not args.model.endswith(".npz"):
        args.model += ".npz"
        print("note: multiclass models use the .npz format (the "
              "reference text format is binary-only)")
    model.save(args.model)
    print(f"model saved to {args.model}")
    return 0


def _fold_fit_factory(args, config):
    """One fold-refit closure per svm_type — the family dispatch shared
    by -v cross-validation (and mirroring the -b Platt refit shim).
    Folds deliberately run without callbacks/checkpoints: a fold is a
    throwaway refit, not a resumable training run."""
    from dpsvm_tpu.train import train

    if args.svm_type == "c-svc":
        def fit(xf, yf):
            return train(xf, yf, config, backend=args.backend,
                         num_devices=args.num_devices)[0]
    elif args.svm_type == "nu-svc":
        from dpsvm_tpu.models.nusvm import train_nusvc

        def fit(xf, yf):
            return train_nusvc(xf, yf, nu=args.nu, config=config,
                               backend=args.backend,
                               num_devices=args.num_devices)[0]
    elif args.svm_type == "eps-svr":
        from dpsvm_tpu.models.svr import train_svr

        def fit(xf, yf):
            return train_svr(xf, yf, config,
                             svr_epsilon=args.svr_epsilon,
                             backend=args.backend,
                             num_devices=args.num_devices)[0]
    else:  # nu-svr
        from dpsvm_tpu.models.nusvm import train_nusvr

        def fit(xf, yf):
            return train_nusvr(xf, yf, nu=args.nu, config=config,
                               backend=args.backend,
                               num_devices=args.num_devices)[0]
    return fit


def _fold_split(y, k: int, seed: int = 0, stratify: bool = False):
    """Deterministic k-fold index split; stratify=True spreads each class
    proportionally across folds (svm-train stratifies its -v folds for
    classification — unstratified folds on imbalanced data can lose a
    class from a training complement and are not comparable to LibSVM's
    numbers)."""
    rng = np.random.default_rng(seed)
    if not stratify:
        return np.array_split(rng.permutation(len(y)), k)
    parts = [[] for _ in range(k)]
    for ci, cls in enumerate(np.unique(y)):
        idx = rng.permutation(np.nonzero(y == cls)[0])
        # np.array_split hands every remainder member to the LOWEST
        # part indices; rotating the assignment by the class counter
        # spreads remainders across folds instead of systematically
        # making fold 0 the largest (ADVICE round-4).
        for i, p in enumerate(np.array_split(idx, k)):
            if p.size:
                parts[(i + ci) % k].append(p)
    return [rng.permutation(np.concatenate(p)) if p
            else np.empty(0, np.int64) for p in parts]


def _cross_validate(args, x, y, config) -> int:
    """LibSVM svm-train -v: N-fold cross-validation. Each fold refits the
    requested model family on the other folds and scores the held fold;
    prints LibSVM's own output lines (Cross Validation Accuracy for
    classifiers, Mean squared error + Squared correlation coefficient
    for SVR) and writes NO model file. Classification folds are
    STRATIFIED, like svm-train's. Deterministic folds (seed 0, like the
    -b Platt calibration refits).
    """
    k = args.cross_validate
    if k < 2:
        print("error: -v requires N >= 2 folds", file=sys.stderr)
        return 2
    if args.svm_type == "one-class":
        print("error: -v cross-validation is not defined for one-class "
              "(no held-out labels to score)", file=sys.stderr)
        return 2
    if args.kernel == "precomputed":
        print("error: -v does not compose with --kernel precomputed "
              "(folds would need per-fold Gram sub-matrices; precompute "
              "per-fold Grams and run them separately)", file=sys.stderr)
        return 2
    if len(y) < k:
        print(f"error: -v {k} needs at least {k} rows", file=sys.stderr)
        return 2
    # Flags that -v cannot honor must fail loudly, never be silently
    # dropped (this file's -b/-o convention): -v trains throwaway fold
    # models, so probability calibration, checkpointing and per-chunk
    # metrics have nothing durable to attach to.
    ignored = [flag for flag, val in (
        ("-b 1", args.probability), ("--checkpoint", args.checkpoint),
        ("--resume", args.resume),
        ("--metrics-jsonl", args.metrics_jsonl),
        ("--profile-dir", args.profile_dir)) if val]
    if ignored:
        print(f"error: -v does not compose with {', '.join(ignored)} "
              "(fold refits are throwaway models; run a plain train for "
              "those)", file=sys.stderr)
        return 2

    fit = _fold_fit_factory(args, config)
    classify = args.svm_type in ("c-svc", "nu-svc")
    folds = _fold_split(y, k, seed=0, stratify=classify)
    # Validate EVERY training complement up front — no wall-clock spent
    # before a doomed fold is discovered (possible only when a class has
    # a single member, given the stratified split).
    if classify:
        for i, held in enumerate(folds):
            tr_mask = np.ones(len(y), bool)
            tr_mask[held] = False
            if len(np.unique(y[tr_mask])) < 2:
                print(f"error: fold {i} would lose a class (a class has "
                      "too few members); lower -v or provide more data",
                      file=sys.stderr)
                return 2
    pred = np.empty(len(y), np.float64)
    t0 = time.perf_counter()
    for i, held in enumerate(folds):
        tr = np.concatenate([f for j, f in enumerate(folds) if j != i])
        model = fit(x[tr], y[tr])
        if classify:
            from dpsvm_tpu.predict import predict as predict_cls
            pred[held] = np.asarray(predict_cls(model, x[held]), np.float64)
        else:
            pred[held] = np.asarray(model.predict(x[held]), np.float64)
        if not args.quiet:
            print(f"  fold {i + 1}/{k}: trained on {len(tr)}, "
                  f"scored {len(held)}", file=sys.stderr)
    wall = time.perf_counter() - t0
    if classify:
        acc = float(np.mean(pred == y))
        print(f"Cross Validation Accuracy = {100.0 * acc:g}%")
    else:
        z = np.asarray(y, np.float64)
        mse = float(np.mean((pred - z) ** 2))
        vp, vz = pred - pred.mean(), z - z.mean()
        denom = float(np.sum(vp ** 2) * np.sum(vz ** 2))
        r2 = float(np.sum(vp * vz) ** 2 / denom) if denom > 0 else 0.0
        print(f"Cross Validation Mean squared error = {mse:g}")
        print(f"Cross Validation Squared correlation coefficient = {r2:g}")
    if not args.quiet:
        print(f"({k}-fold over {len(y)} rows in {wall:.2f}s; no model "
              "file written — LibSVM -v contract)", file=sys.stderr)
    return 0


def _cross_validate_multiclass(args, x, y, config) -> int:
    """svm-train -v for a multiclass file: stratified k-fold over the
    OvR/OvO reduction (models/multiclass.py), printing LibSVM's Cross
    Validation Accuracy line and writing no model file. The composition
    blockers (-b, --checkpoint, precomputed, weights, ...) were already
    enforced by _train_multiclass_cli's shared list."""
    from dpsvm_tpu.models.multiclass import (predict_multiclass,
                                             train_multiclass)

    k = args.cross_validate
    if k < 2:
        print("error: -v requires N >= 2 folds", file=sys.stderr)
        return 2
    if len(y) < k:
        print(f"error: -v {k} needs at least {k} rows", file=sys.stderr)
        return 2
    folds = _fold_split(y, k, seed=0, stratify=True)
    for i, held in enumerate(folds):
        tr_mask = np.ones(len(y), bool)
        tr_mask[held] = False
        if len(np.unique(y[tr_mask])) < 2:
            print(f"error: fold {i} would lose all but one class; lower "
                  "-v or provide more data", file=sys.stderr)
            return 2
    pred = np.empty(len(y), np.float64)
    t0 = time.perf_counter()
    for i, held in enumerate(folds):
        tr = np.concatenate([f for j, f in enumerate(folds) if j != i])
        model, _ = train_multiclass(x[tr], y[tr], config,
                                    strategy=args.multiclass,
                                    backend=args.backend,
                                    num_devices=args.num_devices)
        pred[held] = np.asarray(predict_multiclass(model, x[held]),
                                np.float64)
        if not args.quiet:
            print(f"  fold {i + 1}/{k}: trained on {len(tr)}, "
                  f"scored {len(held)}", file=sys.stderr)
    acc = float(np.mean(pred == np.asarray(y, np.float64)))
    print(f"Cross Validation Accuracy = {100.0 * acc:g}%")
    if not args.quiet:
        print(f"({k}-fold over {len(y)} rows in "
              f"{time.perf_counter() - t0:.2f}s; no model file written — "
              "LibSVM -v contract)", file=sys.stderr)
    return 0


def _train_precomputed(args, x, y, config) -> int:
    """Train on a user-supplied Gram matrix (LibSVM -t 4). The model
    carries SV indices (models/precomputed.py), so it saves as .npz."""
    import jax

    from dpsvm_tpu.models.precomputed import PrecomputedSVCModel
    from dpsvm_tpu.utils.metrics import MetricsLogger

    n = x.shape[0]
    if x.shape[1] != n:
        print(f"error: --kernel precomputed needs the square (n, n) Gram "
              f"matrix as features; {args.file_path} is {x.shape[0]} x "
              f"{x.shape[1]}", file=sys.stderr)
        return 2
    backend = args.backend
    if backend == "auto":
        multi = (args.num_devices or len(jax.devices())) > 1
        # The mesh precomputed path exists for the block engine only
        # (Gram symmetry makes its fold local; dist_block.py).
        backend = "mesh" if (multi and config.engine == "block") else "single"
    logger = MetricsLogger(
        sink=None if args.quiet else sys.stderr, jsonl_path=args.metrics_jsonl,
        lookups_per_iter=0)
    try:
        if backend == "single":
            from dpsvm_tpu.solver.smo import solve
            result = solve(x, y, config, callback=logger,
                           checkpoint_path=args.checkpoint,
                           resume=args.resume)
        else:
            from dpsvm_tpu.parallel.dist_smo import solve_mesh
            result = solve_mesh(x, y, config, num_devices=args.num_devices,
                                callback=logger,
                                checkpoint_path=args.checkpoint,
                                resume=args.resume)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        logger.close()

    model = PrecomputedSVCModel.from_solution(y, result.alpha, result.b)
    if result.converged:
        print(f"converged at iteration {result.iterations}")
    else:
        print(f"stopped at max-iter {result.iterations} without converging")
    print(f"training took {result.train_seconds:.2f}s")
    print(f"b: {result.b:.6f}")
    print(f"support vectors: {model.n_sv}")
    # Train accuracy: the training Gram's rows ARE K(train, train).
    acc = float(np.mean(model.predict(x) == y))
    print(f"train accuracy: {acc:.4f}")
    if not args.model.endswith(".npz"):
        args.model += ".npz"
        print("note: precomputed-kernel models use the .npz format "
              "(they store SV indices, not feature rows)")
    model.save(args.model)
    print(f"model saved to {args.model}")
    return 0


def _load_eval_data(args, model_width: int, float_labels: bool = False):
    """Load the test file at its OWN inferred width, then reconcile with
    the model's width. Silent truncation of a wider file is the failure
    mode to avoid (a wrong model for the dataset would print a plausible
    but meaningless accuracy): CSV wider than the model is an error, a
    sparse LIBSVM file gets a loud warning (its width is just the largest
    seen index, so an off-by-a-few mismatch can be legitimate), and an
    explicit -a is taken as consent. A narrower LIBSVM file is padded
    (trailing all-zero features are legitimately absent); a narrower CSV
    is an error as before. Returns (x, y) or None after printing a
    diagnostic."""
    from dpsvm_tpu.data.loader import load_data, sniff_format

    fmt = args.format
    if fmt == "auto":
        fmt = sniff_format(args.file_path)
    # The kernel shapes are pinned by the MODEL; -a is consent to
    # truncate a wider file, never a way to feed a different width (that
    # would only move the crash into the kernel matmul).
    if args.num_att is not None and args.num_att != model_width:
        print(f"error: -a {args.num_att} conflicts with the model's "
              f"{model_width} features (the model fixes the width; use "
              f"-a {model_width} to consent to truncation)",
              file=sys.stderr)
        return None
    natt = model_width
    try:
        x, y = load_data(args.file_path, args.num_ex, None,
                         float_labels=float_labels, fmt=fmt)
    except ValueError as e:
        print(f"error: could not load {args.file_path} (format={fmt}): "
              f"{e}\nhint: pass --format csv|libsvm to override "
              f"auto-detection", file=sys.stderr)
        return None
    w = x.shape[1]
    if w < natt:
        if fmt == "libsvm":
            x = np.pad(x, ((0, 0), (0, natt - w)))
        else:
            print(f"error: {args.file_path} has {w} features but the "
                  f"model expects {natt} (CSV columns are positional — "
                  f"this looks like the wrong model for the dataset)",
                  file=sys.stderr)
            return None
    elif w > natt:
        if args.num_att is not None or fmt == "libsvm":
            msg = (f"warning: {args.file_path} has {w} features; using "
                   f"the first {natt} the model expects")
            print(msg, file=sys.stderr)
            x = x[:, :natt]
        else:
            print(f"error: {args.file_path} has {w} features but the "
                  f"model expects {natt}; pass -a {natt} to truncate "
                  f"explicitly if this is intended", file=sys.stderr)
            return None
    return x, y


def _write_predictions(args, values, fmt: str = "%d") -> None:
    """Shared -o writer for the non-classifier branches: one prediction
    per line (labels for one-class/precomputed, regression values for
    SVR)."""
    if not args.output:
        return
    with open(args.output, "w") as fh:
        fh.writelines((fmt % v) + "\n" for v in values)
    print(f"predictions written to {args.output}")


def _cmd_serve(args) -> int:
    """Run the persistent serving engine (serve.py PredictServer) on a
    saved model: either the offered-load micro-benchmark
    (--server-bench) or a stdin prediction loop (one comma-separated
    feature row per line -> one predicted label per line, micro-batched
    into the pre-compiled buckets; a blank line forces a flush)."""
    import json

    from dpsvm_tpu.config import ServeConfig
    from dpsvm_tpu.serve import PredictServer, offered_load_sweep

    if args.registry or args.journal or args.listen \
            or args.replicas > 1:
        # --journal alone is a valid v2 start: a crash-restarted
        # engine rehydrates its whole model set from the journal.
        # --listen is v2-only (the network front door fronts the
        # ServingEngine); --replicas > 1 likewise (the fleet lives
        # behind it) and fails loudly there instead of being ignored.
        return _cmd_serve_v2(args)
    if not args.model:
        print("error: -m/--model is required (or --registry NAME=PATH "
              "for the v2 engine)", file=sys.stderr)
        return 2
    model_type = "classifier"
    if args.model.endswith(".npz"):
        z = np.load(args.model, allow_pickle=False)
        mt = str(z.get("model_type", ""))
        if mt == "multiclass" or ("n_models" in z and "strategy" in z):
            model_type = "multiclass"
        elif mt in ("svr", "oneclass", "precomputed_svc"):
            print(f"error: cannot serve a {mt} model (the serving "
                  "engine is the classifier decision path)",
                  file=sys.stderr)
            return 2
    if model_type == "multiclass":
        from dpsvm_tpu.models.multiclass import MulticlassSVM
        model = MulticlassSVM.load(args.model)
    else:
        from dpsvm_tpu.models.svm_model import SVMModel
        model = SVMModel.load(args.model)

    try:
        from dpsvm_tpu.config import ObsConfig

        buckets = (None if args.buckets.strip() == "auto" else
                   tuple(int(t) for t in args.buckets.split(",") if t))
        config = ServeConfig(buckets=buckets, dtype=args.dtype,
                             union_storage=args.union_storage,
                             precision=args.precision,
                             num_devices=args.num_devices,
                             metrics_port=args.metrics_port,
                             metrics_host=args.metrics_host,
                             slo_ms=args.slo_ms,
                             obs=ObsConfig(enabled=args.obs,
                                           runlog_dir=args.obs_dir))
        t0 = time.perf_counter()
        server = PredictServer(model, config)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if server.exporter is not None and not args.quiet:
        print(f"metrics: {server.exporter.url} (OpenMetrics; scrape "
              f"with curl or Prometheus)", file=sys.stderr)
    if not args.quiet:
        ens = server.ens
        # server.buckets, not config.buckets: the server trims buckets
        # whose kernel tile would cross the memory budget.
        print(f"server ready in {time.perf_counter() - t0:.2f}s: "
              f"{server.k} decision columns over a {ens.n_union}-row SV "
              f"union ({int(ens.counts.sum())} stacked SVs compacted; "
              f"{len(server.f64_cols)} float64-routed columns), "
              f"buckets {server.buckets}, union storage "
              f"{server.union_storage}", file=sys.stderr)

    if args.server_bench:
        try:
            sizes = [int(t) for t in args.request_sizes.split(",") if t]
            rec = offered_load_sweep(server, sizes, args.requests,
                                     group=args.group)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not args.quiet:
            # Latency percentiles come from the SHARED obs histograms
            # (server.request_seconds / stats["bucket_seconds"]), not a
            # sweep-local aggregation — ISSUE 7 satellite.
            lat = rec["request_latency"]
            print("request latency (shared histogram): "
                  + " ".join(f"{k}={v * 1e3:.2f}ms"
                             for k, v in lat.items()), file=sys.stderr)
        server.close()
        print(json.dumps(rec))
        return 0

    buf: list = []

    def _emit(lines) -> None:
        rows = np.asarray([[float(v) for v in ln.split(",")]
                           for ln in lines], np.float32)
        for lab in server.predict(rows):
            print(int(lab))
        # Piped clients wait for these labels (stdout is block-buffered
        # off a tty; without the flush a blank-line "flush" request
        # would deadlock the client against Python's 8 KB buffer).
        sys.stdout.flush()

    try:
        for line in sys.stdin:
            ln = line.strip()
            if not ln:
                if buf:
                    _emit(buf)
                    buf = []
                continue
            buf.append(ln)
            if len(buf) >= server.buckets[-1]:
                _emit(buf)
                buf = []
        if buf:
            _emit(buf)
    except ValueError as e:
        print(f"error: bad query row ({e})", file=sys.stderr)
        return 2
    server.close()
    if not args.quiet:
        st = server.stats
        print(f"served {st['rows']} rows in {st['dispatches']} "
              f"dispatches (bucket counts {st['bucket_counts']}, "
              f"{st['padded_rows']} padded rows)", file=sys.stderr)
    return 0


def _cmd_serve_v2(args) -> int:
    """`cli serve --registry NAME=PATH [...]`: the v2 multi-model
    serving engine (dpsvm_tpu/serving). stdin protocol: one
    comma-separated feature row per line, optionally prefixed
    ``NAME|`` to route (bare rows need exactly one registered model);
    ``swap NAME=PATH`` hot-swaps a model mid-stream with zero downtime;
    a blank line (or EOF) drains and prints one ``NAME label`` line per
    request in submit order (``NAME MISS`` for work shed past its
    deadline)."""
    from dpsvm_tpu.config import ObsConfig, ServeConfig
    from dpsvm_tpu.serving import ModelLoadError, ServingEngine

    if args.model:
        print("error: use either -m (v1 single-model server) or "
              "--registry (v2 engine), not both", file=sys.stderr)
        return 2
    if args.server_bench:
        print("error: --server-bench drives the v1 server; the v2 "
              "engine's closed-loop benchmark is tools/loadgen.py",
              file=sys.stderr)
        return 2
    if args.precision != "auto":
        print("error: the v2 engine always risk-routes per submodel "
              "(--precision auto semantics); the forced modes are the "
              "v1 server's", file=sys.stderr)
        return 2
    specs = []
    for spec in args.registry or []:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            print(f"error: --registry wants NAME=PATH, got {spec!r}",
                  file=sys.stderr)
            return 2
        specs.append((name, path))

    try:
        buckets = (None if args.buckets.strip() == "auto" else
                   tuple(int(t) for t in args.buckets.split(",") if t))
        timeouts = {}
        if args.conn_timeout_ms is not None:
            timeouts = dict(conn_read_timeout_ms=args.conn_timeout_ms,
                            conn_write_timeout_ms=args.conn_timeout_ms)
        config = ServeConfig(
            buckets=buckets, dtype=args.dtype,
            union_storage=args.union_storage,
            num_devices=args.num_devices,
            deadline_ms=args.deadline_ms,
            dispatch_timeout_ms=args.dispatch_timeout_ms,
            journal_path=args.journal, listen=args.listen,
            replicas=args.replicas,
            admission_max_rows=args.admission_max_rows,
            metrics_port=args.metrics_port,
            metrics_host=args.metrics_host, slo_ms=args.slo_ms,
            obs=ObsConfig(enabled=args.obs, runlog_dir=args.obs_dir),
            **timeouts)
        t0 = time.perf_counter()
        if config.replicas > 1:
            from dpsvm_tpu.serving import ReplicaFleet

            engine = ReplicaFleet(config)
            eng0 = engine.engines[0]
        else:
            engine = ServingEngine(config)
            eng0 = engine
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if eng0._rehydrated and not args.quiet:
        print(f"rehydrated {len(eng0._rehydrated)} model(s) from "
              f"{config.journal_path}: "
              + ", ".join(f"{e.name} v{e.version}"
                          for e in eng0.registry.entries()),
              file=sys.stderr)
    try:
        for name, path in specs:
            entry = engine.register(name, path)
            if not args.quiet:
                print(f"registered {name} v{entry.version}: {entry.k} "
                      f"decision columns over a "
                      f"{int(entry.ens.n_union)}-row SV union "
                      f"({entry.strategy}, d={entry.d})",
                      file=sys.stderr)
    except ModelLoadError as e:
        print(f"error: {e}", file=sys.stderr)
        engine.close()
        return 2
    if not eng0.registry.names():
        print("error: no models to serve (--registry NAME=PATH, or a "
              "--journal with recorded models)", file=sys.stderr)
        engine.close()
        return 2
    if engine.exporter is not None and not args.quiet:
        print(f"metrics: {engine.exporter.url} (OpenMetrics)",
              file=sys.stderr)
    if not args.quiet:
        print(f"engine ready in {time.perf_counter() - t0:.2f}s: "
              f"{len(specs)} models"
              + (f" x {config.replicas} replicas"
                 if config.replicas > 1 else "")
              + f", deadline {config.deadline_ms or 'none'} ms",
              file=sys.stderr)

    if args.listen:
        return _serve_listen(args, engine, config)

    order: list = []

    def _drain_print() -> None:
        done = engine.drain()
        nonlocal order
        for ticket in order:
            if ticket not in done:
                continue
            res = done[ticket]
            lab = res.labels()  # the SERVING version's fold — after a
            if lab is None:     # swap, queued requests were answered
                # expired       # by the OLD entry's columns
                print(f"{res.model} MISS")
            else:
                print(f"{res.model} {int(lab[0])}")
        order = []
        sys.stdout.flush()  # piped clients wait on these labels

    for line in sys.stdin:
        ln = line.strip()
        if not ln:
            _drain_print()
            continue
        if ln.startswith("swap "):
            name, sep, path = ln[5:].strip().partition("=")
            if not sep:
                print("error: swap wants NAME=PATH", file=sys.stderr)
                continue
            try:
                entry = engine.swap(name, path)
                print(f"swapped {name} -> v{entry.version}",
                      file=sys.stderr)
            except (ModelLoadError, KeyError) as e:
                # The hot-swap contract: a bad file/name is refused
                # loudly; the prior version keeps serving.
                print(f"error: {e}", file=sys.stderr)
            continue
        name, sep, row = ln.partition("|")
        if not sep:
            name, row = None, ln
        # Per-line failure containment (the swap path's discipline): a
        # malformed row or unknown model name must not tear down the
        # session and discard every queued request's output.
        try:
            rows = np.asarray([[float(v) for v in row.split(",")]],
                              np.float32)
            order.append(engine.submit(rows, model=name))
        except (ValueError, KeyError) as e:
            print(f"error: skipped bad query line ({e})",
                  file=sys.stderr)
    _drain_print()
    engine.close()
    if not args.quiet:
        snap = engine.snapshot()
        print(f"served {snap['rows']} rows in {snap['dispatches']} "
              f"dispatches ({snap['coalesced_dispatches']} coalesced; "
              f"{snap['deadline_misses']} deadline misses, "
              f"{snap['hot_swaps']} hot swaps)", file=sys.stderr)
    return 0


def _serve_listen(args, engine, config, stop_event=None) -> int:
    """``cli serve --listen HOST:PORT``: run the network front door
    until SIGTERM/SIGINT, then GRACEFULLY DRAIN — stop accepting,
    finish or shed in-flight work by its own deadline (the engine's
    normal explicit verdicts), flush final verdicts, GOODBYE each
    connection, close the engine (journal already consistent: it was
    written atomically at register/swap time). `stop_event` is the
    test seam — production flow sets it from the signal handler."""
    import signal
    import threading

    from dpsvm_tpu.serving.server import ServeServer

    server = ServeServer(engine)
    stop = stop_event if stop_event is not None else threading.Event()
    handled = {}
    if stop_event is None:
        def _on_signal(signum, frame):
            stop.set()  # tiny handler; the drain runs on the main thread

        for sig in (signal.SIGTERM, signal.SIGINT):
            handled[sig] = signal.signal(sig, _on_signal)
    if not args.quiet:
        print(f"front door listening on {server.host}:{server.port} "
              "(SIGTERM = graceful drain)", file=sys.stderr)
    try:
        stop.wait()
        # Drain with OUR handler still installed: a second SIGTERM
        # during the drain is a no-op (the event is already set), not
        # a mid-drain process kill — 'SIGTERM = graceful drain' holds
        # unconditionally. Handlers restore only after teardown.
        snap = server.close()
        engine.close()
    finally:
        for sig, prev in handled.items():
            signal.signal(sig, prev)
    if not args.quiet:
        v = snap["verdicts"]
        print(f"drained: {snap['frames_accepted']} frames over "
              f"{snap['conns_opened']} connections -> "
              + " ".join(f"{k}={v[k]}" for k in sorted(v))
              + (f" undeliverable={snap['undeliverable_total']}"
                 if snap["undeliverable_total"] else ""),
              file=sys.stderr)
    return 0


def _cmd_test(args) -> int:
    from dpsvm_tpu.models.svm_model import SVMModel
    from dpsvm_tpu.ops.kernels import KernelParams

    # Type-dispatch: .npz files carry a model_type field (svr / oneclass /
    # classifier); the reference-compatible .txt format is classifier-only.
    model_type = "classifier"
    if args.model.endswith(".npz"):
        z = np.load(args.model, allow_pickle=False)
        model_type = {"svr": "svr", "oneclass": "oneclass",
                      "precomputed_svc": "precomputed_svc",
                      "multiclass": "multiclass"}.get(
            str(z.get("model_type", "")), "classifier")
        if model_type == "classifier" and "n_models" in z \
                and "strategy" in z:
            # Multiclass bundles saved before the model_type tag existed
            # have everything MulticlassSVM.load needs — dispatch on
            # their structural keys instead of crashing in SVMModel.load.
            model_type = "multiclass"

    if model_type != "classifier" and args.probability:
        # -b 1 needs Platt calibration, which only classifier models
        # carry; failing loudly beats silently ignoring the flag.
        print(f"error: -b 1 is not applicable to a {model_type} model",
              file=sys.stderr)
        return 2

    if model_type != "classifier" and args.precision != "auto":
        # Same loud-failure convention: the precision wiring lives on
        # the binary decision path only (multiclass bundles risk-route
        # per submodel via the serving engine's decision_risk gate).
        print(f"error: --precision {args.precision} applies to binary "
              f"classifier models only, not a {model_type} model",
              file=sys.stderr)
        return 2

    if model_type == "multiclass":
        from dpsvm_tpu.models.multiclass import (MulticlassSVM,
                                                 predict_multiclass)
        if args.gamma is not None:
            # The binary branch honors -g by rebuilding one kernel;
            # silently evaluating k submodels at their TRAINED gammas
            # while the user believes the override applied is worse
            # than refusing.
            print("error: -g does not apply to a multiclass bundle "
                  "(its submodels carry their trained kernels); retrain "
                  "with the desired gamma", file=sys.stderr)
            return 2
        model = MulticlassSVM.load(args.model)
        loaded = _load_eval_data(args, model.models[0].sv_x.shape[1])
        if loaded is None:
            return 2
        x, y = loaded
        extra = sorted(set(np.unique(y).tolist())
                       - set(model.classes.tolist()))
        if extra:
            # Same footgun the binary branch guards: scoring against
            # labels the model cannot predict prints a plausible but
            # meaningless accuracy.
            print(f"error: test labels {extra[:6]} are not among the "
                  f"model's classes {model.classes.tolist()[:6]}",
                  file=sys.stderr)
            return 2
        pred = predict_multiclass(model, x)
        acc = float(np.mean(pred == y))
        print(f"loaded multiclass model: {len(model.classes)} classes, "
              f"{model.strategy}, {len(model.models)} submodels, "
              f"{sum(m.n_sv for m in model.models)} total SVs")
        print(f"test accuracy: {acc:.4f} ({x.shape[0]} examples)")
        _write_predictions(args, pred)
        return 0
    if model_type == "svr":
        from dpsvm_tpu.models.svr import SVRModel
        model = SVRModel.load(args.model)
        loaded = _load_eval_data(args, model.sv_x.shape[1],
                                 float_labels=True)
        if loaded is None:
            return 2
        x, z_true = loaded
        pred = np.asarray(model.predict(x), np.float64)
        rmse = float(np.sqrt(np.mean((pred - z_true) ** 2)))
        ss_tot = float(np.sum((z_true - z_true.mean()) ** 2))
        r2 = 1.0 - float(np.sum((pred - z_true) ** 2)) / ss_tot if ss_tot else 0.0
        print(f"loaded SVR model: {model.n_sv} SVs, gamma={model.kernel.gamma}")
        print(f"test RMSE: {rmse:.6f}  R2: {r2:.4f} ({x.shape[0]} examples)")
        _write_predictions(args, pred, fmt="%.9g")
        return 0
    if model_type == "oneclass":
        from dpsvm_tpu.models.oneclass import OneClassModel
        model = OneClassModel.load(args.model)
        loaded = _load_eval_data(args, model.sv_x.shape[1])
        if loaded is None:
            return 2
        x, y = loaded
        pred = model.predict(x)
        print(f"loaded one-class model: {model.n_sv} SVs, rho={model.rho:.6f}")
        print(f"test inlier fraction: {float(np.mean(pred > 0)):.4f} "
              f"({x.shape[0]} examples)")
        if set(np.unique(y).tolist()) <= {-1, 1}:
            print(f"test accuracy vs +-1 labels: {float(np.mean(pred == y)):.4f}")
        _write_predictions(args, pred)
        return 0
    if model_type == "precomputed_svc":
        from dpsvm_tpu.models.precomputed import PrecomputedSVCModel
        model = PrecomputedSVCModel.load(args.model)
        # The test file's feature columns must be K(test, train) rows —
        # width n_train, exactly like LibSVM's precomputed svm-predict.
        loaded = _load_eval_data(args, model.n_train)
        if loaded is None:
            return 2
        x, y = loaded
        pred = model.predict(x)
        acc = float(np.mean(pred == y))
        print(f"loaded precomputed-kernel model: {model.n_sv} SVs over "
              f"{model.n_train} training points, b={model.b:.6f}")
        print(f"test accuracy: {acc:.4f} ({x.shape[0]} examples)")
        _write_predictions(args, pred)
        return 0

    model = SVMModel.load(args.model)
    if args.gamma is not None:
        model.kernel = KernelParams(
            model.kernel.kind, args.gamma, model.kernel.degree, model.kernel.coef0)
    loaded = _load_eval_data(args, model.sv_x.shape[1])
    if loaded is None:
        return 2
    x, y = loaded
    if not set(np.unique(y).tolist()) <= {-1, 1}:
        # A binary model scored against other labels would print a
        # plausible but meaningless accuracy (only the +1 rows could
        # ever match); fail loudly instead.
        print(f"error: {args.model} is a binary +-1 model but the test "
              f"file's labels are {np.unique(y).tolist()[:6]}; relabel "
              "the test data (or test against the multiclass .npz "
              "model trained from the original labels)", file=sys.stderr)
        return 2
    from dpsvm_tpu.predict import (decision_function, decision_risk,
                                   resolve_precision)

    prec = args.precision
    if prec == "auto":
        prec = resolve_precision(model)
        if prec == "float64":
            print(f"precision auto: decision_risk "
                  f"{decision_risk(model):.3g} >= 0.1 -> exact float64 "
                  "evaluation (pass --precision float32 to force the "
                  "device path)", file=sys.stderr)
    dec = np.asarray(decision_function(model, x, precision=prec))
    proba = None
    if args.probability:
        if not model.has_probability:
            print("error: -b 1 needs a model trained with -b 1 (no Platt "
                  "calibration in this model file)", file=sys.stderr)
            return 2
        from dpsvm_tpu.models.platt import platt_probability

        proba = platt_probability(dec, model.prob_a, model.prob_b)
    # LibSVM's svm-predict scores sign(dec) plain and the max-probability
    # label under -b 1 (Platt's B can shift the p=0.5 threshold off
    # dec=0) — the printed accuracy, the -o labels and LibSVM all agree.
    pred = (np.where(proba >= 0.5, 1, -1) if proba is not None
            else np.where(dec >= 0, 1, -1))
    acc = float(np.mean(pred == y))
    print(f"loaded model: {model.n_sv} SVs, gamma={model.kernel.gamma}, "
          f"b={model.b:.6f}"
          + (", platt-calibrated" if model.has_probability else ""))
    print(f"test accuracy: {acc:.4f} ({x.shape[0]} examples)"
          + (" [labels by max probability, svm-predict -b 1 style]"
             if proba is not None else ""))
    if proba is not None:
        p = np.clip(proba, 1e-15, 1 - 1e-15)
        t = (y > 0).astype(np.float64)
        ll = float(-np.mean(t * np.log(p) + (1 - t) * np.log(1 - p)))
        print(f"test log-loss: {ll:.4f} (Platt A={model.prob_a:.6f} "
              f"B={model.prob_b:.6f})")
    if args.output and proba is not None:
        # Only the -b 1 'label p(+1)' format needs a custom writer.
        with open(args.output, "w") as fh:
            fh.write("label p(+1)\n")
            for pi, pr in zip(pred, proba):
                fh.write(f"{int(pi)} {pr:.6f}\n")
        print(f"predictions written to {args.output}")
    else:
        _write_predictions(args, pred)
    return 0


def train_main() -> int:
    """`svmtrain` console entry — the reference's ./svmTrain binary role."""
    return main(["train"] + sys.argv[1:])


def test_main() -> int:
    """`svmtest` console entry — the reference's svmTest/seq_test role."""
    return main(["test"] + sys.argv[1:])


def serve_main() -> int:
    """`svmserve` console entry — the persistent serving engine (no
    reference equivalent; its tester scores a file and exits)."""
    return main(["serve"] + sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
