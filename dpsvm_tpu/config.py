"""Typed training configuration.

Replaces the reference's global mutable ``state_model state`` singleton
(svmTrainMain.hpp:4-19, svmTrainMain.cpp:60-136) with an immutable dataclass.
Flag names and defaults match the reference CLI (svmTrainMain.cpp:22-71)
except for documented bug fixes:

* default gamma is ``1.0 / num_features`` computed in float (the reference
  computes ``1 / num_attributes`` in integer arithmetic, giving gamma == 0
  for d > 1 — bug B1, svmTrainMain.cpp:133).
* eta (second-derivative of the 2-var subproblem) is clamped to ``tau``
  before division (the reference divides unguarded — bug B2,
  svmTrainMain.cpp:290).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

KERNELS = ("rbf", "linear", "poly", "sigmoid", "precomputed")


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability knobs (dpsvm_tpu/obs — ISSUE 7), shared by
    SVMConfig and ServeConfig as their ``obs`` field.

    enabled    -- master switch for run logs + registry metrics +
                  trace spans. OFF by default and STRICTLY free when
                  off (shared null instruments; no clock reads). The
                  ``DPSVM_OBS=1`` environment variable is the ambient
                  opt-in CI uses. Enabling obs never changes solver
                  behavior: chunk cadence, dispatch counts and
                  compiled HLO are identical either way — the
                  committed tpulint budgets are checked with obs
                  enabled to pin that contract.
    trace_dir  -- capture a jax.profiler device trace (Perfetto/
                  XPlane) here for the run; spans show up named in it.
                  On backends without a profiler the spans degrade to
                  the host-side timeline in the run log. Env override:
                  DPSVM_TRACE_DIR.
    runlog_dir -- directory for the JSONL run logs (one append-only
                  file per tool and process). Default ./obs_runs; env
                  override DPSVM_OBS_DIR.
    """

    enabled: bool = False
    trace_dir: Optional[str] = None
    runlog_dir: Optional[str] = None

    def replace(self, **kw) -> "ObsConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class SVMConfig:
    """Hyper-parameters and runtime knobs for SMO training.

    Attributes mirroring reference CLI flags (svmTrainMain.cpp:46-58):
      c          -- -c/--cost       (default 1)
      gamma      -- -g/--gamma      (default None -> 1/num_features)
      epsilon    -- -e/--epsilon    (default 0.001)
      max_iter   -- -n/--max-iter   (default 150_000)
      cache_lines-- -s/--cache-size (default 0 = cache OFF; the reference
                    defaults to 10 lines, svmTrainMain.cpp:71. Measured on
                    TPU v5e, the MXU kernel-row matvec over bf16 X runs at
                    ~130us/iter for 60k x 784 — essentially the HBM floor —
                    while the functional LRU's in-loop bookkeeping (slot
                    scatter + hit/miss lax.switch) costs ~130us/iter by
                    itself, so even a 100% hit rate only breaks even. The
                    cache was worth it on the reference's GPUs because
                    sgemv dominated; on the MXU it does not. Set > 0 to
                    re-enable for memory-bound regimes, e.g. very large d.)
    """

    c: float = 1.0
    gamma: Optional[float] = None
    epsilon: float = 1e-3
    max_iter: int = 150_000
    cache_lines: int = 0

    # Kernel family. The reference hardcodes RBF (svmTrain.cu:696-714);
    # linear/poly/sigmoid are capability extensions sharing the same
    # dot-product row machinery. "precomputed" (LibSVM -t 4) treats the
    # training input as the (n, n) Gram matrix itself — single-chip
    # xla/block engines; models carry SV indices, not feature rows
    # (use solve() or the estimators.SVC facade, not the file-model
    # train() path).
    kernel: str = "rbf"
    degree: int = 3
    coef0: float = 0.0

    # Per-class C multipliers (LibSVM -w1 / -w-1; no reference equivalent):
    # the box bound of row i is C * weight_{y_i}. Used for imbalanced
    # classes. Equal weights compile to the identical unweighted program.
    weight_pos: float = 1.0
    weight_neg: float = 1.0

    # Working-set selection rule (no reference equivalent for the second):
    #   "mvp"          -- maximal-violating pair, exactly the reference
    #                     algorithm (global argmin/argmax of f);
    #   "second_order" -- LibSVM/ThunderSVM-style WSS2: i as in mvp, j by
    #                     maximal second-order gain (f_i - f_j)^2 / eta_ij
    #                     using row i's kernel values. Converges to the
    #                     same solution in typically far fewer iterations.
    selection: str = "mvp"

    # Compute engine for the single-chip solver:
    #   "xla"    -- pure XLA ops (reference-parity iteration structure).
    #               The per-pair engine of choice: extreme-C convergence
    #               runs use it (PARITY.md covtype section).
    #   "block"  -- blockwise working-set decomposition (solver/block.py):
    #               one batched MXU pass builds kernel rows for the
    #               `working_set_size` most-violating points, then up to
    #               `inner_iters` pair updates run inside that block.
    #               Same optimum and stopping rule; drastically less HBM
    #               traffic per pair than the per-pair engines — THE
    #               throughput path (the headline bench's engine).
    #   "pallas" -- SUPERSEDED, kept as a working design study: fused
    #               Pallas kernel doing the rank-2 f update and the next
    #               selection in one HBM pass, software-pipelined. Same
    #               optimum as "xla" (iteration count may differ by one —
    #               it skips the reference's final degenerate update),
    #               but MEASURED SLOWER than plain "xla" on real v5e at
    #               the PARITY shapes (2.46 vs 1.33 device-s at 10k,
    #               5.04 vs 3.17 at 32k — the per-iteration pallas_call
    #               launch plus the pipelined seed selection cost more
    #               than the one HBM pass it saves; n ~ 60k is where it
    #               reaches parity). Its fused-pass idea is what pays off
    #               at block granularity instead: ops/pallas_fold_select
    #               (fused_fold) applies it per ROUND, where one pass
    #               amortizes over `inner_iters` pair updates. Prefer
    #               "block" for speed, "xla" for per-pair runs.
    engine: str = "xla"

    # Block-engine shape knobs (ignored by other engines). working_set_size
    # (q) is the block height; inner_iters = 0 means "2*q" (measured best
    # across 60k x 784 and 500k x 54 sweeps, tools/sweep_block.py: the
    # subproblem usually closes its local gap before the budget, so a
    # larger cap costs nothing when unused and saves a full-X round when
    # the block still has violators; q pairs leaves work on the table).
    working_set_size: int = 128
    inner_iters: int = 0

    # Pair batching (no reference equivalent): execute several
    # coordinate-disjoint pair updates per serial loop trip, selected
    # from the SAME (stale) extrema reductions with every update's
    # (b_hi, b_lo) corrected to the post-previous-updates gradient —
    # exact descent steps on then-violating pairs, so the optimum is
    # unchanged while the pair SEQUENCE (and exact counts to
    # convergence) differ from pair_batch=1.
    #   engine='block': 2/4 = the subproblem's inner trip runs the MVP
    #     pair plus 1/3 further stale-ranked disjoint pairs
    #     (ops/pallas_subproblem.py). Cuts the serial dependency chain
    #     per pair in the chain-bound regimes (measured at 2; 4 is the
    #     round-5 extension — measure before adopting).
    #   engine='xla':   2/4/8 = the micro-batched per-pair executor
    #     (solver/smo.py _run_chunk_micro): one selection pass + one
    #     batched kernel-row pass + k unrolled scalar pair updates + one
    #     rank-2k fold per trip, amortizing the latency-bound loop
    #     body's fixed cost over k pairs. The extreme-C tail engine
    #     (PARITY.md covtype rows), usually with the resident Gram.
    # mvp selection only (the nu trainers, which re-select to the
    # per-class rule internally, fall back to single-pair rather than
    # rejecting the config).
    pair_batch: int = 1

    # Fleet batching for MANY independent binary subproblems sharing one
    # X (solver/fleet.py; no reference equivalent — LIBSVM-class tools
    # train one subproblem at a time). Up to fleet_size problems stack
    # along a leading axis and train inside ONE compiled while_loop with
    # per-problem convergence masking: multiclass OvR/OvO submodels
    # (models/multiclass.py routes eligible configs automatically) and
    # C-sweeps (estimators.svc_c_sweep) collapse from K dispatch
    # sequences to ceil(K / fleet_size). The fleet executor always runs
    # the per-pair MVP iteration; 1 disables routing (sequential
    # solves). Power of two so OvO's chunked fleets bucket to one
    # compiled shape.
    fleet_size: int = 16

    # Fused fold+select for the block engine (ops/pallas_fold_select.py):
    # the round's gradient fold and the NEXT round's working-set
    # selection run as ONE Pallas pass over f, removing the separate
    # full-n mask+top-k stage from the latency-bound round chain
    # (PROFILE.md). None = auto (on for real TPUs); True forces it (CPU
    # tests run the kernel in interpret mode); False forces the plain
    # two-pass round. Applies to selection in {mvp, second_order} with
    # feature kernels; nu / active-set / precomputed use the plain path.
    fused_fold: Optional[bool] = None

    # ONE-HBM-PASS fused round for the single-chip block engine
    # (ops/pallas_round.py + solver/block.py run_chunk_block_fusedround;
    # ISSUE 12 / ROADMAP item 1's single-chip leg). Extends fused_fold's
    # fusion to the WHOLE round body: the working-set row gather runs as
    # in-kernel dynamic-slice DMAs inside the kernel-row pass (one
    # streaming pass over X builds the (q, n) kernel rows with the
    # (q, q) Gram block riding grid step 0 — no qx round-trip, no
    # separate dots buffer, no standalone Gram launch), and the fold
    # contraction coef @ K(W, :) runs in-register inside the fold+select
    # pass — so select -> gather -> Gram -> fold touches X and the O(n)
    # vectors exactly once per round instead of three-plus times.
    # Trajectories are BITWISE identical to the fused-fold engine
    # (tests/test_fused_round.py pins it; interpret-mode kernels on the
    # CPU harness).
    #   None  -- auto: solver/block.py fused_round_pays — currently OFF
    #            everywhere pending the device-session measurement (the
    #            pipeline_rounds / ring_pays discipline);
    #   True  -- force on (CPU tests/A-B probes run interpret mode);
    #   False -- force off.
    # Single-chip block-engine knob; same applicability contract as
    # fused_fold (selection in {mvp, second_order}, feature kernels,
    # q/2 <= n_pad/128 — contract misses fall back to the plain path);
    # supersedes fused_fold when both would engage; the mesh runners
    # keep their own per-shard fused fold+select machinery and ignore
    # it. Composition limits validated below.
    fused_round: Optional[bool] = None

    # Pipelined block rounds (solver/block.py run_chunk_block_pipelined,
    # parallel/dist_block.py pipelined runner; no reference equivalent —
    # the reference's host-driven loop cannot overlap anything): the
    # NEXT round's working-set selection + row gather + Gram build are
    # issued from the PRE-fold gradient and carry no data dependence on
    # the current round's serial subproblem chain, with a corrected-
    # gradient re-rank + gating pass at handoff so every executed update
    # stays exact (stale SELECTION, exact UPDATE — the pair_batch
    # contract lifted to whole rounds). On the mesh this additionally
    # makes the per-round all_gather/psum collectives overlappable —
    # the term docs/SCALING.md carries as the un-shrinkable per-round
    # floor. None = auto (solver/block.py pipeline_pays: currently OFF
    # everywhere pending the device-session measurement); True forces
    # it (CPU tests, A/B probes); False forces the plain serial round.
    # Applies to engine='block', selection in {mvp, second_order},
    # active_set_size=0; supersedes fused_fold when both would apply.
    pipeline_rounds: Optional[bool] = None

    # Shard-parallel working sets for the MESH block engine
    # (parallel/dist_block.py make_block_shardlocal_chunk_runner — the
    # Cascade-SVM / partitioned-parallel-SMO structure, PAPERS.md; no
    # reference equivalent: the reference replicates one working pair on
    # every rank). local_working_sets:
    #   None -- auto: the measured gate (solver/block.py
    #           shardlocal_pays — currently OFF everywhere pending the
    #           device-session measurement, same discipline as
    #           pipeline_rounds);
    #   1    -- one GLOBAL working set per round: exactly the current
    #           mesh engine (make_block_chunk_runner), bit-identical
    #           trajectories (pinned in tests/test_shardlocal.py);
    #   >= 2 -- ON: every chip selects a q-sized working set from its
    #           OWN shard and runs its subproblem chain concurrently
    #           with all other chips — P chains per wall-clock round
    #           instead of P replicas of one chain (the docs/SCALING.md
    #           Amdahl term), reconciled by one touched-rows all_gather
    #           per sync. The value is a switch, not a count: the
    #           concurrent-chain count is always the mesh's device
    #           count. Final convergence is exact regardless — solve_mesh
    #           demotes to the global-working-set engine at the endgame
    #           (gap stalled across a sync window, or below 10*epsilon).
    # sync_rounds (R): local select/solve/fold rounds between
    # cross-shard syncs (Cascade-style). R > 1 divides the per-sync
    # collective DISPATCHES and the stopping handoff by R at the cost of
    # R rounds of cross-shard gradient staleness. Mesh-only knobs; the
    # single-chip solver has one shard and ignores them.
    local_working_sets: Optional[int] = None
    sync_rounds: int = 1

    # Ring-overlapped mesh candidate exchange (ops/ring.py; ISSUE 11 /
    # ROADMAP item 1). The mesh block runners' per-round/per-window
    # candidate all_gather (+ working-set recovery psums) becomes a ring
    # of pltpu.make_async_remote_copy ICI DMAs inside one Pallas kernel:
    # the global/pipelined runners' candidates travel WITH their rows
    # and scalars (zero XLA collectives left in the device-form round
    # body), and the shard-local sync folds each arriving hop in-kernel
    # while later hops' DMAs fly — candidate exchange costs
    # max(DMA, fold matmul) instead of gather-then-compute. Trajectories
    # are BIT-IDENTICAL to the all_gather path (tests/test_ring.py pins
    # it; interpret-mode kernels on the CPU mesh).
    #   None  -- auto: solver/block.py ring_pays — currently OFF
    #            everywhere pending the device-session measurement (the
    #            pipeline_rounds / shardlocal_pays discipline);
    #   True  -- force on (CPU tests/A-B probes run interpret mode);
    #   False -- force the all_gather path.
    # Mesh block-engine knob (>= 2 devices); the single-chip solver has
    # no exchange and ignores it. Composes with pipeline_rounds and
    # local_working_sets; not with active_set_size / fused_fold /
    # precomputed kernels (validated below); the nu trainers fall back
    # to the all_gather path with a warning (models/nusvm.py).
    ring_exchange: Optional[bool] = None

    # bf16 Gram training path (ISSUE 11): store X in bfloat16 with f32
    # MXU accumulation — halving Gram-pass HBM read traffic — but ONLY
    # when the per-problem perturbation analysis says the trajectory is
    # safe: the solver samples C * p90|K_exact - K_bf16| on THIS data
    # (ops/kernels.py bf16_kernel_perturbation, the measured-failure-
    # calibrated bound behind the existing dtype='bfloat16' warning and
    # the serving engine's bf16 union guard) and flips storage to bf16
    # only under BF16_RISK_THRESHOLD. When the bound refuses, the solve
    # stays float32 and says so loudly (stats['bf16_gram'] carries the
    # risk + a fallback note, plus a warning). Unlike dtype='bfloat16'
    # (which always quantizes and merely warns), this is the gated
    # variant — safe to leave on across a sweep. Feature kernels,
    # in-core engines (validated below).
    bf16_gram: bool = False

    # Active-set shrinking for the block engine (0 = off). When > 0, the
    # solver runs cycles of `reconcile_rounds` block rounds whose
    # selection and fold touch only the `active_set_size` most-violating
    # rows, then applies the accumulated deltas to the full gradient with
    # one batched matmul (solver/block.py run_chunk_block_active — the
    # static-shape re-derivation of LibSVM's do_shrinking). Exact: same
    # optimum and stopping rule; pays off when n is large enough that the
    # full-n fold dominates the round (n >> active_set_size).
    #
    # With config.ooc the same knob sizes the OUT-OF-CORE shrunken
    # stream (ISSUE 19, solver/ooc.py): cycles of `reconcile_rounds`
    # rounds restrict selection to the active_set_size most-violating
    # rows and stream ONLY the tiles the active view intersects; each
    # cycle ends with one full-stream gradient reconstruction (the
    # warmstart fold), so the FINAL model meets the identical stopping
    # rule. 0 there defers to ooc_shrink (the auto gate) with an
    # auto-sized view.
    active_set_size: int = 0
    reconcile_rounds: int = 8

    # Extreme-C numerics (no reference equivalent; the reference's fp32
    # incremental gradient silently drifts the same way ours would,
    # svmTrain.cu:98-137 — measured at its covtype stress config c=2048:
    # carried gap 0.005 vs true 1.1 after one 8M-pair leg).
    #
    # compensated: carry the gradient with a Kahan residual (solver/smo.py
    # kahan_add) so each update's fp32 rounding is deferred instead of
    # accumulated — the carried gap then stays honest through tens of
    # millions of pair updates. Costs 3 elementwise vector ops per
    # update/fold (noise on the latency-bound chain). Supported by the
    # xla and block engines, single-chip and mesh.
    #
    # reconstruct_every: > 0 runs the solve in legs of at most this many
    # pair updates; between legs the gradient is recomputed EXACTLY in
    # float64 on the host (solver/reconstruct.py), a regressed leg is
    # rejected and retried at half budget, and convergence is judged on
    # the RECONSTRUCTED gap — the LibSVM gradient-reconstruction move,
    # productized from the round-3 external harness. Use both together
    # for one-call convergence at extreme C (PARITY.md covtype section).
    compensated: bool = False
    reconstruct_every: int = 0

    # Out-of-core training (solver/ooc.py; the TPU re-derivation of the
    # reference's storage hierarchy: its cache.cu LRU of kernel dot rows
    # was what let it scale past device memory). When True, X stays in
    # HOST memory (np array or np.memmap) and never fully materializes
    # in HBM: the block engine's per-round (q, d) x (d, n) gradient fold
    # streams over (ooc_tile_rows, d) tiles with double buffering —
    # tile t+1's async host->HBM device_put overlaps tile t's
    # partial-fold matmul on the MXU — so the trainable-n ceiling moves
    # from "X fits in HBM" to "X fits on the host". Device-resident
    # state is O(n) vectors (f, alpha, y, x_sq) plus the tile pool plus
    # the optional block cache below; the (n, d) matrix itself never is.
    # Engine='block' with selection in {mvp, second_order}; feature
    # kernels only. Bit-identical to the in-core block engine where
    # both fit (tests/test_ooc.py pins it).
    #
    # ooc_tile_rows: rows per streamed tile (the unit of the H2D
    # double buffer; n is padded up to a multiple of it).
    #
    # ooc_cache_lines: extend the solver/cache.py discipline (static-
    # shape data/keys/ticks arrays, scatter-refresh LRU) to the block
    # engine: an (ooc_cache_lines, n) HBM cache of hot kernel DOT rows
    # keyed by training-row index. A round whose whole working set hits
    # skips the tile stream AND the recompute entirely — near
    # convergence the selection concentrates on a stable set of support
    # vectors, exactly the regime Joachims' shrinking exploits. 0 = off;
    # must be >= working_set_size so one round's misses always fit.
    #
    # ooc_shrink (ISSUE 19): Joachims-style active-set shrinking for
    # the TILE STREAM itself — cycles of `reconcile_rounds` rounds keep
    # a static-shape active view of the most-violating rows
    # (active_set_size when > 0, else auto-sized) and stream only the
    # tiles that view intersects; every cycle ends with one
    # full-stream gradient reconstruction (solver/warmstart.py
    # warm_f_rebuild — the same streamed fold), and the engine demotes
    # itself to the exact full-stream path when the gap stalls or
    # nears epsilon, so the final model meets the identical
    # convergence criterion. None = auto (autotune 'ooc_shrink' gate;
    # the CPU seed profile resolves OFF — solver/block.py
    # ooc_shrink_pays); True forces on; False forces off. Single-chip
    # backend only (the mesh tile stream keeps full streams).
    #
    # Running ooc under backend='mesh' (solve_mesh) shards the stream
    # instead: each device owns a padded row shard's tiles (per-device
    # double-buffered H2D), folds locally, and joins the round with one
    # psum of the working set's (q, 5) scalar rows — bitwise equal to
    # the single-chip ooc trajectory (tests/test_ooc.py pins it at 2
    # devices). The mesh stream rejects ooc_cache_lines and shrinking
    # (validated in parallel/dist_smo.py).
    ooc: bool = False
    ooc_tile_rows: int = 8192
    ooc_cache_lines: int = 0
    ooc_shrink: Optional[bool] = None

    # Resident-Gram acceleration for the per-pair engine (no reference
    # equivalent — it is the 100%-hit-rate limit of the reference's LRU
    # row cache, cache.cu). When on, the solver materializes the full
    # (n, n) float32 kernel matrix ON DEVICE once (ops/kernels.py
    # resident_gram) and runs the solve through the precomputed-kernel
    # path: each per-pair iteration's two kernel rows become row GATHERS
    # instead of two full MXU passes over X. This is what makes
    # extreme-C tail convergence affordable — at the accuracy mode's
    # 6-pass matmul precision the per-iteration matvecs dominate
    # (PARITY.md covtype rows). None = auto: on for engine='xla' with a
    # feature kernel when n >= 8192 and the Gram fits ~70% of the
    # device's memory budget (so it never triggers where it cannot fit,
    # e.g. the 60k x 784 headline shape at 14.4 GB). True forces it
    # (any engine but 'pallas'); False disables. The certification /
    # prediction paths still see the original features.
    gram_resident: Optional[bool] = None

    # MXU matmul precision for every solver matmul (dot rows, Gram
    # blocks, folds, x_sq). TPU f32 matmuls default to ONE bfloat16 MXU
    # pass (~1e-3 relative error in the dot values) — measured on the
    # extreme-C stress problem this, not accumulation rounding, is the
    # dominant gradient drift: 6000 pair updates drift the carried f by
    # 0.37 at default vs 1.3e-3 at "highest" (6-pass, ~f32-exact).
    #   None      -- auto: "highest" when compensated or reconstruct_every
    #                request accuracy mode, else the platform default
    #   "default" -- force the platform default (fastest, bf16 passes)
    #   "high"    -- 3-pass bf16 (~tf32 quality)
    #   "highest" -- 6-pass bf16 (~f32 quality)
    matmul_precision: Optional[str] = None

    # Benchmark budget mode (no reference equivalent — but it mirrors how
    # the reference's published numbers were produced: max_iter-capped
    # runs, reference Makefile:74,77). When True the solver IGNORES the
    # convergence test and executes exactly `max_iter` pair updates, so a
    # wall-clock at a pinned iteration budget is a measurement rather
    # than a projection. The returned `converged` still reports the
    # honest stopping rule at `epsilon` on the final state.
    budget_mode: bool = False

    # Automatic fault recovery (SURVEY.md 5.3 — the reference loses the
    # whole run on a rank death): number of automatic retries when a
    # solve's device dispatch dies with a TRANSIENT runtime fault
    # (UNAVAILABLE / ABORTED / ... — solver/smo.py _GRPC_TRANSIENT and
    # _PROSE_TRANSIENT).
    # Each retry clears the compiled-program caches, waits out the
    # runtime's settle time, bumps chunk_iters (static-arg change =>
    # genuinely fresh compile, dodging poisoned server-side compile
    # caches), and resumes from the last checkpoint when checkpoint_path
    # is set (else restarts the attempt). Non-transient errors always
    # propagate immediately. Set 0 on multi-host pods (a single faulted
    # process cannot re-sync its peers; relaunch with --resume instead).
    retry_faults: int = 2

    # Numerics / runtime knobs (no reference equivalent).
    tau: float = 1e-12  # eta clamp (LibSVM-style guard, fixes bug B2)
    # Debug mode (SURVEY.md 5.2: the reference has no sanitizers at all):
    # verify f/alpha stay finite at every chunk boundary and fail loudly
    # with solver context instead of silently diverging.
    check_numerics: bool = False
    dtype: str = "float32"  # storage dtype for X ("float32" | "bfloat16")
    chunk_iters: int = 2048  # SMO iterations per on-device while_loop dispatch
    checkpoint_every: int = 0  # iterations between solver checkpoints; 0 = off
    # Rotating checkpoint retention (ISSUE 15 satellite): keep the K
    # newest generations (path, path.1, ..., path.(K-1)) so a
    # checkpoint corrupted BY the fault being recovered from still
    # leaves an older restorable one; --resume falls back to the
    # newest loadable generation with a loud warning. 1 = the
    # historical overwrite-in-place.
    checkpoint_keep: int = 1
    verbose: bool = False

    # Observability (dpsvm_tpu/obs): run logs, metrics, trace spans.
    # A frozen sub-config so SVMConfig stays hashable; see ObsConfig.
    # NOTE deliberately NOT part of the `observe` predicate that picks
    # the chunk cadence — obs records ride whatever observations the
    # solve was already making (an unobserved solve logs one chunk
    # record), so enabling it cannot change behavior or timing.
    obs: ObsConfig = ObsConfig()

    def c_bounds(self) -> tuple:
        """(c_pos, c_neg): per-class box upper bounds, hashable for jit."""
        return (self.c * self.weight_pos, self.c * self.weight_neg)

    def resolve_gamma(self, num_features: int) -> float:
        """Default gamma = 1/d computed in float (fixes reference bug B1)."""
        if self.gamma is not None:
            return float(self.gamma)
        return 1.0 / float(num_features)

    def __post_init__(self):
        if self.kernel not in KERNELS:
            raise ValueError(f"unknown kernel {self.kernel!r}; expected one of {KERNELS}")
        if self.c <= 0:
            raise ValueError("c must be > 0")
        if self.epsilon <= 0:
            raise ValueError("epsilon must be > 0")
        if self.cache_lines < 0:
            raise ValueError("cache_lines must be >= 0")
        if self.weight_pos <= 0 or self.weight_neg <= 0:
            raise ValueError("class weights must be > 0")
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError("dtype must be 'float32' or 'bfloat16'")
        if self.selection not in ("mvp", "second_order", "nu"):
            # "nu" is internal: per-class MVP selection for the nu duals,
            # set by the models/nusvm.py trainers (the solvers reject it
            # without the feasible warm start those trainers provide).
            raise ValueError(
                "selection must be 'mvp' or 'second_order' (selection='nu' "
                "is internal to train_nusvc/train_nusvr)")
        if self.engine not in ("xla", "pallas", "block"):
            raise ValueError("engine must be 'xla', 'pallas' or 'block'")
        if self.kernel == "precomputed":
            if self.engine == "pallas":
                raise ValueError(
                    "kernel='precomputed' is not implemented for the fused "
                    "pallas per-pair engine (its kernel evaluation is "
                    "baked into the on-chip pass); use engine='xla' or "
                    "'block'")
            if self.cache_lines:
                raise ValueError(
                    "kernel='precomputed' has nothing to cache (rows are "
                    "gathers, not matvecs); set cache_lines=0")
            if self.active_set_size:
                raise ValueError(
                    "kernel='precomputed' does not compose with active-set "
                    "shrinking (the active view re-indexes rows but the "
                    "Gram block gather needs global column ids); set "
                    "active_set_size=0")
        if self.engine == "pallas" and self.selection != "mvp":
            # The fused per-pair Pallas engine pipelines the NEXT mvp
            # selection into the f-update pass (ops/pallas_fused.py);
            # other rules run on the xla or block engines (the block
            # engine supports all three).
            raise ValueError(
                "engine='pallas' supports selection='mvp' only "
                "(use engine='xla' or engine='block')")
        if self.working_set_size < 2:
            raise ValueError("working_set_size must be >= 2")
        if self.inner_iters < 0:
            raise ValueError("inner_iters must be >= 0 (0 = working_set_size)")
        if self.active_set_size < 0:
            raise ValueError("active_set_size must be >= 0 (0 = shrinking off)")
        if self.pipeline_rounds and self.engine != "block":
            raise ValueError(
                "pipeline_rounds is a block-engine knob (the per-pair "
                "engines have no round structure to pipeline; the fused "
                "pallas engine already pipelines per pair); use "
                "engine='block'")
        if self.pipeline_rounds and self.active_set_size:
            raise ValueError(
                "pipeline_rounds does not compose with active_set_size "
                "(the active cycle's restricted rounds already defer "
                "their folds; pipelining them would stack two staleness "
                "contracts) — use one or the other")
        if self.pipeline_rounds and self.selection == "nu":
            raise ValueError(
                "pipeline_rounds supports selection in {'mvp', "
                "'second_order'} (the nu rule's per-class quarters keep "
                "the plain round; same restriction as fused_fold)")
        if self.fused_round:
            if self.engine != "block":
                raise ValueError(
                    "fused_round is a block-engine knob (the per-pair "
                    "engines have no round body to fuse; the fused "
                    "pallas per-pair engine already fuses per pair); "
                    "use engine='block'")
            if self.kernel == "precomputed":
                raise ValueError(
                    "fused_round supports feature kernels only (its "
                    "one-pass kernel evaluates kernel rows from "
                    "streamed features; a precomputed Gram's rows are "
                    "gathers, not matmuls)")
            if self.gram_resident:
                raise ValueError(
                    "fused_round does not compose with "
                    "gram_resident=True (the resident Gram routes the "
                    "solve through the precomputed-kernel branches — "
                    "same constraint as kernel='precomputed')")
            if self.pipeline_rounds:
                raise ValueError(
                    "fused_round does not compose with "
                    "pipeline_rounds=True (the pipelined engine "
                    "prefetches the next selection off the critical "
                    "path; the fused round folds it into the fold "
                    "pass — the two solve the same floor differently) "
                    "— use one or the other")
            if self.active_set_size:
                raise ValueError(
                    "fused_round does not compose with active_set_size "
                    "(the active cycle's restricted rounds defer their "
                    "folds; the fused round's one-pass contract needs "
                    "the full-n fold in-kernel) — use one or the other")
            if self.ooc:
                raise ValueError(
                    "fused_round does not compose with ooc (the ooc "
                    "fold streams host tiles; the fused round's single "
                    "pass assumes X is HBM-resident) — use one or the "
                    "other")
        if self.local_working_sets is not None and self.local_working_sets < 1:
            raise ValueError(
                "local_working_sets must be None (auto), 1 (global "
                "working set — the exact current engine) or >= 2 "
                "(shard-parallel working sets)")
        if self.local_working_sets is not None and self.local_working_sets >= 2:
            if self.engine != "block":
                raise ValueError(
                    "local_working_sets >= 2 is a mesh block-engine knob "
                    "(the per-pair engines have no working set to "
                    "shard-localize); use engine='block'")
            if self.kernel == "precomputed":
                raise ValueError(
                    "local_working_sets >= 2 supports feature kernels "
                    "only (a precomputed Gram's sync fold would need "
                    "global column ids for rows the shard does not own)")
            if self.active_set_size:
                raise ValueError(
                    "local_working_sets >= 2 does not compose with "
                    "active_set_size (the active cycle already runs "
                    "replicated collective-free rounds; stacking the "
                    "two staleness contracts is untested) — use one or "
                    "the other")
            if self.pipeline_rounds:
                raise ValueError(
                    "local_working_sets >= 2 does not compose with "
                    "pipeline_rounds=True (shard-local rounds have no "
                    "per-round collectives left to hide; the two "
                    "engines solve the same floor differently) — use "
                    "one or the other")
            if self.budget_mode:
                raise ValueError(
                    "local_working_sets >= 2 does not compose with "
                    "budget_mode: P shards spend the pair budget "
                    "concurrently, so the exact-max_iter contract "
                    "cannot hold — use the global working set there")
        if self.ring_exchange:
            if self.engine != "block":
                raise ValueError(
                    "ring_exchange is a mesh block-engine knob (the "
                    "per-pair mesh engine has no block exchange to "
                    "ring); use engine='block'")
            if self.kernel == "precomputed":
                raise ValueError(
                    "ring_exchange supports feature kernels only (a "
                    "precomputed Gram has no rows for the candidate "
                    "ring to carry; its symmetric round is already "
                    "collective-light)")
            if self.ooc:
                raise ValueError(
                    "ring_exchange does not compose with ooc (the mesh "
                    "ooc round folds host-streamed tiles — kernel rows "
                    "never live on device long enough for a candidate "
                    "ring to carry them)")
            if self.active_set_size:
                raise ValueError(
                    "ring_exchange does not compose with "
                    "active_set_size (the active cycle's replicated "
                    "inner rounds are already collective-free; its "
                    "per-cycle recovery keeps the psum path) — use one "
                    "or the other")
            if self.fused_fold:
                raise ValueError(
                    "ring_exchange does not compose with "
                    "fused_fold=True (the fused runner's per-row "
                    "candidate kernel feeds its own all_gather "
                    "epilogue) — use one or the other")
        if self.bf16_gram:
            if self.kernel == "precomputed":
                raise ValueError(
                    "bf16_gram supports feature kernels only (a "
                    "precomputed Gram carries kernel VALUES — rounding "
                    "those is a different contract from rounding "
                    "features; quantize the matrix yourself if that is "
                    "what you want)")
            if self.dtype == "bfloat16":
                raise ValueError(
                    "dtype='bfloat16' already stores X in bfloat16 "
                    "(ungated, warning-only); bf16_gram is the "
                    "perturbation-gated variant — use one or the other")
            if self.ooc:
                raise ValueError(
                    "bf16_gram does not compose with ooc (the ooc tile "
                    "stream stages float32 host tiles; quantized "
                    "streaming is its own contract) — use one or the "
                    "other")
        if self.sync_rounds < 1:
            raise ValueError("sync_rounds must be >= 1")
        if self.sync_rounds > 1 and (self.local_working_sets is None
                                     or self.local_working_sets < 2):
            raise ValueError(
                "sync_rounds > 1 amortizes the shard-local engine's "
                "sync collectives; it needs local_working_sets >= 2 "
                "(with the global working set there is no sync to "
                "amortize)")
        if self.pair_batch not in (1, 2, 4, 8):
            raise ValueError("pair_batch must be 1, 2, 4 or 8")
        if self.pair_batch > 1:
            if self.selection != "mvp":
                raise ValueError(
                    "pair_batch > 1 is an mvp-selection feature "
                    "(second_order/nu pairings pick partners by rules "
                    "the batched extra slots do not implement)")
            if self.engine == "pallas":
                raise ValueError(
                    "pair_batch > 1 is not implemented for the fused "
                    "pallas per-pair engine (use engine='xla' or 'block')")
            if self.engine == "block" and self.pair_batch > 4:
                raise ValueError(
                    "the block subproblem implements pair_batch up to 4 "
                    "(ops/pallas_subproblem.py); pair_batch=8 is the "
                    "per-pair micro-batch executor only (engine='xla', "
                    "solver/smo.py _run_chunk_micro)")
        if (self.fleet_size < 1 or self.fleet_size > 64
                or self.fleet_size & (self.fleet_size - 1)):
            raise ValueError(
                "fleet_size must be a power of two in [1, 64] (the fleet "
                "executor buckets problem counts to powers of two so "
                "chunked OvO fleets share one compiled shape; 1 = "
                "sequential solves)")
        if self.active_set_size and self.engine != "block":
            raise ValueError(
                "active_set_size (shrinking) is a block-engine knob; the "
                "per-pair engines already touch O(1) rows per iteration "
                "(use engine='block')")
        if self.reconcile_rounds < 1:
            raise ValueError("reconcile_rounds must be >= 1")
        if self.reconstruct_every < 0:
            raise ValueError("reconstruct_every must be >= 0 (0 = off)")
        if self.reconstruct_every and self.budget_mode:
            raise ValueError(
                "budget_mode runs exactly max_iter pairs in one dispatch "
                "sequence; reconstruction legs re-judge convergence and "
                "would break the pinned budget — use one or the other")
        if self.compensated and self.engine == "pallas":
            raise ValueError(
                "compensated gradient carry is implemented for the xla and "
                "block engines (the fused pallas per-pair engine bakes its "
                "f update into the on-chip pass); use engine='xla' or "
                "'block'")
        if self.gram_resident:
            if self.engine == "pallas":
                raise ValueError(
                    "gram_resident is not implemented for the fused pallas "
                    "per-pair engine (its kernel evaluation is baked into "
                    "the on-chip pass); use engine='xla' or 'block'")
            if self.kernel == "precomputed":
                raise ValueError(
                    "kernel='precomputed' already IS a resident Gram; "
                    "leave gram_resident unset")
            if self.active_set_size:
                raise ValueError(
                    "gram_resident does not compose with active-set "
                    "shrinking (same constraint as kernel='precomputed': "
                    "the active view re-indexes rows but the Gram block "
                    "gather needs global column ids); set "
                    "active_set_size=0")
        if self.ooc:
            if self.engine != "block":
                raise ValueError(
                    "ooc (out-of-core streaming) is a block-engine path "
                    "(the per-pair engines would stream the full X per "
                    "PAIR instead of per round); use engine='block'")
            if self.kernel == "precomputed":
                raise ValueError(
                    "ooc supports feature kernels only (a precomputed "
                    "(n, n) Gram matrix is the thing that does not fit "
                    "— recompute kernels from streamed features instead)")
            if self.selection == "nu":
                raise ValueError(
                    "ooc supports selection in {'mvp', 'second_order'} "
                    "(the nu trainers fall back to the in-core engines)")
            if self.gram_resident:
                raise ValueError(
                    "ooc and gram_resident are opposite regimes (the "
                    "resident Gram assumes O(n^2) fits HBM; ooc assumes "
                    "even O(n d) does not) — use one or the other")
            if self.active_set_size and self.ooc_shrink is False:
                raise ValueError(
                    "active_set_size > 0 with ooc REQUESTS the shrunken "
                    "tile stream (it sizes the active view); "
                    "ooc_shrink=False forces it off — drop one of the "
                    "two")
            if self.pipeline_rounds:
                raise ValueError(
                    "ooc does not compose with pipeline_rounds (the ooc "
                    "round's overlap is the H2D-vs-MXU double buffer "
                    "inside the fold; the next round's selection needs "
                    "the streamed fold complete) — use one or the other")
            if self.fused_fold:
                raise ValueError(
                    "ooc does not compose with fused_fold=True (the "
                    "fused fold+select pass assumes the full-n fold "
                    "happens in one kernel; the ooc fold is tiled by "
                    "design) — leave fused_fold unset")
            if self.local_working_sets is not None:
                raise ValueError(
                    "the ooc round keeps ONE global working set (the "
                    "mesh ooc stream shards tiles, not selection); "
                    "leave local_working_sets unset")
            if self.reconstruct_every:
                raise ValueError(
                    "ooc does not compose with reconstruct_every (the "
                    "f64 reconstruction legs re-gather the full X "
                    "host-side; run them on the in-core engines)")
        if self.ooc_shrink is not None and not self.ooc:
            raise ValueError(
                "ooc_shrink gates the ooc shrunken tile stream; set "
                "ooc=True (in-core shrinking is active_set_size on the "
                "block engine)")
        if self.ooc_tile_rows < 8:
            raise ValueError("ooc_tile_rows must be >= 8")
        if self.ooc_cache_lines < 0:
            raise ValueError("ooc_cache_lines must be >= 0 (0 = off)")
        if self.ooc_cache_lines and not self.ooc:
            raise ValueError(
                "ooc_cache_lines is the ooc block cache's size; set "
                "ooc=True (the in-core block engine's working set IS "
                "its reuse mechanism, and the per-pair LRU is "
                "cache_lines)")
        if self.ooc_cache_lines and \
                self.ooc_cache_lines < self.working_set_size:
            raise ValueError(
                "ooc_cache_lines must be >= working_set_size (one "
                "round's scatter-refresh writes up to working_set_size "
                "rows at once; a smaller cache would evict lines the "
                "same round wrote) — raise ooc_cache_lines or set 0")
        if self.matmul_precision not in (None, "default", "high", "highest"):
            raise ValueError(
                "matmul_precision must be None (auto), 'default', 'high' "
                "or 'highest'")
        if self.retry_faults < 0:
            raise ValueError("retry_faults must be >= 0 (0 = no retry)")
        if not 1 <= self.checkpoint_keep <= 99:
            raise ValueError(
                "checkpoint_keep must be in [1, 99] (1 = single "
                "overwritten checkpoint; K keeps K rotating "
                "generations — the resume fallback scans suffixes "
                ".1..99)")
        if self.chunk_iters < 1:
            raise ValueError("chunk_iters must be >= 1")
        if self.max_iter > 2 ** 31 - 1:
            raise ValueError(
                "max_iter must fit int32 (the on-device pair counters "
                "are int32); split larger budgets across resumed solves "
                "(checkpoint_path + resume)")

    def resolve_precision(self) -> Optional[str]:
        """The jax.default_matmul_precision value the solvers apply, or
        None for the platform default. Auto (None) escalates to 'highest'
        whenever accuracy mode is requested (compensated gradients or
        reconstruction legs): running certification legs over ~1e-3-
        relative bf16 dot products would waste them."""
        if self.matmul_precision is None:
            return ("highest" if (self.compensated or self.reconstruct_every)
                    else None)
        return None if self.matmul_precision == "default" else self.matmul_precision

    def replace(self, **kw) -> "SVMConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Runtime knobs for the persistent serving engine (serve.py
    PredictServer) — the inference-side sibling of SVMConfig.

    buckets: power-of-two query micro-batch sizes. Incoming requests are
      merged and padded to the smallest bucket that fits (XLA executors
      are shape-keyed: without bucketing, every distinct request size
      pays a fresh compile — the same discipline as training's pad_to
      buckets). Batches beyond the largest bucket loop over it.
      ``None`` resolves through the DeviceProfile ``serve_buckets``
      probe (serve.resolve_buckets — the solver's resolve_auto_gate
      discipline, ISSUE 17): with an authoritative pays verdict the v2
      engine AUTO-APPLIES its own occupancy suggestion
      (engine_core.suggest_buckets) between serving legs, with full
      provenance in the snapshot; without one it serves the default
      ladder. An explicit tuple always wins — no profile, no
      auto-apply.
    union_storage: SV-union storage precision — "f32", "bf16", "int8"
      or "auto" (ISSUE 17). Subsumes ``dtype``: None (default)
      derives from it (float32 -> "f32", bfloat16 -> "bf16") so
      existing configs behave identically. "bf16" halves the
      resident-union HBM footprint and kernel-matmul read bandwidth;
      "int8" (symmetric per-row quantization with f32 scales,
      ops/kernels.quantize_rows_int8) cuts union bytes 4x over f32
      with i32-exact MXU accumulation dequantized into the f32
      decision algebra. Both sit behind the calibrated serving guard
      (serve.resolve_union_storage): the decision-sum perturbation
      bound max-column ``||coef||_1 * p90|dK|`` must clear
      BF16_RISK_THRESHOLD or staging REFUSES the narrow storage —
      loudly, falling back to f32 — per model. "auto" tries int8,
      then bf16, then f32, accepting the narrowest storage the bound
      clears (silently — auto is a request to pick, not a promise).
      Risk-routed f64 columns always see the UNQUANTIZED union.
    dtype: legacy SV-union storage dtype knob ("float32"/"bfloat16"),
      kept for back-compat; ``union_storage`` supersedes it when set.
    precision: "auto" consults predict.decision_risk per submodel and
      routes extreme-|coef| columns to the exact host float64 path
      (predict.AUTO_F64_RISK); "float32" forces the device path;
      "float64" forces the host path for every column.
    num_devices: >1 shards the SV union (rows) over a data mesh and
      psums partial decision columns — inference memory scales with
      device count, like training's X sharding. Both serving engines
      honor it: the v1 PredictServer at staging, and the v2
      ServingEngine's union groups (each coalescing family's stacked
      coefficient operand row-shards with its union; the bucket
      dispatch stays ONE kernel matmul + one psum per batch,
      bitwise-pinned against the single-chip group by
      tests/test_serve_replicas.py).
    warm_start: pre-compile (and pre-touch) every bucket executor at
      construction so the first live request never pays a compile.
    max_pending: queued query rows before enqueue() forces a flush —
      bounds host memory under offered overload.
    metrics_port: when not None, serve an OpenMetrics/Prometheus text
      endpoint (GET /metrics, stdlib http.server thread — no new deps;
      dpsvm_tpu/obs/export.py) with the engine's counters, latency
      summaries, SLO-attainment gauges and compile count. 0 binds an
      ephemeral port (read it from ``server.exporter.port``); None
      (default) runs no endpoint. Scrapes only READ host-held
      instruments — they can never add a device dispatch.
    metrics_host: bind address for the endpoint. Default 127.0.0.1 —
      loopback-only, the safe default for a plaintext unauthenticated
      endpoint; set "0.0.0.0" (or a specific interface) to let a
      remote Prometheus scrape it.
    slo_ms: per-request latency objective in milliseconds for the
      ``serve_slo_attainment`` gauge: the fraction of the recent
      request-latency window at or under this bound (1.0 when the
      window is empty — vacuously attained).
    deadline_ms: default per-request deadline for the v2 serving
      engine (dpsvm_tpu/serving) — requests completed past submit +
      deadline_ms count as deadline misses, and requests whose
      deadline already passed at batch-forming time are SHED with an
      explicit ``expired`` verdict instead of growing the queue.
      None (default) = no deadline discipline; per-request
      ``submit(..., deadline_ms=...)`` overrides. Distinct from
      slo_ms, which is purely an observability threshold and never
      changes scheduling.
    dispatch_timeout_ms: dispatch WATCHDOG for the v2 engine (ISSUE
      13): the bounded wait on the AsyncDispatcher's in-flight batch.
      A batch not materialized within this bound — a wedged device
      dispatch, the one failure mode that would otherwise hang the
      pump thread forever — is FAILED with explicit per-request
      'failed' verdicts and a per-model serve_dispatch_failures
      counter, and the engine keeps serving subsequent batches. None
      (default) = unbounded wait (the pre-watchdog behavior).
    listen: "HOST:PORT" for the network front door (ISSUE 15,
      dpsvm_tpu/serving/server.py): a persistent-connection TCP
      endpoint speaking the length-prefixed binary frame protocol
      (serving/wire.py) in front of the v2 engine. Port 0 binds an
      ephemeral port (read it from ``server.port``). None (default) =
      no network endpoint (in-process submit only). Like
      metrics_host, prefer loopback unless the network is trusted —
      the protocol is plaintext and unauthenticated.
    admission_max_rows: ADMISSION CONTROL bound for the front door:
      a request arriving while the engine already holds this many
      queued rows is REJECTED immediately with an explicit wire
      verdict and a ``retry_after_ms`` hint, instead of buffering
      without bound (the engine-internal ``max_pending`` backpressure
      still guards in-process callers). None (default) = use
      ``max_pending``. Must not exceed max_pending (admission must
      trip BEFORE the blocking in-engine backpressure).
    admission_retry_ms: base of the ``retry_after_ms`` hint on
      rejected verdicts; the hint scales with queue overshoot
      (deterministic — the client backoff tests pin it).
    conn_read_timeout_ms / conn_write_timeout_ms: per-connection
      socket timeouts on the front door. The read timeout bounds
      slow-loris and dead-peer cost (an idle or half-open connection
      is closed after this long with no complete frame); the write
      timeout bounds a stalled reader (a verdict write blocked this
      long kills ONLY that connection and counts its verdicts
      undeliverable — the pump thread is never the one blocked).
    max_frame_bytes: upper bound on a frame payload, checked from the
      fixed-size header BEFORE any allocation — a hostile length
      prefix costs one connection, never server memory.
    journal_path: registry JOURNAL for the v2 engine (ISSUE 13): a
      JSON file atomically rewritten on every register/swap/unregister
      with the live {name -> model path + version} set. A restarting
      ServingEngine constructed with the same path REPLAYS it through
      the normal validate-stage-warm registration path, so a crashed
      or killed server rehydrates its exact live model set (versions
      included) with zero operator action. Only file-backed models
      journal (in-memory model objects cannot be replayed). None
      (default) = no journal.
    replicas: number of v2 ServingEngine replicas behind ONE network
      front door (serving/replicas.py ReplicaFleet). Each replica owns
      its scheduler, staged union groups and dispatcher; the front
      door's pump/admission layer routes each accepted frame to one
      replica, the shared registry journal keeps swap coordinated
      across all of them, and per-replica drain makes rolling restarts
      a policy instead of an outage. >1 requires ``listen`` (the fleet
      exists to scale the wire endpoint; in-process callers hold one
      engine). The five-verdict wire contract and the exact
      frames_accepted == sum(verdicts) accounting are unchanged at any
      replica count.
    device_floor_us_per_row: serial per-dispatch device-time floor in
      microseconds per PADDED row, applied at materialization by the
      v2 engine's AsyncDispatcher. Models an accelerator whose device
      time — not host orchestration — bounds throughput: each
      replica's emulated device is serial (a dispatch starts after the
      previous one's emulated completion). This is the CPU-harness
      knob behind ``loadgen --net --replicas``: on a host-bound CI box
      the replica frontier would otherwise measure host-CPU
      contention, not front-door scale-out. The floor is stamped into
      BENCH_SERVE artifacts (``device_emulation``) so a gated number
      can never silently mix regimes. None (default) = no floor (real
      device time only).
    """

    buckets: Optional[tuple] = (16, 64, 256, 1024, 4096)
    dtype: str = "float32"
    union_storage: Optional[str] = None
    precision: str = "auto"
    num_devices: int = 1
    warm_start: bool = True
    max_pending: int = 65536
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    slo_ms: float = 50.0
    deadline_ms: Optional[float] = None
    dispatch_timeout_ms: Optional[float] = None
    journal_path: Optional[str] = None
    listen: Optional[str] = None
    replicas: int = 1
    device_floor_us_per_row: Optional[float] = None
    admission_max_rows: Optional[int] = None
    admission_retry_ms: float = 50.0
    conn_read_timeout_ms: float = 30000.0
    conn_write_timeout_ms: float = 10000.0
    max_frame_bytes: int = 64 * 1024 * 1024
    # Observability (dpsvm_tpu/obs): serve run logs + trace spans.
    # Bucket latency HISTOGRAMS are always on (they replaced the old
    # bounded timing deques at identical cost); this only gates the
    # run-log/trace layer.
    obs: ObsConfig = ObsConfig()

    def __post_init__(self):
        if self.buckets is not None:
            if not self.buckets:
                raise ValueError(
                    "buckets must be non-empty (None = resolve via the "
                    "autotune serve_buckets profile gate)")
            bs = tuple(int(b) for b in self.buckets)
            if any(b < 1 or (b & (b - 1)) for b in bs):
                raise ValueError(
                    f"buckets must be powers of two, got "
                    f"{self.buckets!r} (XLA executors are shape-keyed; "
                    "arbitrary sizes would compile per request size)")
            if list(bs) != sorted(set(bs)):
                raise ValueError("buckets must be strictly ascending")
            object.__setattr__(self, "buckets", bs)
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError("dtype must be 'float32' or 'bfloat16'")
        if self.union_storage is not None and self.union_storage not in (
                "f32", "bf16", "int8", "auto"):
            raise ValueError(
                "union_storage must be 'f32', 'bf16', 'int8' or 'auto' "
                "(None = derive from the legacy dtype knob)")
        if self.precision not in ("auto", "float32", "float64"):
            raise ValueError(
                "precision must be 'auto', 'float32' or 'float64'")
        if self.num_devices < 1:
            raise ValueError("num_devices must be >= 1")
        if self.buckets is not None \
                and self.max_pending < self.buckets[-1]:
            raise ValueError(
                "max_pending must be at least the largest bucket "
                f"({self.buckets[-1]})")
        if self.buckets is None and self.max_pending < 4096:
            raise ValueError(
                "max_pending must be at least 4096 with buckets=None "
                "(the auto-resolved ladder may include the default top "
                "bucket)")
        if self.metrics_port is not None and not (
                0 <= self.metrics_port <= 65535):
            raise ValueError(
                "metrics_port must be None (no endpoint), 0 "
                "(ephemeral) or a valid TCP port")
        if not self.metrics_host:
            raise ValueError(
                "metrics_host must be a bind address (default "
                "127.0.0.1; use 0.0.0.0 for remote scrapes)")
        if self.slo_ms <= 0:
            raise ValueError("slo_ms must be > 0")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                "deadline_ms must be > 0 (None = no deadlines)")
        if self.dispatch_timeout_ms is not None \
                and self.dispatch_timeout_ms <= 0:
            raise ValueError(
                "dispatch_timeout_ms must be > 0 (None = unbounded "
                "dispatch wait, no watchdog)")
        if self.journal_path is not None and not self.journal_path:
            raise ValueError(
                "journal_path must be a file path (None = no registry "
                "journal)")
        if self.listen is not None:
            host, sep, port = str(self.listen).rpartition(":")
            if not sep or not host or not port.isdigit() \
                    or not (0 <= int(port) <= 65535):
                raise ValueError(
                    f"listen must be 'HOST:PORT' (port 0 = ephemeral), "
                    f"got {self.listen!r}")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.replicas > 1 and self.listen is None:
            raise ValueError(
                "replicas > 1 requires listen (the replica fleet "
                "scales the network front door; in-process callers "
                "hold a single engine)")
        if self.device_floor_us_per_row is not None \
                and self.device_floor_us_per_row <= 0:
            raise ValueError(
                "device_floor_us_per_row must be > 0 (None = no "
                "emulated device-time floor)")
        if self.admission_max_rows is not None:
            if self.admission_max_rows < 1:
                raise ValueError(
                    "admission_max_rows must be >= 1 (None = "
                    "max_pending)")
            if self.admission_max_rows > self.max_pending:
                raise ValueError(
                    "admission_max_rows must not exceed max_pending "
                    f"({self.max_pending}): admission rejects must "
                    "trip BEFORE the blocking in-engine backpressure")
        if self.admission_retry_ms <= 0:
            raise ValueError("admission_retry_ms must be > 0")
        if self.conn_read_timeout_ms <= 0 \
                or self.conn_write_timeout_ms <= 0:
            raise ValueError(
                "conn_read_timeout_ms / conn_write_timeout_ms must be "
                "> 0 (they bound slow-loris and stalled-reader cost)")
        if self.max_frame_bytes < 4096:
            raise ValueError(
                "max_frame_bytes must be >= 4096 (smaller would "
                "refuse even a one-row request frame)")

    def listen_addr(self) -> tuple:
        """('host', port) from the validated listen spec."""
        host, _, port = str(self.listen).rpartition(":")
        return host, int(port)

    def effective_union_storage(self) -> str:
        """The REQUESTED union storage: the union_storage knob when
        set, else derived from the legacy dtype knob (float32 ->
        'f32', bfloat16 -> 'bf16') so pre-ISSUE-17 configs behave
        identically. What actually stages is per model — the serving
        storage guard (serve.resolve_union_storage) may refuse a
        narrow request back to f32."""
        if self.union_storage is not None:
            return self.union_storage
        return "bf16" if self.dtype == "bfloat16" else "f32"

    def replace(self, **kw) -> "ServeConfig":
        return dataclasses.replace(self, **kw)
