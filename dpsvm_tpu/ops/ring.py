"""Ring-overlapped mesh candidate exchange (ROADMAP item 1, ISSUE 11).

The mesh engines' per-round/per-window sync is a serial ``lax.all_gather``
dispatch sequence sitting on the critical path between selection and the
Gram matmul (parallel/dist_block.py) — exactly the exposed-communication
structure Cao et al.'s parallel SMO and Catanzaro et al.'s GPU SMO
(PAPERS.md) name as the scaling limiter once per-chip compute is fast.
This module re-expresses that exchange as a ring of
``pltpu.make_async_remote_copy`` ICI DMAs inside ONE Pallas kernel
(SNIPPETS.md [1]/[2], the jax distributed-Pallas pattern):

``ring_gather``
    The candidate exchange for the global/pipelined runners: each shard's
    per-side top-h candidate block — rows, per-row scalars, score and
    global id packed into (L, lanes) f32 — travels P-1 leftward hops,
    every arrival landing directly in its absolute-device-id slot of the
    (P, L, lanes) output. The output is ordered exactly like
    ``lax.all_gather``'s leading axis, so the downstream global top-h /
    dedup epilogue is the SAME code as the all_gather path and the
    training trajectory is bit-identical (pinned in tests/test_ring.py).
    Because the candidate block carries the rows and scalars themselves,
    the round's separate (q, d) + (q, S) working-set recovery psums
    disappear entirely — the device-form round body has ZERO XLA
    collectives (the tpulint ``mesh_chunk_ring`` budget pins it).

``ring_fold_window``
    The shard-local engine's sync: the (R*q, d+3) touched-row window
    rides the same ring, and each arriving hop is folded into the local
    gradient IN-KERNEL — the grid is (P-1 hops, n_loc/tile tiles), hop
    h's fold matmuls run while nothing blocks the already-started DMAs
    of later hops' upstream senders, so on device the sync costs
    max(DMA, fold matmul) per hop instead of gather-then-fold. The fold
    order matches dist_block.py's rotation (right neighbor first), the
    per-tile fold splits only the OUTPUT dim of the (R*q, n_loc) fold
    matmul, and the Kahan step is solver/smo.py's kahan_add — so the
    folded gradient is bit-identical to the all_gather path's
    (tests/test_ring.py pins exact equality).

Correctness/portability contract (the established pattern of the three
existing Pallas kernels): ``interpret=True`` runs the kernels on the CPU
vdev mesh for tier-1 tests. jax 0.4.37's interpreter DISCHARGES each
remote DMA into an ``all_gather``-based exchange (jax
pallas/mosaic/primitives.py dma_start_discharge_rule) — pure data
movement, so trajectories stay bit-identical, but the interpret-mode HLO
necessarily contains emulation collectives. The "ring hops are DMAs, not
XLA collectives" contract is therefore pinned on the DEVICE form: tpulint
traces the runners with ``interpret=False`` and budgets the jaxpr-level
collective-primitive and dma_start counts (analysis/hlo_facts.py
device_form_facts). Slot discipline: every block lands in its own
device-id-indexed output slot, written exactly once per device — no slot
reuse, hence no overwrite hazard however far upstream senders run ahead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dpsvm_tpu.parallel.mesh import DATA_AXIS

#: tile-row candidates for the in-kernel fold (largest divisor wins; a
#: shard whose n_loc none of these divide folds in one tile). 128-lane
#: multiples keep the (1, tile) f blocks on the TPU vreg grid.
_FOLD_TILES = (2048, 1024, 512, 256, 128)


def fold_tile_rows(n_loc: int) -> int:
    """Rows per in-kernel fold tile: the largest _FOLD_TILES divisor of
    n_loc, else n_loc itself (single-tile fold — the small-shard/test
    regime)."""
    for t in _FOLD_TILES:
        if n_loc % t == 0 and n_loc >= t:
            return t
    return n_loc


def _neighbor_barrier(ndev: int, axis_name: str):
    """Device-only entry barrier: a remote write may not land before its
    target has entered the kernel, so signal both neighbors and wait for
    both signals (the jax distributed-Pallas guide's local barrier).
    Never traced under interpret mode — the interpreter's lockstep
    discharge makes it unnecessary (and its barrier semaphore has no
    interpret path on this jax)."""
    my = lax.axis_index(axis_name)
    barrier = pltpu.get_barrier_semaphore()
    for nb in (lax.rem(my + 1, ndev), lax.rem(my + ndev - 1, ndev)):
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=nb,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)


def _compiler_params():
    """Mosaic params for the device path: the barrier semaphore needs a
    collective_id. Name skew guard: jax 0.4.37 spells it
    TPUCompilerParams (newer jax renames it CompilerParams); DCE safety
    comes from the kernels' real outputs, not a side-effect flag (this
    jax's params have none)."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(collective_id=0)


def _ring_gather_kernel(blk_ref, out_ref, local_sem, send_sem, recv_sem,
                        *, ndev: int, axis_name: str, interpret: bool):
    my = lax.axis_index(axis_name)
    left = lax.rem(my + ndev - 1, ndev)
    if not interpret:
        _neighbor_barrier(ndev, axis_name)
    # Own block into its absolute slot first: hop 0 forwards it.
    cp = pltpu.make_async_copy(blk_ref, out_ref.at[my], local_sem)
    cp.start()
    cp.wait()

    def hop(h, carry):
        # Forward the slot that arrived at hop h-1 (h=0: our own block)
        # to the left neighbor's SAME absolute slot; .wait() covers our
        # send AND the symmetric arrival from the right neighbor, which
        # lands hop h's block in out[(my + h + 1) % ndev]. Each slot is
        # written exactly once per device — no reuse, no overwrite race.
        src = lax.rem(my + h, ndev)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[src], dst_ref=out_ref.at[src],
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=left, device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()
        return carry

    lax.fori_loop(0, ndev - 1, hop, 0)


@functools.partial(jax.jit,
                   static_argnames=("ndev", "axis_name", "interpret"))
def ring_gather(block, ndev: int, axis_name: str = DATA_AXIS,
                interpret: bool = False):
    """Ring all-gather of one (L, lanes) f32 block per shard.

    Returns (ndev, L, lanes) ordered by absolute device id — the same
    layout (and, being pure data movement, the same bits) as
    ``lax.all_gather(block, axis_name)`` — via P-1 leftward
    ``make_async_remote_copy`` hops instead of an XLA collective.
    Must be called inside a shard_map over ``axis_name``.
    """
    l, lanes = block.shape
    kern = functools.partial(_ring_gather_kernel, ndev=ndev,
                             axis_name=axis_name, interpret=interpret)
    kw = {} if interpret else {"compiler_params": _compiler_params()}
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((ndev, l, lanes), block.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 3,
        interpret=interpret,
        **kw,
    )(block)


def _ring_fold_kernel(pend_ref, x_ref, xsq_ref, f_ref, err_ref,
                      out_ref, fout_ref, errout_ref,
                      facc, eacc, blk, local_sem, copy_sem, send_sem,
                      recv_sem, *, ndev: int, axis_name: str, d: int,
                      kp, compensated: bool, interpret: bool):
    """One (hop, tile) grid step of the shard-local sync.

    Refs (compensated=False drops err_ref/errout_ref/eacc):
      pend_ref (R*q, d+3) ANY   — this shard's window block
      x_ref    (tile, d) VMEM   — x_loc rows of tile t (auto-pipelined)
      xsq_ref  (1, tile) VMEM   — squared norms of tile t
      f_ref    (1, tile) VMEM   — pre-sync gradient of tile t
      out_ref  (P, R*q, d+3) ANY — gathered windows (DMA landing slots)
      fout_ref (1, tile) VMEM   — folded gradient of tile t
      facc     (T, tile) VMEM scratch — running fold across hops
      blk      (R*q, d+3) VMEM scratch — the hop's arrived window
    """
    from dpsvm_tpu.ops.kernels import kernel_from_dots
    from dpsvm_tpu.solver.smo import kahan_add

    h = pl.program_id(0)
    t = pl.program_id(1)
    my = lax.axis_index(axis_name)
    left = lax.rem(my + ndev - 1, ndev)

    @pl.when(t == 0)
    def _exchange():
        # One ring hop per h (same slot discipline as ring_gather), then
        # stage the arrived window in VMEM for this hop's fold tiles.
        @pl.when(h == 0)
        def _own():
            if not interpret:
                _neighbor_barrier(ndev, axis_name)
            cp = pltpu.make_async_copy(pend_ref, out_ref.at[my], local_sem)
            cp.start()
            cp.wait()

        src = lax.rem(my + h, ndev)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[src], dst_ref=out_ref.at[src],
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=left, device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()
        arrived = lax.rem(my + h + 1, ndev)
        cp2 = pltpu.make_async_copy(out_ref.at[arrived], blk, copy_sem)
        cp2.start()
        cp2.wait()

    # ---- the fold of the arrived window into tile t: EXACTLY
    # dist_block.py's fold_one on a tile-sized output slice (tiling
    # splits only the output dim of the (R*q, n_loc) fold matmul, so
    # per-element results are unchanged), in rotation order (right
    # neighbor first — the arrival order of a leftward ring).
    x_t = x_ref[...]                  # (tile, d), x storage dtype
    rows = blk[:, :d].astype(x_t.dtype)
    qsq = blk[:, d]
    coef = blk[:, d + 1]
    dots = jnp.dot(rows, x_t.T, preferred_element_type=jnp.float32)
    kr = kernel_from_dots(dots, xsq_ref[0], qsq, kp)   # (R*q, tile)
    delta = coef @ kr                                  # (tile,)
    first = h == 0
    if compensated:
        base_f = jnp.where(first, f_ref[0], facc[t])
        base_e = jnp.where(first, err_ref[0], eacc[t])
        f_new, e_new = kahan_add(base_f, base_e, delta)
        eacc[t] = e_new
        errout_ref[0] = e_new
    else:
        f_new = jnp.where(first, f_ref[0], facc[t]) + delta
    facc[t] = f_new
    fout_ref[0] = f_new


@functools.partial(jax.jit,
                   static_argnames=("ndev", "axis_name", "kp",
                                    "compensated", "interpret"))
def ring_fold_window(pend, x_loc, x_sq_loc, f, f_err, kp,
                     ndev: int, axis_name: str = DATA_AXIS,
                     compensated: bool = False,
                     interpret: bool = False):
    """Shard-local sync as a ring: gather every peer's (R*q, d+3) window
    AND fold each arrival into the local gradient inside one kernel.

    Returns (gathered (P, R*q, d+3), f_new (n_loc,), err_new or None).
    ``gathered`` is ordered by absolute device id (lax.all_gather
    layout — the pair-count lane reduction reads it identically);
    f/err folding is bit-identical to dist_block.py's rotation fori
    (same order, same kahan_add, output-dim-only tiling). Must be
    called inside a shard_map over ``axis_name``.
    """
    n_loc, d = x_loc.shape
    rq, lanes = pend.shape
    assert lanes == d + 3, (lanes, d)
    assert compensated == (f_err is not None)
    tile = fold_tile_rows(n_loc)
    t_tiles = n_loc // tile
    kern = functools.partial(
        _ring_fold_kernel, ndev=ndev, axis_name=axis_name, d=d, kp=kp,
        compensated=compensated, interpret=interpret)

    vec = pl.BlockSpec((1, tile), lambda h, t: (t, 0),
                       memory_space=pltpu.VMEM)
    xspec = pl.BlockSpec((tile, d), lambda h, t: (t, 0),
                         memory_space=pltpu.VMEM)
    anyspec = pl.BlockSpec(memory_space=pltpu.ANY)
    ins = [pend, x_loc, x_sq_loc.reshape(t_tiles, tile),
           f.reshape(t_tiles, tile)]
    in_specs = [anyspec, xspec, vec, vec]
    out_specs = [anyspec, vec]
    out_shape = [jax.ShapeDtypeStruct((ndev, rq, lanes), jnp.float32),
                 jax.ShapeDtypeStruct((t_tiles, tile), jnp.float32)]
    scratch = [pltpu.VMEM((t_tiles, tile), jnp.float32)]
    if compensated:
        ins.append(f_err.reshape(t_tiles, tile))
        in_specs.append(vec)
        out_specs.append(vec)
        out_shape.append(
            jax.ShapeDtypeStruct((t_tiles, tile), jnp.float32))
        scratch.append(pltpu.VMEM((t_tiles, tile), jnp.float32))
    scratch += [pltpu.VMEM((rq, lanes), jnp.float32)] \
        + [pltpu.SemaphoreType.DMA] * 4

    if compensated:
        def kern_c(pend_r, x_r, xsq_r, f_r, err_r, out_r, fout_r,
                   errout_r, facc, eacc, blk, *sems):
            kern(pend_r, x_r, xsq_r, f_r, err_r, out_r, fout_r, errout_r,
                 facc, eacc, blk, *sems)
        body = kern_c
    else:
        def kern_p(pend_r, x_r, xsq_r, f_r, out_r, fout_r, facc, blk,
                   *sems):
            kern(pend_r, x_r, xsq_r, f_r, None, out_r, fout_r, None,
                 facc, None, blk, *sems)
        body = kern_p

    kw = {} if interpret else {"compiler_params": _compiler_params()}
    outs = pl.pallas_call(
        body,
        grid=(ndev - 1, t_tiles),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        **kw,
    )(*ins)
    if compensated:
        gathered, f2, e2 = outs
        return gathered, f2.reshape(n_loc), e2.reshape(n_loc)
    gathered, f2 = outs
    return gathered, f2.reshape(n_loc), None
