from dpsvm_tpu.ops.kernels import (
    KernelParams,
    row_dots,
    kernel_from_dots,
    kernel_rows,
    kernel_matrix,
    squared_norms,
)
from dpsvm_tpu.ops.select import select_working_set, up_mask, low_mask

__all__ = [
    "KernelParams",
    "row_dots",
    "kernel_from_dots",
    "kernel_rows",
    "kernel_matrix",
    "squared_norms",
    "select_working_set",
    "up_mask",
    "low_mask",
]
