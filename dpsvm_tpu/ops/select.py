"""Working-set selection for modified SMO (Keerthi et al. "modification 2").

The reference implements this as a fused Thrust classify functor +
min/max pair reduction (arbitrary_functor svmTrain.cu:41-95, my_maxmin
:400-467) on GPU, and as explicit I_0..I_4 index-vector scans on CPU
(seq.cpp:469-553). On TPU the same computation collapses to masked
argmin/argmax, which XLA lowers to fused single-pass reductions on the VPU.

Set definitions (seq.cpp:469-493):
  I_up  = I_0 u I_1 u I_2 = {0<a<C} u {a=0, y=+1} u {a=C, y=-1}
        = {y=+1, a<C} u {y=-1, a>0}
  I_low = I_0 u I_3 u I_4 = {0<a<C} u {a=C, y=+1} u {a=0, y=-1}
        = {y=+1, a>0} u {y=-1, a<C}

b_hi = min f over I_up, b_lo = max f over I_low; converged when
b_lo <= b_hi + 2 eps (svmTrainMain.cpp:310).

Tie-breaking: jnp.argmin/argmax return the first (lowest-index) extremum, a
deterministic rule independent of device count (the reference tie-breaks by
reduction order, which differs between its CPU and GPU paths — SURVEY.md
section 7.3 item 4).

Indices are int32 throughout — the reference smuggles them through float
buffers, losing exactness above 2^24 rows (bug B4, svmTrain.cu:478-479).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Sentinel magnitude for masked-out entries; the reference uses +-1e9
# (svmTrain.cu:67,84). Use inf: masked entries can then never win.
_INF = jnp.inf


def split_c(c: float | tuple) -> tuple:
    """Normalize a scalar-or-(c_pos, c_neg) box bound to the pair form."""
    return c if isinstance(c, tuple) else (c, c)


def c_of(y: jax.Array, c_pos: float, c_neg: float, xp=jnp):
    """Per-row upper bound C_i = C * w_{y_i} (LibSVM -w class weights).
    Statically collapses to the scalar when the weights are equal, so the
    unweighted hot path compiles with zero extra ops. `xp` selects the
    array namespace (jnp on device; np for the host-side extrema_np) so
    the set definitions exist exactly once."""
    if c_pos == c_neg:
        return c_pos
    return xp.where(y > 0, c_pos, c_neg)


def up_mask(alpha: jax.Array, y: jax.Array, c_pos: float,
            c_neg: float | None = None, xp=jnp) -> jax.Array:
    """Membership in I_up."""
    c = c_of(y, c_pos, c_pos if c_neg is None else c_neg, xp)
    return xp.where(y > 0, alpha < c, alpha > 0)


def low_mask(alpha: jax.Array, y: jax.Array, c_pos: float,
             c_neg: float | None = None, xp=jnp) -> jax.Array:
    """Membership in I_low."""
    c = c_of(y, c_pos, c_pos if c_neg is None else c_neg, xp)
    return xp.where(y > 0, alpha > 0, alpha < c)


def candidate_live_mask(alpha_w, y_w, c, xp=jnp):
    """Handoff gate for PIPELINED block rounds (solver/block.py
    run_chunk_block_pipelined, parallel/dist_block.py pipelined runner):
    a working set selected from the PRE-fold gradient is only handed to
    the subproblem after this corrected-gradient pass re-derives each
    slot's admissibility from the CURRENT alpha. A slot stays live iff
    its point is still in I_up or I_low — a candidate the previous
    round's updates saturated out of both sets is masked (not
    recomputed; the prefetched Gram row for it is simply unused). The
    subproblem re-checks per-iteration membership itself, so this gate
    is the documented staleness contract, not a hidden correctness
    crutch: it keeps dead slots from occupying selection ranks.

    alpha_w/y_w are the (q,) gathered CURRENT per-slot values; `c` is a
    scalar or (c_pos, c_neg)."""
    cp, cn = split_c(c)
    return (up_mask(alpha_w, y_w, cp, cn, xp=xp)
            | low_mask(alpha_w, y_w, cp, cn, xp=xp))


def nu_stopping_pair(bh_p, bl_p, bh_n, bl_n, xp=jnp):
    """LibSVM's nu stopping gap: report the per-class (b_hi, b_lo) of the
    class with the larger violation, so b_lo - b_hi ==
    max(violation_+, violation_-) (select_working_set_nu's rule, shared
    by the block engines' selection extrema and the host-side refresh)."""
    take_p = (bl_p - bh_p) >= (bl_n - bh_n)
    return (xp.where(take_p, bh_p, bh_n), xp.where(take_p, bl_p, bl_n))


def select_working_set_nu(
    f: jax.Array,
    alpha: jax.Array,
    y: jax.Array,
    c: float | tuple,
    valid: jax.Array | None = None,
):
    """Working-set selection for the nu duals (Solver_NU role,
    LibSVM svm.cpp select_working_set of the nu solver).

    The nu problems carry TWO equality constraints (one per class), so a
    pair update must stay within one class: select the maximal-violating
    pair separately inside {y=+1} and {y=-1} and take the class with the
    larger violation. In f terms the per-class candidate sets are simply
    the C-SVC I_up/I_low masks intersected with the class.

    Returns (i_up, b_hi, i_low, b_lo) of the chosen class; b_lo - b_hi is
    max(violation_+, violation_-), so the standard stopping rule
    b_lo <= b_hi + 2 eps is LibSVM's nu stopping rule.

    No reference equivalent (the reference is C-SVC only).
    """
    cp, cn = split_c(c)
    f = f.astype(jnp.float32)
    up = up_mask(alpha, y, cp, cn)
    low = low_mask(alpha, y, cp, cn)
    if valid is not None:
        up = up & valid
        low = low & valid
    pos = y > 0

    def class_pair(cls):
        f_up = jnp.where(up & cls, f, _INF)
        f_low = jnp.where(low & cls, f, -_INF)
        i_up = jnp.argmin(f_up).astype(jnp.int32)
        i_low = jnp.argmax(f_low).astype(jnp.int32)
        return i_up, f_up[i_up], i_low, f_low[i_low]

    iu_p, bh_p, il_p, bl_p = class_pair(pos)
    iu_n, bh_n, il_n, bl_n = class_pair(~pos)
    take_p = (bl_p - bh_p) >= (bl_n - bh_n)
    i_up = jnp.where(take_p, iu_p, iu_n)
    i_low = jnp.where(take_p, il_p, il_n)
    b_hi = jnp.where(take_p, bh_p, bh_n)
    b_lo = jnp.where(take_p, bl_p, bl_n)
    return i_up, b_hi, i_low, b_lo


def stopping_extrema(f, alpha, y, c, valid=None, rule: str = "mvp"):
    """Device-side masked stopping extrema (b_hi, b_lo) of the CURRENT
    state — the jnp sibling of extrema_np, sharing the same
    up_mask/low_mask/nu_stopping_pair set definitions.

    Used by the shard-local mesh engine's sync handoff
    (parallel/dist_block.py make_block_shardlocal_chunk_runner): each
    shard reduces its LOCAL extrema of the post-sync corrected gradient
    with this, then ONE max-allreduce of (-b_hi, b_lo) replicates the
    exact global pair — the whole KKT stopping test costs one tiny
    collective per sync instead of a selection exchange per round.
    rule="second_order" shares the mvp extrema (the stopping rule is the
    same b_lo <= b_hi + 2 eps over I_up/I_low; only the PAIRING differs).
    The "nu" branch is the per-class rule for completeness — note its
    per-shard result does NOT compose under a plain cross-shard max (the
    class choice must be made from global per-class extrema), which is
    one reason the shard-local engine is restricted to the C-SVC rules."""
    cp, cn = split_c(c)
    f = f.astype(jnp.float32)
    up = up_mask(alpha, y, cp, cn)
    low = low_mask(alpha, y, cp, cn)
    if valid is not None:
        up = up & valid
        low = low & valid
    if rule == "nu":
        pos = y > 0
        bh_p = jnp.min(jnp.where(up & pos, f, _INF))
        bl_p = jnp.max(jnp.where(low & pos, f, -_INF))
        bh_n = jnp.min(jnp.where(up & ~pos, f, _INF))
        bl_n = jnp.max(jnp.where(low & ~pos, f, -_INF))
        return nu_stopping_pair(bh_p, bl_p, bh_n, bl_n)
    return (jnp.min(jnp.where(up, f, _INF)),
            jnp.max(jnp.where(low, f, -_INF)))


def extrema_np(f, alpha, y, c, rule: str = "mvp"):
    """Host-side (NumPy) stopping extrema (b_hi, b_lo) of a final state.

    The block engines' loop carry holds extrema that are one fold behind
    when the solve exits on the iteration budget (solver/block.py: the
    selection that would refresh them belongs to the round that never
    ran). Callers use this on the already-pulled final (f, alpha) to
    report exact b_hi/b_lo — no extra device dispatch. The set
    definitions are the SAME up_mask/low_mask/nu_stopping_pair the device
    loop compiles, evaluated under NumPy via their `xp` parameter."""
    import numpy as np

    cp, cn = split_c(c)
    # Preserve a float64 f: the reconstruction path (solver/reconstruct.py)
    # judges convergence on these extrema and must not have its exact
    # gradient rounded back to f32 on the way in.
    f = np.asarray(f)
    if f.dtype != np.float64:
        f = f.astype(np.float32)
    alpha = np.asarray(alpha)
    y = np.asarray(y)
    up = up_mask(alpha, y, cp, cn, xp=np)
    low = low_mask(alpha, y, cp, cn, xp=np)

    def pair(u, lo):
        b_hi = float(np.min(np.where(u, f, np.inf)))
        b_lo = float(np.max(np.where(lo, f, -np.inf)))
        return b_hi, b_lo

    if rule != "nu":
        return pair(up, low)
    pos = y > 0
    bh_p, bl_p = pair(up & pos, low & pos)
    bh_n, bl_n = pair(up & ~pos, low & ~pos)
    b_hi, b_lo = nu_stopping_pair(bh_p, bl_p, bh_n, bl_n, xp=np)
    return float(b_hi), float(b_lo)


def refresh_extrema_host(f, alpha, y, c, epsilon: float, rule: str = "mvp"):
    """Budget-exit refresh shared by solve() and solve_mesh(): the block
    engines' carried extrema are one fold behind when the loop exits on
    the iteration budget, so recompute (b_hi, b_lo, converged) exactly
    from the pulled final state — this also catches a solve whose very
    last in-budget round closed the gap."""
    b_hi, b_lo = extrema_np(f, alpha, y, c, rule)
    return b_hi, b_lo, not (b_lo > b_hi + 2.0 * epsilon)


def shrink_view(w, slot_ok, n: int, n_pad: int, tile: int):
    """Host-side active view from a shrink-cycle m-select (the ooc
    shrunken stream, solver/ooc.py — Joachims' SVMlight shrinking
    re-derived for a streamed fold).

    ``w``/``slot_ok`` are the pulled (m,) selection outputs: the m
    most-violating rows under the SAME up/low set definitions every
    other selection here uses (select_block with q=m — violation-
    ordered by construction, so no new ranking machinery). Returns

      (active, live_tiles): ``active`` an (n_pad,) bool mask over the
      selected REAL rows (dead slots and any index past n dropped —
      padded lanes can never enter the view), ``live_tiles`` the
      sorted unique indices of the (tile,)-row stream tiles the view
      intersects — the tiles a shrunken round actually streams; every
      other tile's H2D put and fold dispatch simply never happen.
    """
    import numpy as np

    ids = np.asarray(w)[np.asarray(slot_ok, bool)]
    ids = ids[(ids >= 0) & (ids < n)]
    active = np.zeros((n_pad,), bool)
    active[ids] = True
    return active, np.unique(ids // tile)


def select_working_set_batched(
    f: jax.Array,
    alpha: jax.Array,
    y: jax.Array,
    c_pos: jax.Array,
    c_neg: jax.Array,
    valid: jax.Array | None = None,
):
    """Maximal-violating-pair selection for a STACK of independent
    problems (solver/fleet.py): one batched masked argmin/argmax pass
    serves every problem in the fleet.

    f, alpha, y: (k, n) per-problem rows over the shared padded X;
    c_pos, c_neg: (k, 1) per-problem box bounds (traced, so a C sweep
    batches without recompiling); valid: (k, n) bool row masks (padding
    AND each problem's OvO class subset). Returns (i_hi, b_hi, i_lo,
    b_lo), each (k,).

    The set definitions are up_mask/low_mask inlined: c_of's static
    equal-weights collapse cannot apply when the bounds are per-problem
    arrays, so the per-row bound is materialized unconditionally (one
    (k, n) where — noise next to the reductions)."""
    f = f.astype(jnp.float32)
    pos = y > 0
    c_row = jnp.where(pos, c_pos, c_neg)
    up = jnp.where(pos, alpha < c_row, alpha > 0)
    low = jnp.where(pos, alpha > 0, alpha < c_row)
    if valid is not None:
        up = up & valid
        low = low & valid
    f_up = jnp.where(up, f, _INF)
    f_low = jnp.where(low, f, -_INF)
    i_hi = jnp.argmin(f_up, axis=1).astype(jnp.int32)
    i_lo = jnp.argmax(f_low, axis=1).astype(jnp.int32)
    b_hi = jnp.take_along_axis(f_up, i_hi[:, None].astype(jnp.int32),
                               axis=1)[:, 0]
    b_lo = jnp.take_along_axis(f_low, i_lo[:, None].astype(jnp.int32),
                               axis=1)[:, 0]
    return i_hi, b_hi, i_lo, b_lo


def select_working_set(
    f: jax.Array,
    alpha: jax.Array,
    y: jax.Array,
    c: float | tuple,
    valid: jax.Array | None = None,
):
    """Pick the most-violating pair.

    Returns (i_up, b_hi, i_low, b_lo): int32 indices and float32 extrema.
    `valid` masks out padding rows (needed when n is padded up to a multiple
    of the device count / lane width; the reference never pads — bug B3 is
    its unguarded uneven shard math).

    `c` may be a scalar or a (c_pos, c_neg) pair for class-weighted C.
    """
    cp, cn = split_c(c)
    f = f.astype(jnp.float32)
    up = up_mask(alpha, y, cp, cn)
    low = low_mask(alpha, y, cp, cn)
    if valid is not None:
        up = up & valid
        low = low & valid
    f_up = jnp.where(up, f, _INF)
    f_low = jnp.where(low, f, -_INF)
    i_up = jnp.argmin(f_up).astype(jnp.int32)
    i_low = jnp.argmax(f_low).astype(jnp.int32)
    return i_up, f_up[i_up], i_low, f_low[i_low]
