"""Fused Pallas TPU kernel: rank-2 gradient update + next working-set
selection in ONE pass over HBM.

Motivation (SURVEY.md section 7.1 step 7): per SMO iteration the XLA
engine streams f several times — the f-update reads (f, d_hi, d_lo, x_sq)
and writes f, then the next iteration's selection re-reads (f, alpha, y).
At n ~ 60k each stream is only ~240 KB, so per-kernel launch/fusion
boundaries dominate; fusing update+selection halves the passes over f and
cuts the per-iteration kernel count. This is the TPU counterpart of the
reference fusing classify+reduce into one Thrust pass (svmTrain.cu:469-476)
— except here the *update* is fused in too, which the reference could not
do because its update and selection straddle an MPI round trip.

The kernel computes, per grid block of 128-lane rows:

    k_hi = kernel(d_hi, x_sq, qsq_hi)        # rebuild kernel row values
    k_lo = kernel(d_lo, x_sq, qsq_lo)        #   (svmTrain.cu:128-135 algebra)
    f'   = f + coef_hi * k_hi + coef_lo * k_lo
    partial min/argmin of f' over I_up, max/argmax over I_low

and a tiny jnp epilogue reduces the per-block partials. Selection masks
use the ALREADY-UPDATED alpha (the caller scatters the pair first), so the
result equals running selection at the top of the next iteration — the
solver loop is software-pipelined around it (see solver/smo.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots
from dpsvm_tpu.ops.select import split_c

LANES = 128
_BIG = float("inf")  # plain float: a jnp scalar here would be a captured constant


def _fused_kernel(scalars_ref, f_ref, alpha_ref, y_ref, valid_ref,
                  d_hi_ref, d_lo_ref, x_sq_ref,
                  f_out_ref, bhi_ref, ihi_ref, blo_ref, ilo_ref,
                  *, kp: KernelParams, c: float, rows_per_block: int):
    """One grid step: update a (rows, 128) block of f and emit selection
    partials for it."""
    coef_hi = scalars_ref[0]
    coef_lo = scalars_ref[1]
    qsq_hi = scalars_ref[2]
    qsq_lo = scalars_ref[3]

    x_sq = x_sq_ref[:]
    k_hi = kernel_from_dots(d_hi_ref[:], x_sq, qsq_hi, kp)
    k_lo = kernel_from_dots(d_lo_ref[:], x_sq, qsq_lo, kp)
    f_new = f_ref[:] + coef_hi * k_hi + coef_lo * k_lo
    f_out_ref[:] = f_new

    alpha = alpha_ref[:]
    y = y_ref[:]
    # valid rides as float32: Mosaic can't truncate i8 vectors to i1, and
    # sub-32-bit VMEM tiles have their own layout constraints.
    valid = valid_ref[:] > 0.0
    # Pure i1 logic (no jnp.where over booleans: Mosaic materializes the
    # select at i8 and cannot truncate i8 vectors back to i1).
    cp, cn = split_c(c)
    pos = y > 0
    neg = ~pos
    if cp == cn:
        lt_cp = lt_cn = alpha < cp
    else:  # class-weighted C: per-class box bound (LibSVM -w)
        lt_cp = alpha < cp
        lt_cn = alpha < cn
    gt_0 = alpha > 0
    up = ((pos & lt_cp) | (neg & gt_0)) & valid
    low = ((pos & gt_0) | (neg & lt_cn)) & valid

    rows = rows_per_block
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    base = pl.program_id(0) * (rows * LANES)
    flat_ids = base + row_ids * LANES + col_ids

    f_up = jnp.where(up, f_new, _BIG)
    f_low = jnp.where(low, f_new, -_BIG)
    # Lowest-global-index tie-break, matching jnp.argmin/argmax first-hit
    # semantics (SURVEY.md 7.3 item 4): among equal extrema prefer the
    # smallest flat id.
    bhi = jnp.min(f_up)
    ihi = jnp.min(jnp.where(f_up == bhi, flat_ids, jnp.int32(2**31 - 1)))
    blo = jnp.max(f_low)
    ilo = jnp.min(jnp.where(f_low == blo, flat_ids, jnp.int32(2**31 - 1)))

    # Partial outputs live whole-array in SMEM (Mosaic rejects rank-1
    # blocks of size 1); each grid step writes its own slot.
    blk = pl.program_id(0)
    bhi_ref[blk] = bhi
    ihi_ref[blk] = ihi
    blo_ref[blk] = blo
    ilo_ref[blk] = ilo


@functools.partial(jax.jit, static_argnames=("kp", "c", "block_rows", "interpret"))
def fused_update_select(
    f2d: jax.Array,  # (R, 128) float32 — f, lane-tiled
    alpha2d: jax.Array,  # (R, 128) float32
    y2d: jax.Array,  # (R, 128) float32 (+-1)
    valid2d: jax.Array,  # (R, 128) float32 (1.0 = real row)
    d_hi2d: jax.Array,  # (R, 128) float32 dot row for the hi index
    d_lo2d: jax.Array,  # (R, 128) float32 dot row for the lo index
    x_sq2d: jax.Array,  # (R, 128) float32
    scalars: jax.Array,  # (4,) float32: coef_hi, coef_lo, qsq_hi, qsq_lo
    kp: KernelParams,
    c: float,
    block_rows: int = 64,
    interpret: bool = False,
):
    """Returns (f_new2d, b_hi, i_hi, b_lo, i_lo) with flat int32 indices.

    Arrays are shaped (R, 128) where R = n_padded / 128; padding rows must
    have valid == 0.
    """
    rows = f2d.shape[0]
    assert rows % block_rows == 0, (rows, block_rows)
    nblocks = rows // block_rows

    block = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    part = pl.BlockSpec(memory_space=pltpu.SMEM)  # whole (nblocks,) array
    kern = functools.partial(_fused_kernel, kp=kp, c=c,
                             rows_per_block=block_rows)

    f_new, bhi_p, ihi_p, blo_p, ilo_p = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # scalars, whole array
            block, block, block, block, block, block, block,
        ],
        out_specs=[block, part, part, part, part],
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            jax.ShapeDtypeStruct((nblocks,), jnp.float32),
            jax.ShapeDtypeStruct((nblocks,), jnp.int32),
            jax.ShapeDtypeStruct((nblocks,), jnp.float32),
            jax.ShapeDtypeStruct((nblocks,), jnp.int32),
        ],
        interpret=interpret,
    )(scalars, f2d, alpha2d, y2d, valid2d, d_hi2d, d_lo2d, x_sq2d)

    # Epilogue: reduce the per-block partials (nblocks is tiny).
    b_hi = jnp.min(bhi_p)
    i_hi = jnp.min(jnp.where(bhi_p == b_hi, ihi_p, jnp.int32(2**31 - 1)))
    b_lo = jnp.max(blo_p)
    i_lo = jnp.min(jnp.where(blo_p == b_lo, ilo_p, jnp.int32(2**31 - 1)))
    return f_new, b_hi, i_hi, b_lo, i_lo
