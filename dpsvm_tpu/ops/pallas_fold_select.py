"""Fused Pallas TPU kernel: block-engine fold + next working-set
candidate selection in ONE pass over HBM.

The block round's fixed cost is a latency-bound serial stage sequence
(PROFILE.md: 0.20-0.74 ms/round of selection -> gathers -> Gram ->
subproblem -> fold), and its two largest non-matmul stages are
back-to-back full-n passes separated by kernel boundaries: the fold
writes f, and the next round's selection (mask building + approx_max_k)
immediately re-reads it. This kernel extends the ops/pallas_fused.py
pattern (the per-pair engine's fused update+select — itself the TPU
counterpart of the reference fusing classify+reduce, svmTrain.cu:469-476)
to the block engine:

    per (rows, 128) grid block:
      f'   = f + delta            (compensated: Kahan with the err carry)
      up/low masks from the ALREADY-SCATTERED alpha
      per-128-lane-row (min f' over I_up, max f' over I_low) + flat argext

emitting ONE candidate per side per 128-element row — (n/128,) value and
index arrays. A tiny epilogue takes top-h over those (exact lax.top_k on
n/128 elements) to assemble the next working set. Selection invariants
match solver/block.py select_block: each row's true extremum is always
retained, so the globally most-violating pair is always in W and the
emitted extrema are exact; only the mid-rank recall pattern differs
(<=1 candidate per 128-row vs approx_max_k's bins), which swaps
interchangeable mid-rank violators exactly as the approx path already
does.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dpsvm_tpu.ops.select import split_c

LANES = 128
_BIG = float("inf")
_IMAX = 2 ** 31 - 1


def fold_delta(f, err, delta):
    """The block fold's per-tile step, shared by _fold_select_kernel and
    the one-pass round kernel (ops/pallas_round.py): plain add when
    ``err`` is None, else the canonical Kahan step (solver/smo.py
    kahan_add — the same function every other engine's fold uses).
    Returns (f_new, err_new_or_None, f_sel) where f_sel is the effective
    gradient the selection masks must see (true ~= f - err)."""
    if err is not None:
        from dpsvm_tpu.solver.smo import kahan_add

        f_new, err_new = kahan_add(f, err, delta)
        return f_new, err_new, f_new - err_new
    f_new = f + delta
    return f_new, None, f_new


def emit_row_candidates(f_sel, alpha, y, valid_f, c, rows: int, base,
                        upv_ref, upi_ref, lov_ref, loi_ref):
    """Mask building + per-128-row candidate emission, shared by
    _fold_select_kernel and the one-pass round kernel
    (ops/pallas_round.py) so the selection semantics live once.

    Set membership is the up_mask/low_mask algebra of ops/select.py,
    re-expressed as pure i1 logic: those helpers build on jnp.where
    over booleans, which Mosaic materializes at i8 and cannot truncate
    back to i1 (same constraint, ops/pallas_fused.py) — keep the two
    in sync. ``base`` is the flat id of this (rows, 128) block's first
    element (caller passes pl.program_id(0) * rows * LANES)."""
    valid = valid_f > 0.0  # float mask: see ops/pallas_fused.py
    cp, cn = split_c(c)
    pos = y > 0
    neg = ~pos
    if cp == cn:
        lt_cp = lt_cn = alpha < cp
    else:
        lt_cp = alpha < cp
        lt_cn = alpha < cn
    gt_0 = alpha > 0
    up = ((pos & lt_cp) | (neg & gt_0)) & valid
    low = ((pos & gt_0) | (neg & lt_cn)) & valid

    col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
    flat_ids = base + row_ids * LANES + col_ids

    f_up = jnp.where(up, f_sel, _BIG)
    f_low = jnp.where(low, f_sel, -_BIG)
    # Per-ROW extremum + lowest-flat-id argext (SURVEY 7.3 item 4
    # tie-break), keepdims so the lane reduction stays 2D for Mosaic.
    upv = jnp.min(f_up, axis=1, keepdims=True)  # (rows, 1)
    upi = jnp.min(jnp.where(f_up == upv, flat_ids, _IMAX),
                  axis=1, keepdims=True)
    lov = jnp.max(f_low, axis=1, keepdims=True)
    loi = jnp.min(jnp.where(f_low == lov, flat_ids, _IMAX),
                  axis=1, keepdims=True)
    upv_ref[:] = upv
    upi_ref[:] = upi
    lov_ref[:] = lov
    loi_ref[:] = loi


def _fold_select_kernel(*refs, c, rows_per_block: int, compensated: bool,
                        fold: bool = True):
    """One grid step: fold a (rows, 128) block of delta into f and emit
    per-row selection candidates. With fold=False (the PRE-FOLD selection
    variant, select_rows below) there is no delta input and no f/err
    output — the candidates are emitted from f as it stands."""
    if not fold:
        (f_ref, alpha_ref, y_ref, valid_ref,
         upv_ref, upi_ref, lov_ref, loi_ref) = refs
        if compensated:
            raise AssertionError(
                "select_rows passes the effective f (f - err) directly")
        f_sel = f_ref[:]
    elif compensated:
        (f_ref, err_ref, alpha_ref, y_ref, valid_ref, delta_ref,
         f_out_ref, err_out_ref, upv_ref, upi_ref, lov_ref, loi_ref) = refs
    else:
        (f_ref, alpha_ref, y_ref, valid_ref, delta_ref,
         f_out_ref, upv_ref, upi_ref, lov_ref, loi_ref) = refs

    if fold:
        f_new, err_new, f_sel = fold_delta(
            f_ref[:], err_ref[:] if compensated else None, delta_ref[:])
        if compensated:
            err_out_ref[:] = err_new
        f_out_ref[:] = f_new

    rows = rows_per_block
    base = pl.program_id(0) * (rows * LANES)
    emit_row_candidates(f_sel, alpha_ref[:], y_ref[:], valid_ref[:], c,
                        rows, base, upv_ref, upi_ref, lov_ref, loi_ref)


@functools.partial(jax.jit,
                   static_argnames=("c", "block_rows", "compensated",
                                    "interpret"))
def fold_select(f2d, err2d, alpha2d, y2d, valid2d, delta2d, c,
                block_rows: int = 8, compensated: bool = False,
                interpret: bool = False):
    """Fold delta into f (optionally Kahan-compensated) and emit per-row
    working-set candidates.

    All arrays are (R, 128) float32, R % block_rows == 0; err2d is None
    unless compensated. Returns (f_new2d, err_new2d_or_None, up_vals,
    up_ids, low_vals, low_ids) with (R,) candidate arrays — one per
    128-element row.
    """
    rows = f2d.shape[0]
    assert rows % block_rows == 0, (rows, block_rows)
    nblocks = rows // block_rows

    block = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    cand = pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    kern = functools.partial(_fold_select_kernel, c=c,
                             rows_per_block=block_rows,
                             compensated=compensated)
    full = jax.ShapeDtypeStruct((rows, LANES), jnp.float32)
    cval = jax.ShapeDtypeStruct((rows, 1), jnp.float32)
    cidx = jax.ShapeDtypeStruct((rows, 1), jnp.int32)

    if compensated:
        ins = (f2d, err2d, alpha2d, y2d, valid2d, delta2d)
        out_specs = [block, block, cand, cand, cand, cand]
        out_shape = [full, full, cval, cidx, cval, cidx]
    else:
        ins = (f2d, alpha2d, y2d, valid2d, delta2d)
        out_specs = [block, cand, cand, cand, cand]
        out_shape = [full, cval, cidx, cval, cidx]

    outs = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=[block] * len(ins),
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*ins)
    if compensated:
        f_new, err_new, upv, upi, lov, loi = outs
    else:
        f_new, upv, upi, lov, loi = outs
        err_new = None
    return (f_new, err_new, upv[:, 0], upi[:, 0], lov[:, 0], loi[:, 0])


@functools.partial(jax.jit,
                   static_argnames=("c", "block_rows", "interpret"))
def select_rows(f2d, alpha2d, y2d, valid2d, c, block_rows: int = 8,
                interpret: bool = False):
    """PRE-FOLD selection variant of fold_select: emit per-row working-set
    candidates from f AS IT STANDS (no delta, no fold). Built for the
    pipelined block engine (solver/block.py run_chunk_block_pipelined),
    whose next-round selection is issued from the pre-fold gradient and
    therefore has no delta to fold — the ONE pass over f replaces the
    full-n mask-building + approx_max_k stage of select_block exactly as
    fold_select does for the fused engine, without manufacturing a
    zero-delta fold (which would still write the (R, 128) f output back
    to HBM for nothing).

    Compensated carries pass the effective f (f - err) — the caller
    already holds both and the selection only READS f, so no err
    plumbing is needed here. Same contract as fold_select otherwise:
    (R, 128) float32 arrays, R % block_rows == 0; returns (up_vals,
    up_ids, low_vals, low_ids), one candidate per 128-element row, ids
    flat over the (R, 128) layout."""
    rows = f2d.shape[0]
    assert rows % block_rows == 0, (rows, block_rows)
    nblocks = rows // block_rows

    block = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    cand = pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    kern = functools.partial(_fold_select_kernel, c=c,
                             rows_per_block=block_rows,
                             compensated=False, fold=False)
    cval = jax.ShapeDtypeStruct((rows, 1), jnp.float32)
    cidx = jax.ShapeDtypeStruct((rows, 1), jnp.int32)
    upv, upi, lov, loi = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=[block] * 4,
        out_specs=[cand, cand, cand, cand],
        out_shape=[cval, cidx, cval, cidx],
        interpret=interpret,
    )(f2d, alpha2d, y2d, valid2d)
    return upv[:, 0], upi[:, 0], lov[:, 0], loi[:, 0]


def assemble_working_set(upv, upi, lov, loi, h: int):
    """Epilogue: the next round's (w, slot_ok, b_hi, b_lo) from the
    per-row candidates — exact top-h over n/128 elements (tiny), then the
    shared cross-half dedup (solver/block.py combine_halves)."""
    from dpsvm_tpu.solver.block import combine_halves

    vals, idx = jax.lax.top_k(jnp.stack([-upv, lov]), h)  # (2, h)
    ids = jnp.take_along_axis(jnp.stack([upi, loi]), idx, axis=1)
    w, slot_ok = combine_halves(ids[0], jnp.isfinite(vals[0]),
                                ids[1], jnp.isfinite(vals[1]))
    return w, slot_ok, -vals[0, 0], vals[1, 0]
