"""Pallas TPU kernel: the block engine's whole q-variable subproblem solve
as ONE kernel launch.

Motivation: the block engine's inner loop (solver/block.py) touches only
q-sized state, but as an XLA ``lax.while_loop`` each iteration still costs
a fixed multi-kernel dispatch sequence (~100 us on v5e) that dwarfs the
nanoseconds of VPU work per step. Running the entire loop inside one
Pallas kernel keeps K(W, W), alpha_W, f_W resident in VMEM for the whole
solve: per-iteration cost collapses to the actual vector ops.

This is the TPU answer to the reference keeping its working state device-
resident across Thrust launches (svmTrain.cu:469-499) — except the whole
*loop* lives on-core, not just the state.

Semantics are identical to solver/block.py::_solve_subproblem: maximal-
violating-pair selection over the working set, the shared
``pair_alpha_update`` algebra (solver/smo.py), incremental f_W updates
from K(W, W) rows, stop when the local gap closes or `inner_iters` pair
updates have run.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dpsvm_tpu.ops.select import split_c
from dpsvm_tpu.solver.smo import pair_alpha_update

_INF = float("inf")
_IMAX = 2**31 - 1


def _pick1(sel, vec):
    """Extract vec[i] as a scalar given the one-hot mask sel = (idx == i).
    Random scalar gathers are not a Mosaic primitive; a masked reduce is
    one VPU pass over the (rows, 128) register tile."""
    return jnp.sum(jnp.where(sel, vec, 0.0))


def _subproblem_kernel(limit_ref, kb_ref, alpha_ref, y_ref, f_ref, kd_ref,
                       ok_ref, alpha_out_ref, t_ref,
                       *, rows: int, cp: float, cn: float, eps: float,
                       tau: float, rule: str, pair_batch: int = 1):
    # All working-set state lives in (rows, 128) tiles: a (1, q) vector
    # occupies ceil(q/128) vregs with 7 of 8 sublanes idle, while the
    # (rows, 128) layout packs the same q values 8x denser — every
    # elementwise op and reduction below runs on ~1/4 the vector
    # instructions at q=512. `lanes` becomes the flattened slot index.
    lanes = (lax.broadcasted_iota(jnp.int32, (rows, 128), 0) * 128
             + lax.broadcasted_iota(jnp.int32, (rows, 128), 1))
    y = y_ref[:]
    kd = kd_ref[:]
    ok = ok_ref[:] > 0.0
    pos = y > 0
    neg = ~pos
    limit = limit_ref[0]

    def masks(alpha):
        """I_up / I_low membership over the working set — the up_mask /
        low_mask rule (ops/select.py) in pure i1 logic (Mosaic cannot
        truncate i8 selects back to i1), shared by cond and body."""
        if cp == cn:
            lt_cp = lt_cn = alpha < cp
        else:
            lt_cp = alpha < cp
            lt_cn = alpha < cn
        gt_0 = alpha > 0
        up = ((pos & lt_cp) | (neg & gt_0)) & ok
        low = ((pos & gt_0) | (neg & lt_cn)) & ok
        return up, low

    def iteration(carry):
        # One mask/extrema computation per pair update: the selection
        # below yields the pair AND the stopping gap of the CURRENT
        # (alpha, f), so `cond` only tests the carried flag — the old
        # structure recomputed masks + both extrema a second time in
        # cond on every trip. The final trip runs with the update gated
        # to a no-op (pair_alpha_update's `gate`), exactly like the
        # outer block round's terminal-round gating (solver/block.py).
        alpha, f, t, _ = carry
        up, low = masks(alpha)
        if rule == "nu":
            # Per-class MVP; pick the class with the larger violation so
            # the pair shares a class (the nu duals' per-class equality
            # constraints; ops/select.py select_working_set_nu). Compute
            # both classes' candidates and select SCALARS only — Mosaic
            # cannot legalize a select over i1 (mask) vectors.
            f_up_p = jnp.where(up & pos, f, _INF)
            f_low_p = jnp.where(low & pos, f, -_INF)
            f_up_n = jnp.where(up & neg, f, _INF)
            f_low_n = jnp.where(low & neg, f, -_INF)
            bh_p = jnp.min(f_up_p)
            bl_p = jnp.max(f_low_p)
            bh_n = jnp.min(f_up_n)
            bl_n = jnp.max(f_low_n)
            i_p = jnp.min(jnp.where(f_up_p == bh_p, lanes, _IMAX))
            j_p = jnp.min(jnp.where(f_low_p == bl_p, lanes, _IMAX))
            i_n = jnp.min(jnp.where(f_up_n == bh_n, lanes, _IMAX))
            j_n = jnp.min(jnp.where(f_low_n == bl_n, lanes, _IMAX))
            take_p = (bl_p - bh_p) >= (bl_n - bh_n)
            b_hi = jnp.where(take_p, bh_p, bh_n)
            b_lo = jnp.where(take_p, bl_p, bl_n)
            i = jnp.where(take_p, i_p, i_n)
            j = jnp.where(take_p, j_p, j_n)
            row_i = jnp.reshape(kb_ref[pl.ds(i, 1)], (rows, 128))
        elif rule == "second_order":
            # LibSVM WSS2: i by max violation; j by max second-order gain
            # (f_j - b_hi)^2 / eta_ij over row i of the VMEM Gram block.
            # CRITICAL: the stopping gap uses the MAX violator (b_lo_stop),
            # not the gain-selected j's violation — the best-gain j can sit
            # within 2 eps while a larger violator with a bigger eta stays
            # open; gating on f[j] - b_hi would end the subproblem with
            # zero pairs, the outer fold would change nothing, and the
            # outer round loop would re-select the same W forever (a
            # single dispatch spinning until the device watchdog kills it).
            f_up = jnp.where(up, f, _INF)
            b_hi = jnp.min(f_up)
            b_lo_stop = jnp.max(jnp.where(low, f, -_INF))
            i = jnp.min(jnp.where(f_up == b_hi, lanes, _IMAX))
            row_i = jnp.reshape(kb_ref[pl.ds(i, 1)], (rows, 128))
            sel_i0 = lanes == i
            diff = f - b_hi
            eta_j = jnp.maximum(_pick1(sel_i0, kd) + kd - 2.0 * row_i, tau)
            gain = jnp.where(low & (diff > 0.0), diff * diff / eta_j, -_INF)
            g_best = jnp.max(gain)
            j = jnp.min(jnp.where(gain == g_best, lanes, _IMAX))
            # At the honest epsilon an eligible j exists whenever the stop
            # gap is open (some f_low > b_hi). budget_mode compiles
            # eps=-1e30, which keeps the gap open after the eligible set
            # empties — then gain is all -inf and j degenerates to lane 0,
            # so the update must ALSO be gated on has_j (a counted no-op;
            # gating the loop itself would stall the pair counter and
            # spin the budget-mode outer loop forever).
            has_j = g_best > -_INF
            sel_j0 = lanes == j
            b_lo = _pick1(sel_j0, f)
        else:
            f_up = jnp.where(up, f, _INF)
            f_low = jnp.where(low, f, -_INF)
            b_hi = jnp.min(f_up)
            b_lo = jnp.max(f_low)
            i = jnp.min(jnp.where(f_up == b_hi, lanes, _IMAX))
            j = jnp.min(jnp.where(f_low == b_lo, lanes, _IMAX))
            row_i = jnp.reshape(kb_ref[pl.ds(i, 1)], (rows, 128))

        b_lo_gap = b_lo_stop if rule == "second_order" else b_lo
        gap_open = (b_lo_gap - b_hi) > 2.0 * eps
        upd_ok = gap_open & has_j if rule == "second_order" else gap_open
        row_j = jnp.reshape(kb_ref[pl.ds(j, 1)], (rows, 128))
        sel_i = lanes == i
        sel_j = lanes == j
        # Measured dead ends, recorded so they are not retried: (1) a
        # stacked (3, q) masked-reduce extraction — Mosaic rejects i1
        # vreg concatenation ("Invalid vector register cast"); (2) SMEM
        # scalar mirrors of y/kd/alpha serving these picks as scalar-core
        # loads — lowered fine but moved nothing (the loop is bound by
        # its serial dependency chain, not by reduction count).
        y_i = _pick1(sel_i, y)
        y_j = _pick1(sel_j, y)
        k_ij = _pick1(sel_j, row_i)
        eta = jnp.maximum(_pick1(sel_i, kd) + _pick1(sel_j, kd) - 2.0 * k_ij,
                          tau)
        a_i_old = _pick1(sel_i, alpha)
        a_j_old = _pick1(sel_j, alpha)
        c_i = cp if cp == cn else jnp.where(y_i > 0, cp, cn)
        c_j = cp if cp == cn else jnp.where(y_j > 0, cp, cn)
        a_i_new, a_j_new = pair_alpha_update(
            a_i_old, a_j_old, y_i, y_j, b_hi, b_lo, eta, c_i, c_j,
            gate=upd_ok)
        alpha = jnp.where(sel_i, a_i_new, alpha)
        alpha = jnp.where(sel_j, a_j_new, alpha)
        f = f + (a_i_new - a_i_old) * y_i * row_i \
              + (a_j_new - a_j_old) * y_j * row_j
        if pair_batch == 1:
            return alpha, f, t + jnp.int32(gap_open), gap_open

        # ---- pair_batch >= 2 (rule == "mvp", validated upstream):
        # pair_batch-1 further coordinate-disjoint pairs per trip.
        # SELECTION is stale (rank-s extrema of the same pre-update
        # f_up/f_low reductions, excluding all earlier pairs' lanes — no
        # extra full-tile reduction pass on the serial chain for the
        # candidate values); each UPDATE is exact: its b_hi/b_lo are
        # re-picked from the CURRENT f tile and its alpha coords are
        # untouched by the earlier pairs (disjointness), so every
        # applied step is a true SMO step on the updated state —
        # monotone descent, conservation, box all hold. Counting matches
        # the second_order precedent: an attempted slot counts even when
        # gated to a no-op (deterministic budget math); the update
        # itself is gated on the STALE sets being non-empty (empty-set
        # sentinel index would alias lane 0 — a real, wrong update, not
        # a no-op) and on the corrected pair still violating (deliberate
        # margin-free b_lo > b_hi gate — the pinned pair_batch=2
        # semantics; see the counting note in solver/block.py).
        excl = sel_i | sel_j
        f_up_s, f_low_s = f_up, f_low
        t_cur = t + jnp.int32(gap_open)
        for _s in range(pair_batch - 1):
            f_up_s = jnp.where(excl, _INF, f_up_s)
            f_low_s = jnp.where(excl, -_INF, f_low_s)
            bh_s = jnp.min(f_up_s)
            bl_s = jnp.max(f_low_s)
            i2 = jnp.min(jnp.where(f_up_s == bh_s, lanes, _IMAX))
            j2 = jnp.min(jnp.where(f_low_s == bl_s, lanes, _IMAX))
            sel_i2 = lanes == i2
            sel_j2 = lanes == j2
            row_i2 = jnp.reshape(kb_ref[pl.ds(i2, 1)], (rows, 128))
            row_j2 = jnp.reshape(kb_ref[pl.ds(j2, 1)], (rows, 128))
            b_hi2 = _pick1(sel_i2, f)  # corrected: current gradient
            b_lo2 = _pick1(sel_j2, f)
            y_i2 = _pick1(sel_i2, y)
            y_j2 = _pick1(sel_j2, y)
            eta2 = jnp.maximum(
                _pick1(sel_i2, kd) + _pick1(sel_j2, kd)
                - 2.0 * _pick1(sel_j2, row_i2), tau)
            a_i2_old = _pick1(sel_i2, alpha)
            a_j2_old = _pick1(sel_j2, alpha)
            cnt2 = gap_open & (t_cur < limit)
            upd2 = (cnt2 & (bh_s < _INF) & (bl_s > -_INF)
                    & (b_lo2 > b_hi2))
            c_i2 = cp if cp == cn else jnp.where(y_i2 > 0, cp, cn)
            c_j2 = cp if cp == cn else jnp.where(y_j2 > 0, cp, cn)
            a_i2_new, a_j2_new = pair_alpha_update(
                a_i2_old, a_j2_old, y_i2, y_j2, b_hi2, b_lo2, eta2,
                c_i2, c_j2, gate=upd2)
            alpha = jnp.where(sel_i2, a_i2_new, alpha)
            alpha = jnp.where(sel_j2, a_j2_new, alpha)
            f = f + (a_i2_new - a_i2_old) * y_i2 * row_i2 \
                  + (a_j2_new - a_j2_old) * y_j2 * row_j2
            t_cur = t_cur + jnp.int32(cnt2)
            excl = excl | sel_i2 | sel_j2
        return alpha, f, t_cur, gap_open

    def cond(carry):
        _, _, t, gap_open = carry
        return (t < limit) & gap_open

    alpha, _, t, _ = lax.while_loop(
        cond, iteration,
        (alpha_ref[:], f_ref[:], jnp.int32(0), limit > 0))
    alpha_out_ref[:] = alpha
    t_ref[0] = t


@functools.partial(jax.jit,
                   static_argnames=("c", "eps", "tau", "rule", "interpret",
                                    "pair_batch"))
def solve_subproblem_pallas(kb_w, alpha_w, y_w, f_w, kd_w, slot_ok, limit,
                            c, eps: float, tau: float, rule: str = "mvp",
                            interpret: bool = False, pair_batch: int = 1):
    """Solve the q-variable subproblem on-core.

    kb_w: (q, q) float32 Gram block; the five vectors are (q,) float32
    (slot_ok as 1.0/0.0); `limit` is the dynamic pair-update budget (int32
    scalar — per-round inner_iters already clamped to the remaining
    max_iter budget). Returns (alpha_w_new (q,), n_pairs int32).
    `rule` is the pairing rule ("mvp" | "second_order" | "nu" — see
    solver/block.py _solve_subproblem). pair_batch=2 (mvp only) executes
    a second coordinate-disjoint pair per while-loop trip — stale-selected,
    exactly-updated (see the kernel comment) — trading one trip's serial
    dependency chain for two counted pairs.
    """
    if pair_batch not in (1, 2, 4):
        raise ValueError("pair_batch must be 1, 2 or 4")
    if pair_batch > 1 and rule != "mvp":
        raise ValueError("pair_batch>1 is implemented for rule='mvp' only")
    cp, cn = split_c(c)
    q = kb_w.shape[0]
    # Pad the working set up to whole 128-lane rows and hand the kernel
    # (rows, 128) tiles (see the layout note in _subproblem_kernel). Pad
    # slots carry ok=0 so the masks exclude them everywhere; padded Gram
    # columns are zero so row broadcasts leave their (dead) f untouched
    # in any way that matters.
    qp = -(-q // 128) * 128
    rows = qp // 128
    pad = qp - q

    def padv(v, fill):
        v = v.astype(jnp.float32)
        if pad:
            v = jnp.pad(v, (0, pad), constant_values=fill)
        return v.reshape(rows, 128)

    kb_p = kb_w if not pad else jnp.pad(kb_w, ((0, pad), (0, pad)))
    kern = functools.partial(
        _subproblem_kernel, rows=rows, cp=float(cp), cn=float(cn),
        eps=float(eps), tau=float(tau), rule=rule, pair_batch=pair_batch)
    vec = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    alpha_out, t = pl.pallas_call(
        kern,
        in_specs=[smem] + [vec] * 6,
        out_specs=[vec, smem],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret,
    )(jnp.asarray(limit, jnp.int32).reshape(1),
      kb_p.reshape(qp, rows, 128),
      padv(alpha_w, 0.0), padv(y_w, 1.0), padv(f_w, 0.0),
      padv(kd_w, 1.0), padv(slot_ok, 0.0))
    return alpha_out.reshape(qp)[:q], t[0]
