"""Out-of-core tile primitives: the per-tile partial gradient fold.

The in-core block engine's fold is ONE (q, d) x (d, n) pass over the
device-resident X followed by the gradient accumulate
(solver/block.py run_local_round). Out of core (config.ooc,
solver/ooc.py), X lives in host memory and the same fold streams over
(tile_rows, d) tiles: for each tile the driver issues an async
host->HBM ``device_put`` of tile t+1 and then dispatches THIS kernel
on tile t, so the H2D DMA overlaps the MXU matmul instead of
serializing with it (the double buffer).

The kernel is deliberately TILE-LOCAL: every argument is tile-pool- or
q-sized, never (n, ...)-sized, so the compiled program — and its
tpulint budget (``ooc_fold_tile``) — is a pure function of
(tile_rows, d, q). That is the contract that makes the ooc path's
device footprint independent of total n: tests/test_tpulint.py
mutation-verifies that doubling n leaves the budget facts unchanged.

Bit-exactness: the gradient accumulate ``f_tile + coef @ K`` lives
INSIDE this program, exactly as the in-core round fuses its fold into
the accumulate — XLA's codegen for the exp/matmul/add chain rounds
identically whether the column extent is n or tile_rows, but NOT
whether the final add is fused or dispatched separately (measured on
the CPU backend; the ooc-vs-in-core bit-identity test in
tests/test_ooc.py is what holds this in place).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots


def fold_tile_body(x_tile, xsq_tile, f_tile, err_tile, qx, qsq, coef,
                   kp: KernelParams, want_dots: bool = False,
                   compensated: bool = False):
    """The fold algebra, traceable from any enclosing program.

    ``ooc_fold_tile`` below jits it per tile on the single-chip path;
    the MESH ooc stream (parallel/dist_block.py make_ooc_mesh_programs)
    traces the SAME body inside its shard_map fold so the per-slot op
    sequence — dot, kernel transform, coef @ K, the (possibly Kahan)
    accumulate — is the identical XLA program at the identical shapes,
    which is what makes the mesh trajectory bitwise equal to the
    single-chip one (tests/test_ooc.py pins it at 2 devices)."""
    from dpsvm_tpu.solver.smo import kahan_add

    with jax.named_scope("ooc_fold_tile"):
        dots = jnp.dot(qx.astype(x_tile.dtype), x_tile.T,
                       preferred_element_type=jnp.float32)  # (q, T)
        k = kernel_from_dots(dots, xsq_tile, qsq, kp)  # (q, T) f32
        delta = coef @ k  # (T,) f32
        if compensated:
            f_new, err_new = kahan_add(f_tile, err_tile, delta)
        else:
            f_new, err_new = f_tile + delta, None
    return f_new, err_new, (dots if want_dots else None)


@partial(jax.jit, donate_argnames=("f_tile", "err_tile"),
         static_argnames=("kp", "want_dots", "compensated"))
def ooc_fold_tile(x_tile, xsq_tile, f_tile, err_tile, qx, qsq, coef,
                  kp: KernelParams, want_dots: bool = False,
                  compensated: bool = False):
    """One tile's share of the round fold, applied to the tile's slice
    of the gradient.

    x_tile   (T, d)  streamed tile of X (storage dtype, f32 or bf16)
    xsq_tile (T,)    the tile rows' squared norms (from the setup pass)
    f_tile   (T,)    this tile's slice of the carried gradient
    err_tile (T,)|None  its Kahan residual slice (config.compensated)
    qx       (q, d)  working-set rows (same storage dtype)
    qsq      (q,)    working-set squared norms
    coef     (q,)    fold coefficients (dalpha * y, dead slots zero)

    Returns (f_tile_new, err_tile_new, dots_tile): the folded gradient
    slice and — when ``want_dots`` (the block cache is live) — the raw
    (q, T) dot rows, the cache's currency (solver/cache.py stores DOT
    rows and re-applies the kernel transform per use, the reference
    cache.cu discipline); None otherwise, so the cache-off program
    never materializes them.

    The SHRUNKEN stream (config.ooc_shrink / active_set_size with ooc,
    solver/ooc.py) never reaches this program for a skipped tile: the
    driver holds a host-side live-tile set and the skipped tiles' f
    slices pass through the round untouched — the skip is a dispatch
    that never happens, not a masked kernel, so this budget
    (``ooc_fold_tile`` / ``ooc_fold_tile_shrink``) is identical under
    shrinking.
    """
    return fold_tile_body(x_tile, xsq_tile, f_tile, err_tile, qx, qsq,
                          coef, kp, want_dots=want_dots,
                          compensated=compensated)
