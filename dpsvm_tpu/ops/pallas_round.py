"""One-HBM-pass block round: Pallas-fused gather -> Gram -> fold -> select
(ISSUE 12 / ROADMAP item 1, the single-chip leg).

The block engine's round body was stock XLA ops stitched between Pallas
kernels: working-set rows gathered by an XLA gather, the (q, n) kernel
rows built by a separate matmul pass over X (materializing the dots AND
the exp'd rows), the fold contraction reading them back, and only the
fold+select tail fused (ops/pallas_fold_select.py). At block-engine
scale the round is HBM-bound on X (ThunderSVM's regime — Catanzaro et
al. fused kernel-row evaluation with the reduction consuming it for the
same reason, PAPERS.md), so every eliminated pass over X and the O(n)
vectors is direct wall-clock. This module makes the round exactly TWO
Pallas passes with the subproblem dispatch between them:

``gather_gram``
    ONE streaming pass over X on a 1-D tile grid. At grid entry the
    working-set rows are gathered into an on-core (q, d) scratch by q
    in-kernel dynamic-slice DMAs from the HBM-resident X (the rows'
    dots feed every tile, so the gather must complete before the first
    tile's matmul — a per-tile copy-out-of-the-streamed-tile
    formulation cannot work: tile t's (q, tile) dot slice needs the
    complete (q, d) block, not the rows that happen to live in tile t).
    Each tile step then runs the (q, d) x (d, tile) dot on the MXU (f32
    accumulation) and rebuilds kernel values in-register with the
    shared ``kernel_from_dots`` algebra — the (q, n) kernel rows reach
    HBM exactly once, with no separate dots buffer, no qx round-trip
    and no standalone Gram launch (the (q, q) block K(W, W) rides grid
    step 0 from the same scratch).

``fold_rows_select``
    ONE pass over the (q, n) kernel rows and the O(n) vectors: per
    (q, tile) block the fold coefficients contract to the tile's delta
    in-register (never materialized), the fold applies it (Kahan when
    compensated) and the next round's per-128-row working-set
    candidates are emitted — the ops/pallas_fold_select.py kernel with
    the delta input replaced by its own in-kernel contraction; the
    mask/candidate code is literally shared (emit_row_candidates /
    fold_delta).

So select -> gather -> Gram -> fold touches X exactly once per round
and f/alpha/y/valid exactly once, instead of the stock fused engine's
gather + dots + exp + contraction + fold stages each taking their own
trip through HBM. The q-sized per-slot scalars (alpha_W, f_W, y_W,
norms, diag) stay tiny XLA gathers — O(q) reads, not passes.

Correctness contract (the established pattern of the four existing
Pallas kernels): ``interpret=True`` runs on the CPU harness and the
trajectory is BITWISE identical to the stock fused engine
(solver/block.py run_chunk_block_fused): the DMA row gather moves the
identical bits ``jnp.take`` would; the per-tile dots split only the
OUTPUT dim of the (q, d) x (d, n) matmul (the ops/ooc.py /
ops/ring.py precedent — per-element results are unchanged);
``kernel_from_dots`` is the same function; the in-kernel delta
contraction splits only the output dim of coef @ K(W, :); and the
fold/selection algebra is shared code. tests/test_fused_round.py pins
full-solve bitwise equality across {mvp, second_order} x {compensated,
plain} including padded tails; the tpulint ``block_chunk_fusedround``
budget pins the device-form structure (zero collectives, zero host
callbacks, donated carry) with the ring kernels' dual
interpret-compile + device_form pattern.

Padding contract (shared with the fused fold+select engine):
n_pad % 1024 == 0 with ``valid`` marking real rows (solver/smo.py
pads), q/2 <= n_pad/128, selection in {"mvp", "second_order"},
feature kernels only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dpsvm_tpu.ops.pallas_fold_select import (LANES, emit_row_candidates,
                                              fold_delta)

#: rows of X per streamed tile — 8 x 128 lanes, the fold/select grid's
#: block, so both kernels share one n_pad % 1024 == 0 padding contract.
TILE_ROWS = 1024
#: f/alpha/y/valid rows per fold/select grid block ((8, 128) f32 vregs —
#: must match ops/pallas_fold_select.py's default so candidate flat ids
#: are identical).
FOLD_ROWS = 8
#: in-flight row DMAs of the grid-entry gather (the guide's double-
#: buffer pattern, widened): copy s+GATHER_BUF starts before copy s is
#: waited on, so the q single-row transfers pipeline through the DMA
#: engine instead of serializing q start->wait round-trips.
GATHER_BUF = 8


def _gather_gram_kernel(w_ref, x_any, x_blk, xsq_blk, qsq_blk,
                        krows_ref, kb_ref, qx, sem, *, q: int, kp):
    """One (TILE_ROWS, d) tile step of the single X pass.

    Refs:
      w_ref    (q,) int32 SMEM      — working-set ids (scalar prefetch)
      x_any    (n_pad, d) ANY       — X in HBM, source of the row gather
      x_blk    (TILE_ROWS, d) VMEM  — tile t of X (auto-pipelined)
      xsq_blk  (1, TILE_ROWS) VMEM  — squared norms of tile t
      qsq_blk  (1, q) VMEM          — working-set squared norms
      krows_ref (q, TILE_ROWS) VMEM — tile t's kernel-row slice (out)
      kb_ref   (q, q) VMEM          — K(W, W), written at step 0 (out)
      qx       (q, d) VMEM scratch  — gathered rows (persists across
                                      grid steps)
    """
    from dpsvm_tpu.ops.kernels import kernel_from_dots

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _gather():
        # q in-kernel dynamic-slice row DMAs from the HBM-resident X —
        # O(q d) traffic once per round, completing before any tile's
        # dot consumes the block (see module docstring). Bitwise the
        # same rows jnp.take would move (disjoint destination slots).
        # GATHER_BUF copies stay in flight (reconstructed descriptors,
        # per-slot semaphores — the double-buffer pattern) so the q
        # transfers pipeline instead of paying q serial round-trips.
        def cp(s, slot):
            return pltpu.make_async_copy(
                x_any.at[pl.ds(w_ref[s], 1), :],
                qx.at[pl.ds(s, 1), :], sem.at[slot])

        def warm(s, carry):
            cp(s, s % GATHER_BUF).start()
            return carry

        lax.fori_loop(0, min(GATHER_BUF, q), warm, 0)

        def hop(s, carry):
            # Wait slot s FIRST, then refill it with copy s+GATHER_BUF:
            # each slot's semaphore tracks exactly one in-flight copy.
            cp(s, s % GATHER_BUF).wait()

            @pl.when(s + GATHER_BUF < q)
            def _refill():
                cp(s + GATHER_BUF, s % GATHER_BUF).start()

            return carry

        lax.fori_loop(0, q, hop, 0)

    qv = qx[...]  # (q, d), x storage dtype
    dots = jnp.dot(qv, x_blk[...].T, preferred_element_type=jnp.float32)
    krows_ref[...] = kernel_from_dots(dots, xsq_blk[0], qsq_blk[0], kp)

    @pl.when(i == 0)
    def _gram():
        dots_w = jnp.dot(qv, qv.T, preferred_element_type=jnp.float32)
        kb_ref[...] = kernel_from_dots(dots_w, qsq_blk[0], qsq_blk[0], kp)


@functools.partial(jax.jit, static_argnames=("kp", "interpret"))
def gather_gram(x, w, x_sq, qsq, kp, interpret: bool = False):
    """The round's single pass over X: gather the working-set rows
    in-kernel and emit the (q, n_pad) kernel rows K(W, :) plus the
    (q, q) Gram block K(W, W) in one pallas_call.

    x (n_pad, d) any float dtype, n_pad % TILE_ROWS == 0; w (q,) int32
    ids (< n_pad — dead slots carry in-range filler, exactly what the
    stock gather reads); x_sq (n_pad,) / qsq (q,) float32 squared
    norms. Returns (k_rows f32 (q, n_pad), kb f32 (q, q)) — bitwise
    what ``kernel_rows(x, x_sq, take(x, w), qsq, kp)`` and the stock
    Gram-block matmul produce (output-dim tiling only)."""
    n_pad, d = x.shape
    q = w.shape[0]
    assert n_pad % TILE_ROWS == 0, (n_pad, TILE_ROWS)
    ntiles = n_pad // TILE_ROWS
    kern = functools.partial(_gather_gram_kernel, q=q, kp=kp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ntiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((TILE_ROWS, d), lambda i, w: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_ROWS), lambda i, w: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, q), lambda i, w: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((q, TILE_ROWS), lambda i, w: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q, q), lambda i, w: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((q, d), x.dtype),
                        pltpu.SemaphoreType.DMA((GATHER_BUF,))],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((q, n_pad), jnp.float32),
                   jax.ShapeDtypeStruct((q, q), jnp.float32)],
        interpret=interpret,
    )(w, x, x, x_sq.reshape(1, n_pad), qsq.reshape(1, q))


def _fold_rows_select_kernel(*refs, c, rows_per_block: int,
                             compensated: bool):
    """One (q, TILE_ROWS) block of the fold+select pass: contract the
    fold coefficients against the kernel-row slice in-register, fold
    the resulting delta (Kahan when compensated) and emit the per-row
    candidates — ops/pallas_fold_select.py's kernel with the delta
    input replaced by its own contraction."""
    if compensated:
        (kr_ref, coef_ref, f_ref, err_ref, alpha_ref, y_ref, valid_ref,
         f_out_ref, err_out_ref, upv_ref, upi_ref, lov_ref, loi_ref) = refs
    else:
        (kr_ref, coef_ref, f_ref, alpha_ref, y_ref, valid_ref,
         f_out_ref, upv_ref, upi_ref, lov_ref, loi_ref) = refs
    # The tile's fold delta: (q,) @ (q, TILE_ROWS) — the output-dim
    # slice of the stock engine's coef @ K(W, :) contraction, never
    # written to HBM.
    delta = (coef_ref[0] @ kr_ref[...]).reshape(rows_per_block, LANES)
    f_new, err_new, f_sel = fold_delta(
        f_ref[:], err_ref[:] if compensated else None, delta)
    if compensated:
        err_out_ref[:] = err_new
    f_out_ref[:] = f_new
    base = pl.program_id(0) * (rows_per_block * LANES)
    emit_row_candidates(f_sel, alpha_ref[:], y_ref[:], valid_ref[:], c,
                        rows_per_block, base,
                        upv_ref, upi_ref, lov_ref, loi_ref)


@functools.partial(jax.jit,
                   static_argnames=("c", "compensated", "interpret"))
def fold_rows_select(k_rows, coef, f2d, err2d, alpha2d, y2d, valid2d, c,
                     compensated: bool = False, interpret: bool = False):
    """The round's single pass over the O(n) vectors: fold
    coef @ K(W, :) into f (optionally Kahan-compensated) and emit the
    next round's per-row working-set candidates.

    k_rows (q, n_pad) f32 from gather_gram; coef (q,) f32 fold
    coefficients (dead slots zeroed); the 2D arrays are the
    (n_pad/128, 128) float32 views fold_select uses. Returns
    (f_new2d, err_new2d_or_None, up_vals, up_ids, low_vals, low_ids) —
    exactly fold_select's contract, with delta2d computed in-kernel."""
    rows = f2d.shape[0]
    n_pad = k_rows.shape[1]
    q = k_rows.shape[0]
    assert rows % FOLD_ROWS == 0 and rows * LANES == n_pad, (rows, n_pad)
    nblocks = rows // FOLD_ROWS

    block = pl.BlockSpec((FOLD_ROWS, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    cand = pl.BlockSpec((FOLD_ROWS, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    kr = pl.BlockSpec((q, TILE_ROWS), lambda i: (0, i),
                      memory_space=pltpu.VMEM)
    cf = pl.BlockSpec((1, q), lambda i: (0, 0), memory_space=pltpu.VMEM)
    kern = functools.partial(_fold_rows_select_kernel, c=c,
                             rows_per_block=FOLD_ROWS,
                             compensated=compensated)
    full = jax.ShapeDtypeStruct((rows, LANES), jnp.float32)
    cval = jax.ShapeDtypeStruct((rows, 1), jnp.float32)
    cidx = jax.ShapeDtypeStruct((rows, 1), jnp.int32)

    if compensated:
        ins = (k_rows, coef.reshape(1, q), f2d, err2d, alpha2d, y2d,
               valid2d)
        in_specs = [kr, cf, block, block, block, block, block]
        out_specs = [block, block, cand, cand, cand, cand]
        out_shape = [full, full, cval, cidx, cval, cidx]
    else:
        ins = (k_rows, coef.reshape(1, q), f2d, alpha2d, y2d, valid2d)
        in_specs = [kr, cf, block, block, block, block]
        out_specs = [block, cand, cand, cand, cand]
        out_shape = [full, cval, cidx, cval, cidx]

    outs = pl.pallas_call(
        kern,
        grid=(nblocks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*ins)
    if compensated:
        f_new, err_new, upv, upi, lov, loi = outs
    else:
        f_new, upv, upi, lov, loi = outs
        err_new = None
    return (f_new, err_new, upv[:, 0], upi[:, 0], lov[:, 0], loi[:, 0])


def fused_round(x, y, x_sq, k_diag, y2d, valid2d, alpha, f, f_err,
                w, slot_ok, b_hi, b_lo, budget_left, kp, c, eps: float,
                tau: float, q: int, inner_iters: int, inner_impl: str,
                interpret: bool, selection: str, pair_batch: int = 1):
    """The thin composition layer: ONE complete block round as
    gather_gram -> dispatch_subproblem -> scatter -> fold_rows_select,
    stage-for-stage the body of solver/block.py run_chunk_block_fused
    with the XLA gather/Gram/kernel-rows/delta stages replaced by the
    two one-pass kernels (each replacement bitwise-exact — see module
    docstring), so the trajectories are pinned bitwise equal.

    `(w, slot_ok, b_hi, b_lo)` is the carried candidate set selected by
    the PREVIOUS round's fold pass (exact post-fold extrema — the fused
    engine's carry contract). Returns (alpha, f, f_err, b_hi_n, b_lo_n,
    w_n, ok_n, t): the updated row state, the next round's candidates
    and the executed pair count."""
    from dpsvm_tpu.ops.pallas_fold_select import assemble_working_set
    from dpsvm_tpu.solver.block import dispatch_subproblem

    n_pad = y.shape[0]
    shp = (n_pad // LANES, LANES)
    compensated = f_err is not None
    f_cur = f if f_err is None else f - f_err  # eff_f on loose fields
    gap_open = b_lo > b_hi + 2.0 * eps
    with jax.named_scope("fusedround_gather_gram"):
        qsq = jnp.take(x_sq, w)
        kd_w = jnp.take(k_diag, w)
        a_w0 = jnp.take(alpha, w)
        y_w = jnp.take(y, w)
        f_w0 = jnp.take(f_cur, w)
        k_rows, kb_w = gather_gram(x, w, x_sq, qsq, kp,
                                   interpret=interpret)
    # Per-round pair budget: clamped to the caller's remaining budget
    # and gated to 0 on the terminal round (same as _round_core).
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    with jax.named_scope("fusedround_subproblem"):
        a_w, coef, t = dispatch_subproblem(
            kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
            inner_impl, interpret, selection, pair_batch=pair_batch)
    # Scatter alpha BEFORE the fused pass: its selection masks must see
    # the updated box membership (the run_chunk_block_fused contract).
    safe_w = jnp.where(slot_ok, w, jnp.int32(n_pad))
    alpha = alpha.at[safe_w].set(jnp.where(slot_ok, a_w, 0.0),
                                 mode="drop")
    err2d = f_err.reshape(shp) if compensated else None
    with jax.named_scope("fusedround_fold_select"):
        f2d, err_new2d, upv, upi, lov, loi = fold_rows_select(
            k_rows, coef, f.reshape(shp), err2d, alpha.reshape(shp),
            y2d, valid2d, c, compensated=compensated,
            interpret=interpret)
    w_n, ok_n, b_hi_n, b_lo_n = assemble_working_set(upv, upi, lov, loi,
                                                     q // 2)
    return (alpha, f2d.reshape(n_pad),
            err_new2d.reshape(n_pad) if compensated else None,
            b_hi_n, b_lo_n, w_n, ok_n, t)
