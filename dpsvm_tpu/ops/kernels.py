"""Kernel (Gram) evaluation primitives, MXU-first.

The reference evaluates kernel rows two ways:
  * device: cuBLAS sgemv X . x_i producing a dot-product row, then rebuilds
    the RBF value per element as exp(-gamma (|x_i|^2 + |x_j|^2 - 2 dot))
    inside the f-update functor (svmTrain.cu:222,247 and :128-135);
  * host: CBLAS saxpy + snrm2 per pair (svmTrain.cu:696-714, seq.cpp:398-415).

Here every kernel family is derived from dot products (plus cached squared
norms for RBF), so the dot-product row is the one cached/communicated
quantity — exactly the property the reference's cache exploits (cache.cu
stores dot rows, not exp'd rows). Dots are computed on the MXU via jnp.dot
with float32 accumulation; storage dtype of X may be bfloat16.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class KernelParams:
    """Static kernel parameters (hashable -> usable as a jit static arg)."""

    kind: str = "rbf"  # rbf | linear | poly | sigmoid | precomputed
    gamma: float = 1.0
    degree: int = 3
    coef0: float = 0.0

    def npz_fields(self) -> dict:
        """The .npz serialization of the kernel, shared by every model
        class (SVMModel / SVRModel / OneClassModel) so the format lives in
        exactly one place."""
        import numpy as np

        return {
            "kernel_kind": self.kind,
            "gamma": np.float32(self.gamma),
            "degree": np.int32(self.degree),
            "coef0": np.float32(self.coef0),
        }

    @classmethod
    def from_npz(cls, z) -> "KernelParams":
        return cls(kind=str(z["kernel_kind"]), gamma=float(z["gamma"]),
                   degree=int(z["degree"]), coef0=float(z["coef0"]))


def squared_norms(x: jax.Array) -> jax.Array:
    """Per-row |x_i|^2, shape (n,).

    The reference computes these once at setup with n sequential
    thrust::inner_product launches (svmTrain.cu:361-364); here it is one
    fused reduction.
    """
    xf = x.astype(jnp.float32)
    return jnp.einsum("nd,nd->n", xf, xf)


def row_dots(x: jax.Array, q: jax.Array) -> jax.Array:
    """Dot-product rows X . q^T on the MXU.

    x: (n, d) data matrix (any float dtype); q: (k, d) or (d,) query rows.
    Returns float32 (k, n) or (n,). Equivalent of the reference's
    cublasSgemv row evaluation (svmTrain.cu:222,247) but batched so hi/lo
    rows share one pass over X.
    """
    squeeze = q.ndim == 1
    q2 = jnp.atleast_2d(q).astype(x.dtype)
    out = jnp.dot(q2, x.T, preferred_element_type=jnp.float32)
    return out[0] if squeeze else out


def kernel_from_dots(
    dots: jax.Array,
    x_sq: jax.Array,
    q_sq: jax.Array,
    params: KernelParams,
) -> jax.Array:
    """Turn dot-product rows into kernel rows.

    dots: (..., n) dot rows; x_sq: (n,) squared norms of the data rows;
    q_sq: (...,) squared norms of the query rows (ignored except for rbf).
    RBF matches the reference's update_functor algebra
    exp(-gamma (x_sq + q_sq - 2 dot)) (svmTrain.cu:128-135).
    """
    dots = dots.astype(jnp.float32)
    if params.kind == "precomputed":
        raise ValueError(
            "precomputed kernels have no dot-product form; gather rows of "
            "the Gram matrix instead (kernel_rows handles this)")
    if params.kind == "linear":
        return dots
    if params.kind == "rbf":
        q_sq = jnp.asarray(q_sq, jnp.float32)
        sq_dist = x_sq + q_sq[..., None] if dots.ndim > 1 else x_sq + q_sq
        sq_dist = jnp.maximum(sq_dist - 2.0 * dots, 0.0)
        return jnp.exp(-params.gamma * sq_dist)
    if params.kind == "poly":
        return (params.gamma * dots + params.coef0) ** params.degree
    if params.kind == "sigmoid":
        return jnp.tanh(params.gamma * dots + params.coef0)
    raise ValueError(f"unknown kernel kind {params.kind!r}")


def kernel_diag(x_sq: jax.Array, params: KernelParams) -> jax.Array:
    """Diagonal K(x_i, x_i) for all rows, from the cached squared norms:
    dot(x_i, x_i) == |x_i|^2, so this is kernel_from_dots applied
    elementwise (for rbf the distance term cancels to 0 -> 1). Needed by
    second-order working-set selection for the curvature eta_ij."""
    x_sq = x_sq.astype(jnp.float32)
    if params.kind == "rbf":
        # Shortcut the exp(0): exact ones, no transcendental.
        return jnp.ones_like(x_sq)
    return kernel_from_dots(x_sq, x_sq, x_sq, params)


def kernel_rows(
    x: jax.Array,
    x_sq: jax.Array,
    q: jax.Array,
    q_sq: jax.Array,
    params: KernelParams,
) -> jax.Array:
    """Full kernel rows K(q_k, x_i): (k, n) or (n,).

    kind="precomputed" (LibSVM -t 4): `x` IS the (n, n) Gram matrix, so a
    gathered query row already holds its kernel values — return it
    verbatim (no dot products exist to compute)."""
    if params.kind == "precomputed":
        return q.astype(jnp.float32)
    return kernel_from_dots(row_dots(x, q), x_sq, q_sq, params)


def blocked_kernel_matvec(x, coef, params: KernelParams,
                          dtype: str = "float32", block: int = 8192):
    """K(x, x_active) @ coef_active without materializing more than a
    (block, n_active) kernel tile — the initial-gradient evaluator shared
    by the warm-started reductions (one-class, nu-SVC).

    `dtype` is the solver's X storage dtype: with bfloat16 storage the
    solver's own kernel rows see the bf16-rounded features, so this must
    evaluate on the same rounded values or the start gradient is
    ~1e-3-relative inconsistent with every subsequent rank-2 update — an
    error the solver can never repair. Returns float32 (n,).
    """
    import numpy as np

    x = np.asarray(x, np.float32)
    coef = np.asarray(coef, np.float32)
    xj = jnp.asarray(x)
    if dtype == "bfloat16":
        xj = xj.astype(jnp.bfloat16)
    active = coef != 0
    if not active.any():
        return np.zeros((x.shape[0],), np.float32)
    xa = xj[np.nonzero(active)[0]]
    ca = jnp.asarray(coef[active])
    out = np.empty((x.shape[0],), np.float32)
    for s in range(0, x.shape[0], block):
        k = kernel_matrix(xj[s:s + block], xa, params)
        out[s:s + block] = np.asarray(k @ ca)
    return out


def bf16_rbf_perturbation(x, gamma: float, sample: int = 2048,
                          pairs: int = 4096, seed: int = 0) -> float:
    """p90 of |K_exact - K_bf16-stored| over sampled pairs: how much
    storing X in bfloat16 perturbs RBF kernel values for THIS data.

    The footgun it quantifies (measured, BENCH_COVTYPE.md): at the
    reference's covtype stress config (c=2048, gamma=0.03125) bf16
    storage silently drops train accuracy from 0.97 to 0.59 — the box
    bound C amplifies kernel perturbation into O(1) decision changes, so
    the risk scale is C * p90|dK| (0.46 for the failing covtype config
    vs <= 0.001 for the mnist-shaped headline and adult-shaped configs).
    Host NumPy on a seeded sample; ~ms cost.
    """
    import ml_dtypes
    import numpy as np

    x = np.asarray(x, np.float32)
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    idx = rng.choice(n, min(sample, n), replace=False)
    s = x[idx].astype(np.float64)
    sb = x[idx].astype(ml_dtypes.bfloat16).astype(np.float64)
    i = rng.integers(0, len(s), pairs)
    j = rng.integers(0, len(s), pairs)

    def kvals(a):
        nrm = (a ** 2).sum(1)
        d2 = np.maximum(nrm[i] + nrm[j]
                        - 2.0 * np.einsum("nd,nd->n", a[i], a[j]), 0.0)
        return np.exp(-gamma * d2)

    return float(np.percentile(np.abs(kvals(s) - kvals(sb)), 90))


def bf16_kernel_perturbation(x, params: KernelParams, sample: int = 2048,
                             pairs: int = 4096, seed: int = 0) -> float:
    """p90 of |K_exact - K_bf16-stored| over sampled pairs for ANY
    feature kernel — the generalization of bf16_rbf_perturbation the
    training bf16-Gram gate needs (ISSUE 11): rbf delegates to the
    measured-failure-calibrated original; linear/poly/sigmoid sample
    the same pair population through their own dot-product algebra
    (f64 exact vs bf16-rounded features, f64 accumulation — the
    rounding under test is STORAGE rounding, matching how the solver's
    f32-accumulating MXU passes see bf16 X). Host NumPy on a seeded
    sample; ~ms cost; deterministic for fixed (x, params, seed)."""
    if params.kind == "rbf":
        return bf16_rbf_perturbation(x, params.gamma, sample=sample,
                                     pairs=pairs, seed=seed)
    if params.kind == "precomputed":
        raise ValueError(
            "precomputed kernels carry values, not features; there is "
            "no storage-rounding perturbation to sample")
    import ml_dtypes
    import numpy as np

    x = np.asarray(x, np.float32)
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    idx = rng.choice(n, min(sample, n), replace=False)
    s = x[idx].astype(np.float64)
    sb = x[idx].astype(ml_dtypes.bfloat16).astype(np.float64)
    i = rng.integers(0, len(s), pairs)
    j = rng.integers(0, len(s), pairs)

    def kvals(a):
        dots = np.einsum("nd,nd->n", a[i], a[j])
        if params.kind == "linear":
            return dots
        if params.kind == "poly":
            return (params.gamma * dots + params.coef0) ** params.degree
        if params.kind == "sigmoid":
            return np.tanh(params.gamma * dots + params.coef0)
        raise ValueError(f"unknown kernel kind {params.kind!r}")

    return float(np.percentile(np.abs(kvals(s) - kvals(sb)), 90))


def quantize_rows_int8(x):
    """Symmetric per-row int8 quantization of a feature matrix:
    ``(values int8, scales float32)`` with ``values[i] =
    round(x[i] / scales[i])`` clipped to [-127, 127] and ``scales[i] =
    max|x[i]| / 127`` (1.0 for all-zero rows, so dequantization is
    exact zeros instead of 0/0).

    Per-ROW (not per-tensor) because the serving union stacks support
    vectors from many submodels whose feature scales differ; a single
    tensor scale would burn the int8 range on the largest row. The
    symmetric zero-point-free form keeps the dequant fused dot a pure
    rank-1 rescale: ``dots = (q_int8 @ sv_int8^T) * (t_q ⊗ s_sv)`` —
    no zero-point correction terms. Host NumPy (staging-time, like the
    bf16 cast in serve._stage)."""
    import numpy as np

    x = np.asarray(x, np.float32)
    amax = np.abs(x).max(axis=1, initial=0.0)
    scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(x / scales[:, None]), -127, 127).astype(np.int8)
    return q, scales


def dequantize_rows_int8(q, scales):
    """float32 rows from quantize_rows_int8 output — the values the
    int8 dot operands actually carry; squared norms for the rbf
    distance expansion must come from THESE rows (the serving bf16
    path's norms-from-ROUNDED-rows discipline)."""
    import numpy as np

    return (np.asarray(q, np.float32)
            * np.asarray(scales, np.float32)[:, None])


def int8_kernel_perturbation(x, params: KernelParams, sample: int = 2048,
                             pairs: int = 4096, seed: int = 0) -> float:
    """p90 of |K_exact - K_int8-stored| over sampled pairs for any
    feature kernel — the int8 sibling of bf16_kernel_perturbation,
    sampling the SAME pair population with the same seed so the two
    storage candidates are compared on identical pairs. The rounding
    under test is symmetric per-row int8 quantization of the rows
    (quantize_rows_int8 round-trip), matching how the serving int8
    executor's dequant-fused dot sees the union: quantized operands,
    f64-exact accumulation here standing in for the i32-exact MXU
    accumulation (integer dots are EXACT — the only error is storage
    rounding, which is what this samples). rbf norms come from the
    dequantized rows, as the executor computes them. Host NumPy on a
    seeded sample; ~ms cost; deterministic for fixed (x, params,
    seed)."""
    if params.kind == "precomputed":
        raise ValueError(
            "precomputed kernels carry values, not features; there is "
            "no storage-rounding perturbation to sample")
    import numpy as np

    x = np.asarray(x, np.float32)
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    idx = rng.choice(n, min(sample, n), replace=False)
    s = x[idx].astype(np.float64)
    q, scales = quantize_rows_int8(x[idx])
    sq = dequantize_rows_int8(q, scales).astype(np.float64)
    i = rng.integers(0, len(s), pairs)
    j = rng.integers(0, len(s), pairs)

    def kvals(a):
        dots = np.einsum("nd,nd->n", a[i], a[j])
        if params.kind == "linear":
            return dots
        if params.kind == "rbf":
            nrm = (a ** 2).sum(1)
            d2 = np.maximum(nrm[i] + nrm[j] - 2.0 * dots, 0.0)
            return np.exp(-params.gamma * d2)
        if params.kind == "poly":
            return (params.gamma * dots + params.coef0) ** params.degree
        if params.kind == "sigmoid":
            return np.tanh(params.gamma * dots + params.coef0)
        raise ValueError(f"unknown kernel kind {params.kind!r}")

    return float(np.percentile(np.abs(kvals(s) - kvals(sq)), 90))


def storage_perturbation(x, params: KernelParams, storage: str,
                         sample: int = 2048, pairs: int = 4096,
                         seed: int = 0) -> float:
    """p90|dK| for a named union storage: the ONE sampler dispatch the
    serving storage guard scales by its coefficient amplifier. 'f32'
    is exactly 0.0 by definition (no storage rounding)."""
    if storage == "f32":
        return 0.0
    if storage == "bf16":
        return bf16_kernel_perturbation(x, params, sample=sample,
                                        pairs=pairs, seed=seed)
    if storage == "int8":
        return int8_kernel_perturbation(x, params, sample=sample,
                                        pairs=pairs, seed=seed)
    raise ValueError(f"unknown union storage {storage!r}")


# C * p90|dK| above this warns (see bf16_rbf_perturbation): calibrated
# between the measured-failing covtype-stress value (0.46) and the
# passing headline/adult configs (<= 0.001). The int8 serving guard
# reuses the same threshold: the amplifier (max-column ||coef||_1 for
# serving, C for training) times p90|dK| bounds the decision-sum
# perturbation identically regardless of WHICH storage rounding
# produced dK.
BF16_RISK_THRESHOLD = 0.1


def resolve_bf16_gram(x, config, gamma: float, c_max: float = None,
                      scope: str = ""):
    """The per-problem bf16-Gram gate (config.bf16_gram, ISSUE 11):
    decide whether storing X in bfloat16 (f32 MXU accumulation — half
    the Gram-pass HBM read traffic) is safe for THIS (data, config), by
    the same risk scale the ungated dtype='bfloat16' warning and the
    serving engine's bf16 union guard use: C * p90|dK| against
    BF16_RISK_THRESHOLD.

    `c_max` overrides the box bound the risk is scaled by (the fleet
    executor passes the largest bound across its problems — one shared
    X, one storage dtype, the conservative reading); `scope` is spliced
    into the refusal note (e.g. "for the fleet"). THE one definition of
    the gate — solve(), solve_mesh() and solve_fleet() all call here so
    a calibration change can never diverge them.

    Returns (active, risk, stats_entry): `active` says the solve should
    flip storage to bf16; `stats_entry` is the dict the solver merges
    into SolveResult.stats either way, carrying a LOUD `note` when the
    bound refuses (the trajectory would likely degrade — measured 0.97
    -> 0.59 train accuracy on the covtype stress config,
    BENCH_COVTYPE.md) so a refused gate is never silent."""
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    c_ref = max(config.c_bounds()) if c_max is None else float(c_max)
    risk = c_ref * bf16_kernel_perturbation(x, kp)
    active = risk <= BF16_RISK_THRESHOLD
    entry = {"active": active, "risk": round(risk, 6),
             "threshold": BF16_RISK_THRESHOLD}
    if not active:
        where = f" {scope}" if scope else ""
        entry["note"] = (
            f"bf16_gram REFUSED{where}: C * p90|dK| = {risk:.4g} > "
            f"{BF16_RISK_THRESHOLD} — storage rounding at this (C, "
            f"kernel, data) risks O(1) decision changes; Gram stays "
            f"float32 (lower C / raise gamma to re-qualify)")
    return active, risk, entry


def warn_if_bf16_degrades(x, config) -> None:
    """Loud warning when dtype='bfloat16' is configured in a regime where
    storage rounding is likely to destroy solution quality (SURVEY 7.3
    numerics-parity item 3). Called by both solver backends before any
    device work; rbf only (the measured failure mode is the rbf
    exponent's cancellation structure)."""
    if config.dtype != "bfloat16" or config.kernel != "rbf":
        return
    import warnings

    import numpy as np

    gamma = config.resolve_gamma(np.asarray(x).shape[1])
    risk = max(config.c_bounds()) * bf16_rbf_perturbation(x, gamma)
    if risk > BF16_RISK_THRESHOLD:
        warnings.warn(
            f"dtype='bfloat16' is likely to destroy solution quality for "
            f"this data: C * p90|dK| = {risk:.3f} > {BF16_RISK_THRESHOLD} "
            f"(bf16 feature rounding perturbs RBF kernel values enough "
            f"for the box bound C to amplify into O(1) decision changes; "
            f"measured on the covtype stress config this costs 0.97 -> "
            f"0.59 train accuracy, BENCH_COVTYPE.md). Use "
            f"dtype='float32', or lower C / raise gamma.",
            stacklevel=3)


def _gram_tile_body(g, x, x_sq, s, params: KernelParams, tile: int):
    d = x.shape[1]
    qx = lax.dynamic_slice(x, (s, 0), (tile, d))
    qsq = lax.dynamic_slice(x_sq, (s,), (tile,))
    rows = kernel_rows(x, x_sq, qx, qsq, params)  # (tile, n) f32
    return lax.dynamic_update_slice(g, rows, (s, 0))


# The Gram buffer is DONATED through each tile write so the build's peak
# footprint is exactly one (n, n) buffer plus one (tile, n) block. The
# obvious fori_loop formulation is a memory trap on TPU runtimes: the
# compiled while-loop executable keeps an O(n^2) scoped temp reservation
# for as long as it stays in the jit cache, which OOMs the SOLVE
# executor dispatched right after it (measured at n=50k on a 16 GiB
# v5e: build succeeds, the first executor dispatch ResourceExhausts,
# and jax.clear_caches() — unloading the build executable — cures it).
# CPU backends don't implement donation (they'd warn and copy), so the
# undonated variant serves them; their allocator has no such reservation.
_gram_tile_donated = partial(jax.jit, donate_argnums=(0,),
                             static_argnames=("params", "tile"))(_gram_tile_body)
_gram_tile_plain = partial(jax.jit,
                           static_argnames=("params", "tile"))(_gram_tile_body)


def resident_gram(x, x_sq, params: KernelParams, tile: int = 2048):
    """The full (n, n) float32 Gram matrix, built ON DEVICE in row tiles.

    Backs the solver's resident-Gram acceleration (config.gram_resident):
    when the (n, n) matrix fits HBM, the per-pair engine's two kernel
    rows per iteration become ROW GATHERS of this matrix instead of two
    full passes of X through the MXU — at the extreme-C accuracy mode
    (matmul_precision='highest', 6-pass bf16) that removes the dominant
    per-iteration cost entirely. The reference's LRU cache (cache.cu)
    chases the same reuse reactively, one row at a time; a resident Gram
    is the 100%-hit-rate limit of that idea, affordable on a 16 GB-HBM
    TPU for n up to ~60k.

    Host-driven tile loop (~n/tile dispatches) with a donated output
    buffer — see the note above _gram_tile_donated for why this is NOT a
    fori_loop. The last partial tile re-computes a few overlapping rows
    into the same slot rather than tracing a dynamic shape.
    """
    n = x.shape[0]
    t = min(tile, n)
    dev = x.devices().pop()
    step = (_gram_tile_donated
            if getattr(dev, "platform", "cpu") == "tpu"
            else _gram_tile_plain)
    g = jnp.zeros((n, n), jnp.float32, device=dev)
    for i in range(-(-n // t)):
        s = jnp.int32(min(i * t, n - t))
        g = step(g, x, x_sq, s, params=params, tile=t)
    return g


@partial(jax.jit, static_argnames=("params",))
def kernel_matrix(
    a: jax.Array,
    b: jax.Array,
    params: KernelParams,
) -> jax.Array:
    """Dense Gram matrix K(a_i, b_j) of shape (n_a, n_b).

    Used by the predictor and the test oracles; the training path never
    materialises the full Gram matrix (it is O(n^2) — the reason the
    reference exists at all; see SURVEY.md section 5.7).
    """
    if params.kind == "precomputed":
        raise ValueError(
            "precomputed kernels carry no feature vectors; index the "
            "user-supplied Gram matrix (K_test[:, support]) instead")
    a_sq = squared_norms(a)
    b_sq = squared_norms(b)
    dots = jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32).T,
                   preferred_element_type=jnp.float32)
    if params.kind == "linear":
        return dots
    if params.kind == "rbf":
        sq = jnp.maximum(a_sq[:, None] + b_sq[None, :] - 2.0 * dots, 0.0)
        return jnp.exp(-params.gamma * sq)
    if params.kind == "poly":
        return (params.gamma * dots + params.coef0) ** params.degree
    if params.kind == "sigmoid":
        return jnp.tanh(params.gamma * dots + params.coef0)
    raise ValueError(f"unknown kernel kind {params.kind!r}")
