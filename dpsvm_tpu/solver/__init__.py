from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.solver.reference import smo_reference
from dpsvm_tpu.solver.smo import solve as solve_single_chip

__all__ = ["SolveResult", "smo_reference", "solve_single_chip"]
