"""Blockwise working-set (decomposition) SMO engine.

The per-pair engines (solver/smo.py) are HBM-bound: every iteration streams
the full (n, d) data matrix through the MXU to produce two kernel rows
(the reference pays the same way per cuBLAS sgemv on a cache miss,
svmTrain.cu:222,247). This engine amortises that pass with the classic
SVMlight/ThunderSVM decomposition structure re-derived for the TPU memory
hierarchy. Each OUTER round:

  1. selects a working set W of the q most-violating points (top q/2 of
     I_up by smallest f, top q/2 of I_low by largest f — a strict superset
     of the reference's single maximal-violating pair, svmTrain.cu:469-481);
  2. builds the tiny (q, q) Gram block K(W, W) with one (q,d)x(d,q) matmul;
  3. runs up to `inner_iters` exact pair updates ON THE SUBPROBLEM ONLY:
     the loop carry is (alpha_W, f_W) of size q, f_W maintained
     incrementally from K(W, W) rows — nothing of size n is read or
     written inside the loop (per-element gathers from HBM are scalar-core
     DMAs on TPU; keeping the inner state q-sized is what makes inner
     pairs ~100x cheaper than per-pair iterations);
  4. folds the accumulated alpha deltas into the global f with ONE fused
     matmul chain f += K(:, W) @ (dalpha * y_W), re-selects globally, and
     checks the reference's stopping rule b_lo <= b_hi + 2 eps
     (svmTrainMain.cpp:310).

Convergence follows from every W containing the globally most-violating
pair (standard decomposition argument); the fixed point satisfies the same
KKT system, so the optimum matches the per-pair engines. There is no
reference equivalent — the reference's LRU cache (cache.cu) chases the
same HBM-traffic reduction reactively; the block solver gets it
proactively with static shapes, which is what XLA wants.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots, kernel_rows
from dpsvm_tpu.ops.select import (c_of, low_mask, nu_stopping_pair,
                                  select_working_set_nu, split_c, up_mask)
from dpsvm_tpu.solver.smo import pair_alpha_update


class BlockState(NamedTuple):
    """Outer while_loop carry."""

    alpha: jax.Array  # (n,) float32
    f: jax.Array  # (n,) float32
    b_hi: jax.Array  # float32, from the last GLOBAL selection
    b_lo: jax.Array  # float32
    pairs: jax.Array  # int32: total pair updates (comparable to per-pair iters)
    rounds: jax.Array  # int32: outer rounds (block builds)

    @property
    def hits(self):
        """The block engine has no LRU cache (the working-set block IS its
        reuse mechanism); report 0 so cache stats stay consistent
        (MetricsLogger reads state.hits on every backend)."""
        return jnp.int32(0)


def select_block(f, alpha, y, c, q: int, valid=None, rule: str = "mvp"):
    """Pick the q most-violating points: q/2 from I_up (smallest f) and
    q/2 from I_low (largest f). Returns (w, slot_ok, b_hi, b_lo):

      w        (q,) int32 global indices (junk filler where a set ran short)
      slot_ok  (q,) bool — slot holds a real, unique candidate
      b_hi     f32 min f over I_up   (exact: _top_h retains each row's
      b_lo     f32 max f over I_low   true extremum even on the approx path)

    The extrema ride the SAME selection pass, so one call per round serves
    both the working set and the reference's stopping rule
    b_lo <= b_hi + 2 eps (svmTrainMain.cpp:310) — the round body needs no
    separate select_working_set sweep over n.

    A point in I_0 (0 < alpha < C) may appear in both halves; the
    duplicate low-half slot is masked out so each global index occupies at
    most one live slot (two live slots for one point would let the inner
    loop update the same alpha through two disagreeing copies).

    rule="nu" splits the block into per-class quarters instead (q/4 from
    each of I_up/I_low within each class): the nu duals carry one equality
    constraint per class, so the subproblem must be able to pair within
    BOTH classes (ops/select.py select_working_set_nu) — a W with only one
    class's violators could stall the other class's gap. Its (b_hi, b_lo)
    are the larger-violation class's pair, matching
    select_working_set_nu's stopping gap.
    """
    cp, cn = split_c(c)
    up = up_mask(alpha, y, cp, cn)
    low = low_mask(alpha, y, cp, cn)
    if valid is not None:
        up = up & valid
        low = low & valid
    if rule == "nu":
        pos = y > 0
        h = q // 4
        scores = jnp.stack([jnp.where(up & pos, -f, -jnp.inf),
                            jnp.where(low & pos, f, -jnp.inf),
                            jnp.where(up & ~pos, -f, -jnp.inf),
                            jnp.where(low & ~pos, f, -jnp.inf)])
        vals, idx = _top_h(scores, h)  # (4, h)
        # Dedup within a class only (the classes are disjoint).
        w_p, ok_p = combine_halves(idx[0], jnp.isfinite(vals[0]),
                                   idx[1], jnp.isfinite(vals[1]))
        w_n, ok_n = combine_halves(idx[2], jnp.isfinite(vals[2]),
                                   idx[3], jnp.isfinite(vals[3]))
        b_hi, b_lo = nu_stopping_pair(-jnp.max(vals[0]), jnp.max(vals[1]),
                                      -jnp.max(vals[2]), jnp.max(vals[3]))
        return (jnp.concatenate([w_p, w_n]),
                jnp.concatenate([ok_p, ok_n]),
                b_hi.astype(jnp.float32), b_lo.astype(jnp.float32))
    h = q // 2
    # One batched selection over both candidate sides.
    scores = jnp.stack([jnp.where(up, -f, -jnp.inf),
                        jnp.where(low, f, -jnp.inf)])
    vals, idx = _top_h(scores, h)  # (2, h)
    w, slot_ok = combine_halves(idx[0], jnp.isfinite(vals[0]),
                                idx[1], jnp.isfinite(vals[1]))
    # Empty-set semantics match select_working_set: all-(-inf) scores give
    # b_hi=+inf / b_lo=-inf, which reads as a closed gap.
    return w, slot_ok, -jnp.max(vals[0]), jnp.max(vals[1])


def _top_h(scores, h: int):
    """Top-h per row via the TPU-native approximate top-k.

    ``lax.top_k`` over a stacked (r, n) operand falls off XLA's fast path
    for h > ~128 (measured 6.7 ms at n=500k vs 0.77 ms for approx — see
    tools/profile_round.py). ``approx_max_k``'s bin-max construction
    ALWAYS retains each row's true maximum, so the convergence invariant
    (the globally most-violating pair is in W) and the b_hi/b_lo extrema
    are exact; the ~1-2% recall loss only swaps interchangeable mid-rank
    violators. Falls back to exact top_k on non-TPU backends where
    approx_max_k has no fast lowering anyway."""
    if jax.default_backend() == "tpu":
        return lax.approx_max_k(scores, h)
    return lax.top_k(scores, h)


def combine_halves(up_idx, up_ok, low_idx, low_ok):
    """Assemble (w, slot_ok) from the two candidate halves, masking low
    slots that duplicate a LIVE up slot. Only LIVE up slots can shadow a
    low candidate: when I_up runs short, top_k filler indices are
    arbitrary row ids and must not mask out real low-half violators (that
    could hide the global max violator and stall the outer loop with the
    gap open). Shared by the single-chip and mesh selectors."""
    dup = jnp.any((low_idx[:, None] == up_idx[None, :]) & up_ok[None, :],
                  axis=1)
    low_ok = low_ok & ~dup
    w = jnp.concatenate([up_idx, low_idx]).astype(jnp.int32)
    slot_ok = jnp.concatenate([up_ok, low_ok])
    return w, slot_ok


def _solve_subproblem(kb_w, kd_w, slot_ok, alpha_w, y_w, f_w, c,
                      eps: float, tau: float, limit, rule: str = "mvp"):
    """Exact SMO on the q-variable subproblem. All state is q-sized.

    kb_w: (q, q) Gram block K(w_i, w_j); kd_w: (q,) its diagonal. `limit`
    is the pair-update budget for THIS block (dynamic: the per-round
    inner_iters cap already clamped to the remaining max_iter budget).
    Returns (alpha_w, f_w, n_pairs). The first iteration reproduces the
    reference's maximal-violating-pair step exactly (the global argmin /
    argmax live in W by construction).

    rule selects the pairing inside W:
      "mvp"          — maximal-violating pair (reference algorithm);
      "second_order" — i by max violation, j by max second-order gain
                       (f_j - b_hi)^2 / eta_ij over K(W, W)'s row i —
                       LibSVM's WSS2 at essentially zero extra cost
                       because the Gram block is already resident;
      "nu"           — per-class MVP (both pair members share a class;
                       the nu duals' two-equality-constraint rule).
    """
    cp, cn = split_c(c)

    def cond(carry):
        _, _, t, gap_open = carry
        return (t < limit) & gap_open

    def body(carry):
        alpha_w, f_w, t, _ = carry
        up = up_mask(alpha_w, y_w, cp, cn) & slot_ok
        low = low_mask(alpha_w, y_w, cp, cn) & slot_ok
        if rule == "nu":
            # The per-class pairing rule already exists as
            # select_working_set_nu; slot_ok plays the valid-mask role.
            i, b_hi_l, j, b_lo_l = select_working_set_nu(
                f_w, alpha_w, y_w, c, valid=slot_ok)
            gap_open = b_lo_l > b_hi_l + 2.0 * eps
            row_i = lax.dynamic_index_in_dim(kb_w, i, 0, keepdims=False)
        elif rule == "second_order":
            f_up = jnp.where(up, f_w, jnp.inf)
            f_low = jnp.where(low, f_w, -jnp.inf)
            i = jnp.argmin(f_up).astype(jnp.int32)
            b_hi_l = f_up[i]
            b_lo_max = jnp.max(f_low)  # convergence uses the max violator
            gap_open = b_lo_max > b_hi_l + 2.0 * eps
            row_i = lax.dynamic_index_in_dim(kb_w, i, 0, keepdims=False)
            diff = f_w - b_hi_l
            eta_j = jnp.maximum(kd_w[i] + kd_w - 2.0 * row_i, tau)
            gain = jnp.where(low & (diff > 0), diff * diff / eta_j,
                             -jnp.inf)
            # gap_open implies an eligible j exists (some f_low > b_hi);
            # when closed the update is gated off anyway.
            j = jnp.where(gap_open, jnp.argmax(gain), i).astype(jnp.int32)
            b_lo_l = f_w[j]
        else:
            f_up = jnp.where(up, f_w, jnp.inf)
            f_low = jnp.where(low, f_w, -jnp.inf)
            i = jnp.argmin(f_up).astype(jnp.int32)
            j = jnp.argmax(f_low).astype(jnp.int32)
            b_hi_l = f_up[i]
            b_lo_l = f_low[j]
            gap_open = b_lo_l > b_hi_l + 2.0 * eps
            row_i = lax.dynamic_index_in_dim(kb_w, i, 0, keepdims=False)

        row_j = lax.dynamic_index_in_dim(kb_w, j, 0, keepdims=False)
        eta = jnp.maximum(kd_w[i] + kd_w[j] - 2.0 * row_i[j], tau)
        y_i = y_w[i]
        y_j = y_w[j]
        a_i_old = alpha_w[i]
        a_j_old = alpha_w[j]
        a_i_new, a_j_new = pair_alpha_update(
            a_i_old, a_j_old, y_i, y_j, b_hi_l, b_lo_l, eta,
            c_of(y_i, cp, cn), c_of(y_j, cp, cn), gate=gap_open)
        # One-hot writes instead of scatters: q-sized selects fuse into the
        # surrounding elementwise work.
        lanes = jnp.arange(alpha_w.shape[0], dtype=jnp.int32)
        alpha_w = jnp.where(lanes == i, a_i_new, alpha_w)
        alpha_w = jnp.where(lanes == j, a_j_new, alpha_w)
        f_w = f_w + (a_i_new - a_i_old) * y_i * row_i \
                  + (a_j_new - a_j_old) * y_j * row_j
        return alpha_w, f_w, t + jnp.int32(gap_open), gap_open

    alpha_w, f_w, t, _ = lax.while_loop(
        cond, body, (alpha_w, f_w, jnp.int32(0), jnp.bool_(True)))
    return alpha_w, f_w, t


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau", "q",
                                  "inner_iters", "rounds_per_chunk",
                                  "inner_impl", "interpret", "selection"))
def run_chunk_block(x, y, x_sq, k_diag, state: BlockState, max_iter,
                    kp: KernelParams, c, eps: float, tau: float,
                    q: int, inner_iters: int, rounds_per_chunk: int,
                    inner_impl: str = "xla",
                    interpret: bool = False,
                    selection: str = "mvp") -> BlockState:
    """Run up to `rounds_per_chunk` outer rounds fully on device.

    inner_impl: "xla" runs the subproblem as a lax.while_loop of XLA ops
    (portable); "pallas" runs it as one on-core kernel
    (ops/pallas_subproblem.py) — same algebra, far lower per-pair dispatch
    cost on real TPUs.

    selection: "mvp" | "second_order" | "nu" — the subproblem pairing rule
    (see _solve_subproblem). "nu" also switches the outer block selection
    to per-class quarters and the convergence gap to the per-class rule."""
    end = state.rounds + rounds_per_chunk

    def cond(st: BlockState):
        return ((st.rounds < end) & (st.pairs < max_iter)
                & (st.b_lo > st.b_hi + 2.0 * eps))

    def body(st: BlockState):
        # ONE selection pass per round: the same sweep yields the working
        # set for this round AND the stopping extrema of the CURRENT f.
        # The loop cond therefore sees extrema one fold behind; the final
        # convergence round runs with `limit` gated to 0 (a selection +
        # one inert fold), and the exit-state b_hi/b_lo are exact for the
        # final f. Callers that exit on the iteration budget instead
        # refresh the extrema host-side (solver/smo.py).
        w, slot_ok, b_hi, b_lo = select_block(st.f, st.alpha, y, c, q,
                                              rule=selection)
        gap_open = b_lo > b_hi + 2.0 * eps
        qx = jnp.take(x, w, axis=0)  # (q, d)
        qsq = jnp.take(x_sq, w)
        dots_w = jnp.dot(qx.astype(x.dtype), qx.astype(x.dtype).T,
                         preferred_element_type=jnp.float32)
        kb_w = kernel_from_dots(dots_w, qsq, qsq, kp)  # (q, q)
        kd_w = jnp.take(k_diag, w)
        alpha_w0 = jnp.take(st.alpha, w)
        y_w = jnp.take(y, w)
        f_w0 = jnp.take(st.f, w)

        # Per-round pair budget, clamped so total pairs never exceed
        # max_iter (the per-pair engines cap exactly; so must this one)
        # and gated to 0 on the final (already-converged) round.
        limit = jnp.minimum(jnp.int32(inner_iters), max_iter - st.pairs)
        limit = jnp.where(gap_open, limit, 0)
        if inner_impl == "pallas":
            from dpsvm_tpu.ops.pallas_subproblem import solve_subproblem_pallas

            alpha_w, t = solve_subproblem_pallas(
                kb_w, alpha_w0, y_w, f_w0, kd_w,
                slot_ok.astype(jnp.float32), limit, c, eps, tau,
                rule=selection, interpret=interpret)
        else:
            alpha_w, _, t = _solve_subproblem(
                kb_w, kd_w, slot_ok, alpha_w0, y_w, f_w0, c, eps, tau,
                limit, rule=selection)

        # Fold the round's alpha deltas into the global state with one
        # fused matmul chain over X (the single O(n d q) pass per round):
        # f += (dalpha * y)_W @ K(W, :), with K(W, :) from the same
        # kernel_rows machinery every other engine uses.
        coef = jnp.where(slot_ok, (alpha_w - alpha_w0) * y_w, 0.0)  # (q,)
        k_rows = kernel_rows(x, x_sq, qx, qsq, kp)  # (q, n) fp32
        f = st.f + coef @ k_rows
        # Dead slots must not scatter. The inert index must be OUT OF
        # RANGE (n), not -1: mode="drop" only drops beyond-range indices,
        # while -1 wraps to the LAST row and would erase its alpha.
        safe_w = jnp.where(slot_ok, w, jnp.int32(st.alpha.shape[0]))
        alpha = st.alpha.at[safe_w].set(
            jnp.where(slot_ok, alpha_w, 0.0), mode="drop")
        return BlockState(alpha, f, b_hi, b_lo, st.pairs + t, st.rounds + 1)

    return lax.while_loop(cond, body, state)

