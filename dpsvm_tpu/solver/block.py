"""Blockwise working-set (decomposition) SMO engine.

The per-pair engines (solver/smo.py) are HBM-bound: every iteration streams
the full (n, d) data matrix through the MXU to produce two kernel rows
(the reference pays the same way per cuBLAS sgemv on a cache miss,
svmTrain.cu:222,247). This engine amortises that pass with the classic
SVMlight/ThunderSVM decomposition structure re-derived for the TPU memory
hierarchy. Each OUTER round:

  1. selects a working set W of the q most-violating points (top q/2 of
     I_up by smallest f, top q/2 of I_low by largest f — a strict superset
     of the reference's single maximal-violating pair, svmTrain.cu:469-481);
  2. builds the tiny (q, q) Gram block K(W, W) with one (q,d)x(d,q) matmul;
  3. runs up to `inner_iters` exact pair updates ON THE SUBPROBLEM ONLY:
     the loop carry is (alpha_W, f_W) of size q, f_W maintained
     incrementally from K(W, W) rows — nothing of size n is read or
     written inside the loop (per-element gathers from HBM are scalar-core
     DMAs on TPU; keeping the inner state q-sized is what makes inner
     pairs ~100x cheaper than per-pair iterations);
  4. folds the accumulated alpha deltas into the global f with ONE fused
     matmul chain f += K(:, W) @ (dalpha * y_W), re-selects globally, and
     checks the reference's stopping rule b_lo <= b_hi + 2 eps
     (svmTrainMain.cpp:310).

Convergence follows from every W containing the globally most-violating
pair (standard decomposition argument); the fixed point satisfies the same
KKT system, so the optimum matches the per-pair engines. There is no
reference equivalent — the reference's LRU cache (cache.cu) chases the
same HBM-traffic reduction reactively; the block solver gets it
proactively with static shapes, which is what XLA wants.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots, kernel_rows
from dpsvm_tpu.ops.select import (c_of, candidate_live_mask, low_mask,
                                  nu_stopping_pair, select_working_set_nu,
                                  split_c, up_mask)
from dpsvm_tpu.solver.smo import eff_f, maybe_kahan, pair_alpha_update


class BlockState(NamedTuple):
    """Outer while_loop carry."""

    alpha: jax.Array  # (n,) float32
    f: jax.Array  # (n,) float32
    b_hi: jax.Array  # float32, from the last GLOBAL selection
    b_lo: jax.Array  # float32
    pairs: jax.Array  # int32: total pair updates (comparable to per-pair iters)
    rounds: jax.Array  # int32: outer rounds (block builds)
    # Kahan residual of f (config.compensated; see solver/smo.py
    # kahan_add): the fold's delta accumulates compensated so the carried
    # gradient stays honest at extreme C. None = compensation off.
    f_err: Optional[jax.Array] = None

    @property
    def hits(self):
        """The block engine has no LRU cache (the working-set block IS its
        reuse mechanism); report 0 so cache stats stay consistent
        (MetricsLogger reads state.hits on every backend)."""
        return jnp.int32(0)


def fused_fold_pays(n_rows: int, d: int) -> bool:
    """The fused fold+select auto-gate's measured crossover (shared by
    the single-chip and mesh paths so the constants live once).

    Round-5 same-session sweep (tools/profile_round.py --ablate-only,
    fused-vs-plain FIXED round cost, q=512, fp32, real v5e):

      | rows | d=54 plain/fused | d=784 plain/fused |
      |------|------------------|-------------------|
      | 100k | 1.44 / 1.11 ms (-23%) | 2.03 / 2.28 ms (+13%) |
      | 150k | 1.63 / 1.44 ms (-12%) | 2.84 / 2.66 ms (-6%)  |
      | 250k | 1.86 / 1.79 ms (-4%)  | 3.93 / 3.85 ms (-2%)  |

    Small-d rounds win from ~100k rows (selection mask-building over n
    is a larger fraction of their round); large-d rounds cross between
    100k and 150k (the fold matmul dominates and the fuse's extra
    launch costs relatively more). Round-4's single 200k constant sat
    inside the unmeasured 60k-500k band — the verdict's item 6."""
    return n_rows >= (100_000 if d <= 128 else 150_000)


def shardlocal_pays(n_loc: int, d: int) -> bool:
    """Auto-gate for the SHARD-LOCAL mesh working-set engine
    (parallel/dist_block.py make_block_shardlocal_chunk_runner;
    config.local_working_sets). Same single-source discipline as
    fused_fold_pays / pipeline_pays: the gate constants come from a
    device measurement or the gate stays off.

    Status (2026-08-03): the engine is implemented and CPU-verified
    (tests/test_shardlocal.py: 8-virtual-device trajectories reach the
    oracle optimum; the endgame demotion restores exact final
    convergence), its per-sync collective structure is pinned from
    compiled HLO, and the A/B probe exists (tools/profile_round.py
    --shardlocal) — but no TPU was reachable this session, so there is
    no measured crossover and the honest auto default is OFF everywhere
    (config.local_working_sets >= 2 forces it on for measurement and
    for the CPU tests). Expected shape of the eventual gate, from the
    docs/SCALING.md round-7 model: pays when the replicated subproblem
    chain dominates the round (the covtype P=8 regime, where it is THE
    Amdahl term) and the CPU-measured pair-inflation factor kappa stays
    under ~5; does NOT pay at P=1 (pure sync overhead) or under tiny
    per-shard row counts where local working sets starve. This is the
    NO-PROFILE default: an installed DeviceProfile's measured verdict
    (dpsvm_tpu/autotune, `make autotune` on the pod) overrides it via
    resolve_auto_gate."""
    return False


def ring_pays(n_dev: int, n_loc: int, d: int) -> bool:
    """Auto-gate for the ring-overlapped mesh candidate exchange
    (ops/ring.py; config.ring_exchange). Same single-source discipline
    as pipeline_pays / shardlocal_pays: the gate constants come from a
    device measurement or the gate stays off.

    Status (2026-08-04): the kernels are implemented and CPU-verified
    bit-identical to the all_gather path in interpret mode
    (tests/test_ring.py; all three runners), the device-form collective
    contract is pinned by the tpulint mesh_chunk_ring /
    shardlocal_chunk_ring budgets, and the A/B probe exists
    (tools/profile_round.py --ring) — but no TPU was reachable this
    session, so there is no measured crossover and the honest auto
    default is OFF everywhere (config.ring_exchange=True forces it on
    for measurement and for the CPU tests). Expected shape of the
    eventual gate: pays when per-round exchange latency is a visible
    round fraction — small n_loc (latency-bound rounds) or large P
    (XLA's all_gather+psum dispatch chain grows while the ring's
    per-hop payload shrinks); the shard-local in-kernel fold pays when
    the window fold matmul is long enough to hide a hop's DMA
    (max(DMA, matmul) vs DMA + matmul). This is the NO-PROFILE
    default: an installed DeviceProfile's measured verdict
    (dpsvm_tpu/autotune) overrides it via resolve_auto_gate."""
    return False


def fused_round_pays(n_rows: int, d: int) -> bool:
    """Auto-gate for the ONE-HBM-PASS fused round (ops/pallas_round.py;
    config.fused_round). Same single-source discipline as
    pipeline_pays / ring_pays: the gate constants come from a device
    measurement or the gate stays off.

    Status (2026-08-04): the kernels are implemented and CPU-verified
    bitwise identical to the stock fused engine in interpret mode
    (tests/test_fused_round.py pins full-solve trajectories across both
    selection rules and the compensated carry), the device-form
    structure is pinned by the tpulint block_chunk_fusedround budget,
    and the A/B probe exists (tools/profile_round.py --fused-round) —
    but no TPU was reachable this session, so there is no measured
    crossover and the honest auto default is OFF everywhere
    (config.fused_round=True forces it on for measurement and for the
    CPU tests). Expected shape of the eventual gate: pays where the
    round is HBM-bound on X and the launch floor matters — large n*d
    at small-to-moderate q (the one-pass kernel removes the qx/dots
    round-trips and three XLA launches from the fixed round cost), and
    should inherit fused_fold_pays' d-dependent crossover shape since
    it strictly extends that kernel's fusion. This is the NO-PROFILE
    default: an installed DeviceProfile's measured verdict
    (dpsvm_tpu/autotune, ROADMAP item 5's one-command pod TODO)
    overrides it via resolve_auto_gate."""
    return False


def pipeline_pays(n_rows: int, d: int) -> bool:
    """Auto-gate for the PIPELINED round engine (run_chunk_block_pipelined
    / the mesh pipelined runner), same single-source discipline as
    fused_fold_pays: gate constants come from measurement or the gate
    stays off.

    Status (2026-08-03): the engine is implemented and CPU-verified
    exact, and the A/B ablation probes exist (tools/profile_round.py
    --pipeline), but no TPU was reachable this session, so there is no
    measured crossover yet — the honest auto default is OFF everywhere
    (config.pipeline_rounds=True forces it on for measurement and for
    the CPU tests). Expected shape of the eventual gate, from the
    SCALING.md overlapped cost model: single-chip is predicted ~wash
    (TPU cores run one kernel at a time, so the reordering only
    shortens the dependency chain, not the kernel-time sum), while the
    MESH engine is where the overlap is structural — the prefetched
    all_gather/psum pair is collective-async and CAN hide behind the
    replicated subproblem chain. This is the NO-PROFILE default: an
    installed DeviceProfile's measured verdict (dpsvm_tpu/autotune)
    overrides it via resolve_auto_gate; PROFILE.md's pipelined section
    tracks the pending measurement."""
    return False


def ooc_shrink_pays(n_rows: int, d: int) -> bool:
    """Auto-gate for the ooc SHRUNKEN tile stream (solver/ooc.py;
    config.ooc_shrink — Joachims-style active-set shrinking over the
    out-of-core fold). Same single-source discipline as
    fused_round_pays / ring_pays: the gate constants come from a device
    measurement or the gate stays off.

    Status (2026-08-07): the shrunken stream is implemented and
    CPU-verified exact (tests/test_ooc.py: shrink-on solves meet the
    identical stopping rule via per-cycle full-stream reconstruction
    and the endgame demotion; resume is bitwise), the tile-skip
    structure is pinned by the tpulint ooc_fold_tile_shrink budget,
    and the A/B probe exists (autotune/probes.py probe_ooc_shrink,
    tools/profile_round.py --ooc-shrink) — but no TPU was reachable
    this session, so there is no measured crossover and the honest
    auto default is OFF everywhere (config.ooc_shrink=True or
    active_set_size>0 forces it on for measurement and for the CPU
    tests). Expected shape of the eventual gate: pays late in training
    on H2D-bound streams — large n*d where most rows sit at bound and
    the skipped tile bytes dwarf the per-cycle reconstruction stream
    (roughly when the active fraction drops under
    1 - tile_cost_ratio); does NOT pay at small n (the full stream is
    one tile anyway) or when the working set churns across the whole
    index space and re-shrinks thrash. This is the NO-PROFILE default:
    an installed DeviceProfile's measured verdict (dpsvm_tpu/autotune)
    overrides it via resolve_auto_gate."""
    return False


def resolve_auto_gate(knob: str, default: bool,
                      device_kind: str = "") -> tuple:
    """Resolve one ``None``-valued (auto) accelerator knob: the
    installed :mod:`dpsvm_tpu.autotune` DeviceProfile's measured
    verdict for this device kind when one exists, else `default` (the
    hand-measured ``*_pays`` expressions above — the ISSUE 14 loop
    closure: the obs spine's probe measurements now DECIDE the gates
    instead of every gate sitting hard-OFF "pending device
    measurement").

    Returns ``(decision, provenance)`` where provenance is the
    JSON-able record the solvers embed in ``SolveResult.stats
    ['autotune']`` and the runlog manifest: ``{"source": "profile",
    profile file, probe ratio, threshold, ...}`` or ``{"source":
    "default", "decision": ...}``. A profile can only carry a True
    verdict from an AUTHORITATIVE (real-device) probe — see
    autotune/probes.py — so installing the committed CPU-harness seed
    profile provably never changes a compiled program."""
    from dpsvm_tpu.autotune.profile import gate_decision

    hit = gate_decision(knob, device_kind=device_kind or None)
    if hit is None:
        return bool(default), {"source": "default",
                               "decision": bool(default)}
    return bool(hit["decision"]), {"source": "profile", **hit}


def autotune_gate_resolver(device) -> tuple:
    """The solvers' shared gate-resolution scaffold: returns
    ``(gate, embed)`` where ``gate(knob, default)`` resolves one auto
    knob via :func:`resolve_auto_gate` (accumulating provenance) and
    ``embed()`` renders the accumulated records as the
    ``{"autotune": {...}}`` fragment both smo.py and dist_smo.py splat
    into ``SolveResult.stats`` AND the runlog manifest — ONE
    definition of the record shape the obs report's profile column and
    tests/test_autotune.py's stats/manifest parity pin read."""
    from dpsvm_tpu.autotune.profile import device_kind_of

    dev_kind = device_kind_of(device)
    prov: dict = {}

    def gate(knob: str, default: bool) -> bool:
        dec, rec = resolve_auto_gate(knob, default,
                                     device_kind=dev_kind)
        prov[knob] = rec
        return dec

    def embed() -> dict:
        return ({"autotune": {"device_kind": dev_kind, "gates": prov}}
                if prov else {})

    return gate, embed


#: the SAFE configuration (ISSUE 13 graceful degradation): knob ->
#: safe value. f32 storage and the stock engines — every fused /
#: pipelined / reduced-precision accelerator drops out, because those
#: are exactly the knobs that can amplify a hostile coefficient scale
#: into a non-finite carried gradient (the bf16 guards bound the
#: NORMAL case; the demotion path is the backstop for the tail).
_SAFE_KNOBS = (
    ("dtype", "float32"),
    ("bf16_gram", False),
    ("fused_round", False),
    ("fused_fold", False),
    ("pipeline_rounds", False),
)


def demote_to_safe(config):
    """(safe_config, dropped_knobs) for the graceful-degradation path
    (solver/smo.py _solve_with_degradation): the same config with
    every risky knob at its safe value, or ``(None, ())`` when the
    config is ALREADY safe — then a non-finite trajectory is a real
    numerics bug the caller must propagate, not retry.

    A knob counts as DROPPED only when it was truthy; None auto-gates
    are still pinned to False in the demoted config (a measured-pays
    profile must not silently re-enable a fused path on the safe
    rerun) but do not by themselves make a config "unsafe"."""
    changes = {}
    dropped = []
    for knob, safe in _SAFE_KNOBS:
        cur = getattr(config, knob)
        if knob == "dtype":
            if cur != safe:
                changes[knob] = safe
                dropped.append(f"dtype={cur}")
        else:
            if cur is not safe:
                changes[knob] = safe
            if cur:
                dropped.append(knob)
    if not dropped:
        return None, ()
    return config.replace(**changes), tuple(dropped)


class PipelinedCand(NamedTuple):
    """The pipelined engine's loop-carried prefetch: the NEXT round's
    working set plus everything about it that does not depend on the
    in-flight round's updates (rows, norms, Gram block, kernel diag —
    all pure functions of X and the candidate ids, hence EXACT no matter
    how stale the selection that picked them). Per-slot alpha/f are NOT
    staged: they change under the in-flight round, so the handoff
    gathers them fresh (the corrected-gradient re-rank contract)."""

    w: jax.Array  # (q,) int32 global candidate ids
    ok: jax.Array  # (q,) bool live-slot mask from the selection
    b_hi: jax.Array  # f32 stopping extrema of the f the selection saw
    b_lo: jax.Array
    qx: jax.Array  # (q, d) candidate rows (x.dtype)
    qsq: jax.Array  # (q,) squared norms
    kb: jax.Array  # (q, q) f32 Gram block K(W, W)
    kd: jax.Array  # (q,) f32 kernel diagonal at W


def prefetch_working_set(x, y, x_sq, k_diag, f, alpha, valid, kp, c,
                         q: int, selection: str,
                         pallas_select: bool = False,
                         interpret: bool = False) -> PipelinedCand:
    """Select the NEXT round's working set from (f, alpha) and stage its
    data-side artifacts. Everything here is a function of the PRE-fold
    carry only — no data dependence on the in-flight round's subproblem,
    fold or scatter — which is the whole point: XLA is free to schedule
    this stage (and on the mesh, its collectives) concurrently with the
    round's serial q-sized chain.

    pallas_select=True swaps the full-n mask+approx_max_k selection for
    the one-pass Pallas candidate kernel (ops/pallas_fold_select.py
    select_rows + assemble_working_set — the pre-fold variant of the
    fused engine's selection); requires the fused path's padding
    contract (n % 1024 == 0, q/2 <= n/128, two-sided selection)."""
    if pallas_select:
        from dpsvm_tpu.ops.pallas_fold_select import (assemble_working_set,
                                                      select_rows)

        n_pad = y.shape[0]
        shp = (n_pad // 128, 128)
        upv, upi, lov, loi = select_rows(
            f.reshape(shp), alpha.reshape(shp), y.reshape(shp),
            valid.astype(jnp.float32).reshape(shp), c,
            interpret=interpret)
        w, ok, b_hi, b_lo = assemble_working_set(upv, upi, lov, loi,
                                                 q // 2)
    else:
        w, ok, b_hi, b_lo = select_block(f, alpha, y, c, q, valid=valid,
                                         rule=selection)
    qx = jnp.take(x, w, axis=0)
    qsq = jnp.take(x_sq, w)
    if kp.kind == "precomputed":
        # x IS the Gram matrix: the (q, q) block is a column gather of
        # the already-gathered rows (same contract as _round_core).
        kb = jnp.take(qx.astype(jnp.float32), w, axis=1)
    else:
        dots = jnp.dot(qx.astype(x.dtype), qx.astype(x.dtype).T,
                       preferred_element_type=jnp.float32)
        kb = kernel_from_dots(dots, qsq, qsq, kp)
    kd = jnp.take(k_diag, w)
    return PipelinedCand(w, ok, b_hi.astype(jnp.float32),
                         b_lo.astype(jnp.float32), qx, qsq, kb, kd)


def select_block(f, alpha, y, c, q: int, valid=None, rule: str = "mvp"):
    """Pick the q most-violating points: q/2 from I_up (smallest f) and
    q/2 from I_low (largest f). Returns (w, slot_ok, b_hi, b_lo):

      w        (q,) int32 global indices (junk filler where a set ran short)
      slot_ok  (q,) bool — slot holds a real, unique candidate
      b_hi     f32 min f over I_up   (exact: _top_h retains each row's
      b_lo     f32 max f over I_low   true extremum even on the approx path)

    The extrema ride the SAME selection pass, so one call per round serves
    both the working set and the reference's stopping rule
    b_lo <= b_hi + 2 eps (svmTrainMain.cpp:310) — the round body needs no
    separate select_working_set sweep over n.

    A point in I_0 (0 < alpha < C) may appear in both halves; the
    duplicate low-half slot is masked out so each global index occupies at
    most one live slot (two live slots for one point would let the inner
    loop update the same alpha through two disagreeing copies).

    rule="nu" splits the block into per-class quarters instead (q/4 from
    each of I_up/I_low within each class): the nu duals carry one equality
    constraint per class, so the subproblem must be able to pair within
    BOTH classes (ops/select.py select_working_set_nu) — a W with only one
    class's violators could stall the other class's gap. Its (b_hi, b_lo)
    are the larger-violation class's pair, matching
    select_working_set_nu's stopping gap.
    """
    cp, cn = split_c(c)
    up = up_mask(alpha, y, cp, cn)
    low = low_mask(alpha, y, cp, cn)
    if valid is not None:
        up = up & valid
        low = low & valid
    if rule == "nu":
        pos = y > 0
        h = q // 4
        scores = jnp.stack([jnp.where(up & pos, -f, -jnp.inf),
                            jnp.where(low & pos, f, -jnp.inf),
                            jnp.where(up & ~pos, -f, -jnp.inf),
                            jnp.where(low & ~pos, f, -jnp.inf)])
        vals, idx = _top_h(scores, h)  # (4, h)
        # Dedup within a class only (the classes are disjoint).
        w_p, ok_p = combine_halves(idx[0], jnp.isfinite(vals[0]),
                                   idx[1], jnp.isfinite(vals[1]))
        w_n, ok_n = combine_halves(idx[2], jnp.isfinite(vals[2]),
                                   idx[3], jnp.isfinite(vals[3]))
        b_hi, b_lo = nu_stopping_pair(-jnp.max(vals[0]), jnp.max(vals[1]),
                                      -jnp.max(vals[2]), jnp.max(vals[3]))
        return (jnp.concatenate([w_p, w_n]),
                jnp.concatenate([ok_p, ok_n]),
                b_hi.astype(jnp.float32), b_lo.astype(jnp.float32))
    h = q // 2
    # One batched selection over both candidate sides.
    scores = jnp.stack([jnp.where(up, -f, -jnp.inf),
                        jnp.where(low, f, -jnp.inf)])
    vals, idx = _top_h(scores, h)  # (2, h)
    w, slot_ok = combine_halves(idx[0], jnp.isfinite(vals[0]),
                                idx[1], jnp.isfinite(vals[1]))
    # Empty-set semantics match select_working_set: all-(-inf) scores give
    # b_hi=+inf / b_lo=-inf, which reads as a closed gap.
    return w, slot_ok, -jnp.max(vals[0]), jnp.max(vals[1])


def _top_h(scores, h: int):
    """Top-h per row via the TPU-native approximate top-k.

    ``lax.top_k`` over a stacked (r, n) operand falls off XLA's fast path
    for h > ~128 (measured 6.7 ms at n=500k vs 0.77 ms for approx — see
    tools/profile_round.py). ``approx_max_k``'s bin-max construction
    ALWAYS retains each row's true maximum, so the convergence invariant
    (the globally most-violating pair is in W) and the b_hi/b_lo extrema
    are exact; the ~1-2% recall loss only swaps interchangeable mid-rank
    violators. Falls back to exact top_k on non-TPU backends where
    approx_max_k has no fast lowering anyway."""
    if jax.default_backend() == "tpu":
        return lax.approx_max_k(scores, h)
    return lax.top_k(scores, h)


def combine_halves(up_idx, up_ok, low_idx, low_ok):
    """Assemble (w, slot_ok) from the two candidate halves, masking low
    slots that duplicate a LIVE up slot. Only LIVE up slots can shadow a
    low candidate: when I_up runs short, top_k filler indices are
    arbitrary row ids and must not mask out real low-half violators (that
    could hide the global max violator and stall the outer loop with the
    gap open). Shared by the single-chip and mesh selectors."""
    dup = jnp.any((low_idx[:, None] == up_idx[None, :]) & up_ok[None, :],
                  axis=1)
    low_ok = low_ok & ~dup
    w = jnp.concatenate([up_idx, low_idx]).astype(jnp.int32)
    slot_ok = jnp.concatenate([up_ok, low_ok])
    return w, slot_ok


def _solve_subproblem(kb_w, kd_w, slot_ok, alpha_w, y_w, f_w, c,
                      eps: float, tau: float, limit, rule: str = "mvp",
                      pair_batch: int = 1):
    """Exact SMO on the q-variable subproblem. All state is q-sized.

    kb_w: (q, q) Gram block K(w_i, w_j); kd_w: (q,) its diagonal. `limit`
    is the pair-update budget for THIS block (dynamic: the per-round
    inner_iters cap already clamped to the remaining max_iter budget).
    Returns (alpha_w, f_w, n_pairs). The first iteration reproduces the
    reference's maximal-violating-pair step exactly (the global argmin /
    argmax live in W by construction).

    rule selects the pairing inside W:
      "mvp"          — maximal-violating pair (reference algorithm);
      "second_order" — i by max violation, j by max second-order gain
                       (f_j - b_hi)^2 / eta_ij over K(W, W)'s row i —
                       LibSVM's WSS2 at essentially zero extra cost
                       because the Gram block is already resident;
      "nu"           — per-class MVP (both pair members share a class;
                       the nu duals' two-equality-constraint rule).
    """
    if pair_batch > 1 and rule != "mvp":
        raise ValueError("pair_batch>1 is implemented for rule='mvp' only")
    cp, cn = split_c(c)

    def cond(carry):
        _, _, t, gap_open = carry
        return (t < limit) & gap_open

    def body(carry):
        alpha_w, f_w, t, _ = carry
        up = up_mask(alpha_w, y_w, cp, cn) & slot_ok
        low = low_mask(alpha_w, y_w, cp, cn) & slot_ok
        if rule == "nu":
            # The per-class pairing rule already exists as
            # select_working_set_nu; slot_ok plays the valid-mask role.
            i, b_hi_l, j, b_lo_l = select_working_set_nu(
                f_w, alpha_w, y_w, c, valid=slot_ok)
            gap_open = b_lo_l > b_hi_l + 2.0 * eps
            upd_ok = gap_open
            row_i = lax.dynamic_index_in_dim(kb_w, i, 0, keepdims=False)
        elif rule == "second_order":
            f_up = jnp.where(up, f_w, jnp.inf)
            f_low = jnp.where(low, f_w, -jnp.inf)
            i = jnp.argmin(f_up).astype(jnp.int32)
            b_hi_l = f_up[i]
            b_lo_max = jnp.max(f_low)  # convergence uses the max violator
            gap_open = b_lo_max > b_hi_l + 2.0 * eps
            row_i = lax.dynamic_index_in_dim(kb_w, i, 0, keepdims=False)
            diff = f_w - b_hi_l
            eta_j = jnp.maximum(kd_w[i] + kd_w - 2.0 * row_i, tau)
            gain = jnp.where(low & (diff > 0), diff * diff / eta_j,
                             -jnp.inf)
            # At the honest epsilon gap_open implies an eligible j exists
            # (some f_low > b_hi) — but budget_mode compiles eps=-1e30,
            # which keeps gap_open True after the eligible set empties;
            # without the has_j gate argmax over all-(-inf) gains would
            # pick slot 0 (possibly a dead filler slot) as the partner
            # and drift alpha off the dual equality constraint. gap_open
            # itself stays ungated: it drives the loop and the pair
            # counter, and a stalled counter would leave the budget-mode
            # outer loop spinning; the ineligible update is a counted
            # no-op instead.
            has_j = jnp.max(gain) > -jnp.inf
            upd_ok = gap_open & has_j
            j = jnp.where(upd_ok, jnp.argmax(gain), i).astype(jnp.int32)
            b_lo_l = f_w[j]
        else:
            f_up = jnp.where(up, f_w, jnp.inf)
            f_low = jnp.where(low, f_w, -jnp.inf)
            i = jnp.argmin(f_up).astype(jnp.int32)
            j = jnp.argmax(f_low).astype(jnp.int32)
            b_hi_l = f_up[i]
            b_lo_l = f_low[j]
            gap_open = b_lo_l > b_hi_l + 2.0 * eps
            upd_ok = gap_open
            row_i = lax.dynamic_index_in_dim(kb_w, i, 0, keepdims=False)

        row_j = lax.dynamic_index_in_dim(kb_w, j, 0, keepdims=False)
        eta = jnp.maximum(kd_w[i] + kd_w[j] - 2.0 * row_i[j], tau)
        y_i = y_w[i]
        y_j = y_w[j]
        a_i_old = alpha_w[i]
        a_j_old = alpha_w[j]
        a_i_new, a_j_new = pair_alpha_update(
            a_i_old, a_j_old, y_i, y_j, b_hi_l, b_lo_l, eta,
            c_of(y_i, cp, cn), c_of(y_j, cp, cn), gate=upd_ok)
        # One-hot writes instead of scatters: q-sized selects fuse into the
        # surrounding elementwise work.
        lanes = jnp.arange(alpha_w.shape[0], dtype=jnp.int32)
        alpha_w = jnp.where(lanes == i, a_i_new, alpha_w)
        alpha_w = jnp.where(lanes == j, a_j_new, alpha_w)
        f_w = f_w + (a_i_new - a_i_old) * y_i * row_i \
                  + (a_j_new - a_j_old) * y_j * row_j
        if pair_batch == 1:
            return alpha_w, f_w, t + jnp.int32(gap_open), gap_open

        # pair_batch >= 2 (mvp only): further coordinate-disjoint pairs
        # per trip — stale rank-s SELECTION, exact UPDATE on the current
        # state. Identical semantics to the Pallas kernel
        # (ops/pallas_subproblem.py): attempted slots count even when the
        # update gates to a no-op; the update gates on non-empty stale
        # sets (the empty-set argmin aliases slot 0 — a wrong update, not
        # a no-op) and on the corrected pair still violating.
        # Two DELIBERATE counting/tolerance quirks (ADVICE round-4),
        # kept because the round-4 artifacts' trajectories are pinned to
        # them: (1) `iterations` counts attempted slots, so pairs/s under
        # pair_batch>1 includes gated no-op slots and is not directly
        # comparable to pair_batch=1 runs (PROFILE.md documents this
        # wherever the two are compared); (2) the extra slots gate on
        # the MARGIN-FREE b_lo2 > b_hi2, so sub-tolerance slot updates
        # the eps-gated first slot would never take DO apply — still
        # exact descent, slightly different stopping-tolerance
        # semantics. The per-pair micro-batch executor (solver/smo.py
        # _run_chunk_micro) gates its extra slots on the full 2*eps
        # margin instead.
        excl = (lanes == i) | (lanes == j)
        f_up_s, f_low_s = f_up, f_low
        t_cur = t + jnp.int32(gap_open)
        for _s in range(pair_batch - 1):
            f_up_s = jnp.where(excl, jnp.inf, f_up_s)
            f_low_s = jnp.where(excl, -jnp.inf, f_low_s)
            i2 = jnp.argmin(f_up_s).astype(jnp.int32)
            j2 = jnp.argmax(f_low_s).astype(jnp.int32)
            bh2s = f_up_s[i2]
            bl2s = f_low_s[j2]
            row_i2 = lax.dynamic_index_in_dim(kb_w, i2, 0, keepdims=False)
            row_j2 = lax.dynamic_index_in_dim(kb_w, j2, 0, keepdims=False)
            b_hi2 = f_w[i2]  # corrected: current gradient
            b_lo2 = f_w[j2]
            y_i2 = y_w[i2]
            y_j2 = y_w[j2]
            eta2 = jnp.maximum(kd_w[i2] + kd_w[j2] - 2.0 * row_i2[j2], tau)
            cnt2 = gap_open & (t_cur < limit)
            upd2 = (cnt2 & (bh2s < jnp.inf) & (bl2s > -jnp.inf)
                    & (b_lo2 > b_hi2))
            a_i2_old = alpha_w[i2]
            a_j2_old = alpha_w[j2]
            a_i2_new, a_j2_new = pair_alpha_update(
                a_i2_old, a_j2_old, y_i2, y_j2, b_hi2, b_lo2, eta2,
                c_of(y_i2, cp, cn), c_of(y_j2, cp, cn), gate=upd2)
            alpha_w = jnp.where(lanes == i2, a_i2_new, alpha_w)
            alpha_w = jnp.where(lanes == j2, a_j2_new, alpha_w)
            f_w = f_w + (a_i2_new - a_i2_old) * y_i2 * row_i2 \
                      + (a_j2_new - a_j2_old) * y_j2 * row_j2
            t_cur = t_cur + jnp.int32(cnt2)
            excl = excl | (lanes == i2) | (lanes == j2)
        return alpha_w, f_w, t_cur, gap_open

    alpha_w, f_w, t, _ = lax.while_loop(
        cond, body, (alpha_w, f_w, jnp.int32(0), jnp.bool_(True)))
    return alpha_w, f_w, t


def dispatch_subproblem(kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c,
                        eps: float, tau: float, limit, inner_impl: str,
                        interpret: bool, selection: str,
                        pair_batch: int = 1):
    """The subproblem stage of a block round, factored so every round
    body — in-core (_round_core), pipelined (run_chunk_block_pipelined)
    and out-of-core (solver/ooc.py) — dispatches the identical inner
    engine from whatever (q, q) Gram block it assembled. All inputs and
    outputs are q-sized: this is the piece that makes the round body
    tile-composable (nothing in it knows where K(W, W) came from — a
    fresh matmul, a pipelined prefetch, or the ooc block cache).

    Returns (a_w, coef, t): the updated subproblem alphas, the fold
    coefficients (dalpha * y, dead slots zeroed), and the executed pair
    count."""
    if inner_impl == "pallas":
        from dpsvm_tpu.ops.pallas_subproblem import (
            solve_subproblem_pallas)

        a_w, t = solve_subproblem_pallas(
            kb_w, a_w0, y_w, f_w0, kd_w,
            slot_ok.astype(jnp.float32),
            limit, c, eps, tau, rule=selection, interpret=interpret,
            pair_batch=pair_batch)
    else:
        a_w, _, t = _solve_subproblem(
            kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau,
            limit, rule=selection, pair_batch=pair_batch)
    coef = jnp.where(slot_ok, (a_w - a_w0) * y_w, 0.0)  # (q,)
    return a_w, coef, t


def _round_core(x, y, x_sq, k_diag, f, alpha, valid, budget_left,
                kp: KernelParams, c, eps: float, tau: float,
                q: int, inner_iters: int, inner_impl: str,
                interpret: bool, selection: str, cand=None,
                pair_batch: int = 1):
    """The shared block-round step: ONE selection pass (whose top-k values
    also carry the stopping extrema of the CURRENT f), working-set
    gathers, the (q, q) Gram block, the subproblem dispatch, and the fold
    coefficients. `x`/`f`/`alpha` may be the full-n arrays
    (run_chunk_block) or the (m,)-sized active views
    (run_chunk_block_active) — the two engines differ only in what they
    fold `coef` into and how they scatter `a_w` back.

    The loop cond therefore sees extrema one fold behind; the final
    convergence round runs with `limit` gated to 0 (a selection + one
    inert fold), and budget exits are refreshed host-side
    (ops/select.py refresh_extrema_host).

    `cand`, when given, is a precomputed (w, slot_ok, b_hi, b_lo) and the
    selection pass is skipped entirely — the fused-fold path
    (run_chunk_block_fused) selects as part of the PREVIOUS round's fold.

    Returns (w, slot_ok, b_hi, b_lo, a_w, coef, t, qx, qsq)."""
    # jax.named_scope tags the ops of each stage with op_name METADATA
    # (visible in Perfetto/XPlane device traces as select/gather/gram/
    # subproblem/fold stage names) — metadata only: opcode structure,
    # shapes and counts are untouched, which is why the committed
    # tpulint budgets are byte-identical with the scopes in place (the
    # obs zero-HLO-effect contract, checked in CI with obs enabled).
    if cand is not None:
        w, slot_ok, b_hi, b_lo = cand
    else:
        with jax.named_scope("block_select"):
            w, slot_ok, b_hi, b_lo = select_block(f, alpha, y, c, q,
                                                  valid=valid,
                                                  rule=selection)
    gap_open = b_lo > b_hi + 2.0 * eps
    with jax.named_scope("block_gather"):
        qx = jnp.take(x, w, axis=0)  # (q, d)
        qsq = jnp.take(x_sq, w)
        kd_w = jnp.take(k_diag, w)
        a_w0 = jnp.take(alpha, w)
        y_w = jnp.take(y, w)
        f_w0 = jnp.take(f, w)
    with jax.named_scope("block_gram"):
        if kp.kind == "precomputed":
            # x IS the Gram matrix: the (q, q) block is a column gather
            # of the already-gathered rows (kernel_rows likewise
            # returns qx verbatim for the fold).
            kb_w = jnp.take(qx.astype(jnp.float32), w, axis=1)
        else:
            dots_w = jnp.dot(qx.astype(x.dtype), qx.astype(x.dtype).T,
                             preferred_element_type=jnp.float32)
            kb_w = kernel_from_dots(dots_w, qsq, qsq, kp)  # (q, q)
    # Per-round pair budget, clamped so total pairs never exceed the
    # caller's remaining budget (the per-pair engines cap exactly; so
    # must this one) and gated to 0 on the terminal round.
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    with jax.named_scope("block_subproblem"):
        a_w, coef, t = dispatch_subproblem(
            kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
            inner_impl, interpret, selection, pair_batch=pair_batch)
    return w, slot_ok, b_hi, b_lo, a_w, coef, t, qx, qsq


def run_local_round(x, y, x_sq, k_diag, valid, alpha, f, f_err,
                    budget_left, kp: KernelParams, c, eps: float,
                    tau: float, q: int, inner_iters: int, inner_impl: str,
                    interpret: bool, selection: str, pair_batch: int = 1):
    """ONE complete block round on whatever row view the caller holds:
    selection (extrema ride the pass), Gram, subproblem, the fold into
    THIS view's gradient, and the alpha scatter. Factored out of
    run_chunk_block's body so the single-chip engine and the mesh
    SHARD-LOCAL engine (parallel/dist_block.py
    make_block_shardlocal_chunk_runner) execute the identical round
    body — the shard-local engine runs this verbatim on its (n_loc,)
    shard views, which is what makes its local rounds bit-identical to
    single-chip rounds over the same rows.

    Returns (alpha, f, f_err, b_hi, b_lo, t, coef, qx, qsq): the
    updated row state, the selection-pass extrema of the gradient this
    round SAW (one fold behind, as every block engine's carry), the
    executed pair count, and the fold's (coef, rows, norms) so a caller
    can REPLAY the fold against other row sets — the shard-local sync's
    cross-shard reconciliation."""
    f_cur = f if f_err is None else f - f_err  # eff_f on loose fields
    w, slot_ok, b_hi, b_lo, alpha_w, coef, t, qx, qsq = _round_core(
        x, y, x_sq, k_diag, f_cur, alpha, valid, budget_left,
        kp, c, eps, tau, q, inner_iters, inner_impl, interpret,
        selection, pair_batch=pair_batch)
    # Fold the round's alpha deltas into the global state with one
    # fused matmul chain over X (the single O(n d q) pass per round):
    # f += (dalpha * y)_W @ K(W, :), with K(W, :) from the same
    # kernel_rows machinery every other engine uses.
    with jax.named_scope("block_fold"):
        k_rows = kernel_rows(x, x_sq, qx, qsq, kp)  # (q, n) fp32
        f, f_err = maybe_kahan(f, f_err, coef @ k_rows)
        # Dead slots must not scatter. The inert index must be OUT OF
        # RANGE (n), not -1: mode="drop" only drops beyond-range
        # indices, while -1 wraps to the LAST row and would erase its
        # alpha.
        safe_w = jnp.where(slot_ok, w, jnp.int32(alpha.shape[0]))
        alpha = alpha.at[safe_w].set(
            jnp.where(slot_ok, alpha_w, 0.0), mode="drop")
    return alpha, f, f_err, b_hi, b_lo, t, coef, qx, qsq


_CHUNK_STATICS = ("kp", "c", "eps", "tau", "q", "inner_iters",
                  "rounds_per_chunk", "inner_impl", "interpret",
                  "selection", "pair_batch")


def _run_chunk_block(x, y, x_sq, k_diag, valid, state: BlockState, max_iter,
                     kp: KernelParams, c, eps: float, tau: float,
                     q: int, inner_iters: int, rounds_per_chunk: int,
                     inner_impl: str = "xla",
                     interpret: bool = False,
                     selection: str = "mvp",
                     pair_batch: int = 1) -> BlockState:
    """Run up to `rounds_per_chunk` outer rounds fully on device.

    inner_impl: "xla" runs the subproblem as a lax.while_loop of XLA ops
    (portable); "pallas" runs it as one on-core kernel
    (ops/pallas_subproblem.py) — same algebra, far lower per-pair dispatch
    cost on real TPUs.

    selection: "mvp" | "second_order" | "nu" — the subproblem pairing rule
    (see _solve_subproblem). "nu" also switches the outer block selection
    to per-class quarters and the convergence gap to the per-class rule."""
    end = state.rounds + rounds_per_chunk

    def cond(st: BlockState):
        return ((st.rounds < end) & (st.pairs < max_iter)
                & (st.b_lo > st.b_hi + 2.0 * eps))

    def body(st: BlockState):
        # The whole round body lives in run_local_round (shared with the
        # mesh shard-local engine's local rounds — one definition).
        alpha, f, f_err, b_hi, b_lo, t, _, _, _ = run_local_round(
            x, y, x_sq, k_diag, valid, st.alpha, st.f, st.f_err,
            max_iter - st.pairs, kp, c, eps, tau, q, inner_iters,
            inner_impl, interpret, selection, pair_batch=pair_batch)
        return BlockState(alpha, f, b_hi, b_lo, st.pairs + t, st.rounds + 1,
                          f_err)

    return lax.while_loop(cond, body, state)


run_chunk_block = partial(jax.jit,
                          static_argnames=_CHUNK_STATICS)(_run_chunk_block)
# The solve driver's variant: the carried BlockState is DONATED (the
# host loop rebinds `state = run_chunk(...)` and never touches the old
# one), freeing the 2x (n,) f32 input carry from the live set each
# dispatch. A separate name — not donate-by-default — because external
# probes legitimately re-dispatch one warmed state (tools/
# profile_round.py's salted A/B probes); donation works on both the CPU
# and TPU runtimes of this jax (the tpulint donation fact pins it).
run_chunk_block_donated = partial(
    jax.jit, donate_argnums=(5,),
    static_argnames=_CHUNK_STATICS)(_run_chunk_block)


def _run_chunk_block_fused(x, y, x_sq, k_diag, valid, state: BlockState,
                           max_iter, kp: KernelParams, c, eps: float,
                           tau: float, q: int, inner_iters: int,
                           rounds_per_chunk: int,
                           inner_impl: str = "pallas",
                           interpret: bool = False,
                           selection: str = "mvp",
                           pair_batch: int = 1) -> BlockState:
    """Fused-fold variant of run_chunk_block: the round's fold and the
    NEXT round's selection run as ONE Pallas pass over f
    (ops/pallas_fold_select.py), eliminating the separate full-n
    mask-building + approx_max_k stage from the latency-bound serial
    round chain (PROFILE.md reading 4).

    The working set rides the loop carry as per-fold candidates; one
    plain select_block seeds it per chunk (amortized over
    rounds_per_chunk rounds). Because each round's stopping extrema are
    computed from the POST-fold gradient, the carried (b_hi, b_lo) are
    exact rather than one fold behind.

    Requires: n padded to a multiple of 1024 with `valid` marking real
    rows (solver/smo.py pads); selection in {"mvp", "second_order"} (the
    nu rule's per-class quarters use the plain path); q/2 <= n_pad/128
    (one candidate per 128-row per side).
    """
    n_pad = y.shape[0]
    rows = n_pad // 128
    shp = (rows, 128)
    h = q // 2
    y2d = y.reshape(shp)
    valid2d = valid.astype(jnp.float32).reshape(shp)
    end = state.rounds + rounds_per_chunk
    compensated = state.f_err is not None

    from dpsvm_tpu.ops.pallas_fold_select import (assemble_working_set,
                                                  fold_select)

    w0, ok0, bhi0, blo0 = select_block(eff_f(state), state.alpha, y, c, q,
                                       valid=valid, rule=selection)
    st0 = state._replace(b_hi=bhi0, b_lo=blo0)

    def cond(carry):
        st, w, ok = carry
        return ((st.rounds < end) & (st.pairs < max_iter)
                & (st.b_lo > st.b_hi + 2.0 * eps))

    def body(carry):
        st, w, slot_ok = carry
        _, _, b_hi, b_lo, alpha_w, coef, t, qx, qsq = _round_core(
            x, y, x_sq, k_diag, eff_f(st), st.alpha, valid,
            max_iter - st.pairs, kp, c, eps, tau, q, inner_iters,
            inner_impl, interpret, selection,
            cand=(w, slot_ok, st.b_hi, st.b_lo), pair_batch=pair_batch)
        k_rows = kernel_rows(x, x_sq, qx, qsq, kp)  # (q, n_pad) fp32
        delta2d = (coef @ k_rows).reshape(shp)
        # Scatter alpha BEFORE the fused pass: its selection masks must
        # see the updated box membership (same contract as
        # ops/pallas_fused.py).
        safe_w = jnp.where(slot_ok, w, jnp.int32(n_pad))
        alpha = st.alpha.at[safe_w].set(
            jnp.where(slot_ok, alpha_w, 0.0), mode="drop")
        err2d = st.f_err.reshape(shp) if compensated else None
        f2d, err_new2d, upv, upi, lov, loi = fold_select(
            st.f.reshape(shp), err2d, alpha.reshape(shp), y2d, valid2d,
            delta2d, c, compensated=compensated, interpret=interpret)
        w_n, ok_n, b_hi_n, b_lo_n = assemble_working_set(upv, upi, lov,
                                                         loi, h)
        new_st = BlockState(
            alpha, f2d.reshape(n_pad), b_hi_n, b_lo_n, st.pairs + t,
            st.rounds + 1,
            err_new2d.reshape(n_pad) if compensated else None)
        return new_st, w_n, ok_n

    final, _, _ = lax.while_loop(cond, body, (st0, w0, ok0))
    return final


# Donated/undonated pair (the run_chunk_block pattern, PR 5 / ISSUE 12
# satellite): the solve driver dispatches the DONATED variant (the host
# loop rebinds `state = run_chunk(...)` and never touches the old one),
# freeing the carried (n,) alpha/f buffers from the live set each
# dispatch; the undonated name remains for probes that legitimately
# re-dispatch a warmed state (tools/profile_round.py's salted A/Bs).
run_chunk_block_fused = partial(
    jax.jit, static_argnames=_CHUNK_STATICS)(_run_chunk_block_fused)
run_chunk_block_fused_donated = partial(
    jax.jit, donate_argnums=(5,),
    static_argnames=_CHUNK_STATICS)(_run_chunk_block_fused)


def _run_chunk_block_pipelined(x, y, x_sq, k_diag, valid,
                               state: BlockState, max_iter,
                               kp: KernelParams, c, eps: float, tau: float,
                               q: int, inner_iters: int,
                               rounds_per_chunk: int,
                               inner_impl: str = "xla",
                               interpret: bool = False,
                               selection: str = "mvp",
                               pair_batch: int = 1,
                               pallas_select: bool = False) -> BlockState:
    """PIPELINED round engine (config.pipeline_rounds): hide the fixed
    selection/launch floor behind the serial subproblem chain.

    The plain round body is a strict dependency chain
    select -> gather -> Gram -> subproblem -> fold -> scatter, so its
    fixed O(n) stages (PROFILE.md: 0.20-0.74 ms/round) serialize with
    the ~0.5 us/pair chain — the two terms SCALING.md's model carries as
    the un-shrinkable Amdahl floor. This body software-pipelines the
    rounds instead: the working set for round t+1 is selected — and its
    rows, norms and (q, q) Gram block built — from round t's PRE-fold
    carry, so that whole stage has NO data dependence on round t's
    subproblem and the XLA scheduler may overlap the two; only the fold
    contraction and the scatter still trail the chain.

    Staleness contract (the pair_batch precedent, docs/ARCHITECTURE.md):
    SELECTION may be stale — round t+1's W ranks violators by the
    gradient as it stood before round t's fold — but every EXECUTED
    update is exact against the then-current gradient: the handoff
    gathers each slot's CURRENT alpha/f, re-derives admissibility from
    the current alpha (ops/select.py candidate_live_mask — saturated
    candidates are masked, never recomputed), and the subproblem's own
    per-iteration masks and eps gates do the rest. Keerthi et al.'s
    convergence argument needs exactly this much; Fan et al.'s WSS2
    likewise tolerates stale candidate RANKING.

    No-stall property: a round whose stale W absorbs zero pairs folds a
    zero delta, so the NEXT prefetch reads the unchanged — i.e. exact —
    gradient and recovers the true maximal violating pair; stale
    selection can therefore waste at most one round, never cycle. The
    same argument makes the convergence exit exact: the loop only exits
    on extrema selected from a gradient the exiting round did not change
    (a globally closed gap closes every subproblem gate), and budget
    exits are refreshed host-side (ops/select.py refresh_extrema_host)
    exactly as for the other block engines.

    pallas_select routes the prefetch selection through the one-pass
    Pallas candidate kernel (pre-fold variant of the fused engine's
    fold_select; needs that path's padding contract — the caller gates).
    selection in {"mvp", "second_order"}; the nu rule's per-class
    quarters keep the plain engine (same restriction as the fused path).
    """
    n = y.shape[0]
    end = state.rounds + rounds_per_chunk

    def prefetch(f, alpha):
        return prefetch_working_set(x, y, x_sq, k_diag, f, alpha, valid,
                                    kp, c, q, selection,
                                    pallas_select=pallas_select,
                                    interpret=interpret)

    # Seed from the chunk's entry state (exact, amortized over
    # rounds_per_chunk rounds — the run_chunk_block_fused pattern).
    cand0 = prefetch(eff_f(state), state.alpha)
    st0 = state._replace(b_hi=cand0.b_hi, b_lo=cand0.b_lo)

    def cond(carry):
        st, _ = carry
        return ((st.rounds < end) & (st.pairs < max_iter)
                & (st.b_lo > st.b_hi + 2.0 * eps))

    def body(carry):
        st, cand = carry
        f_cur = eff_f(st)
        # ---- handoff: gather CURRENT per-slot state for the staged W
        # and gate slots the previous round invalidated.
        a_w0 = jnp.take(st.alpha, cand.w)
        y_w = jnp.take(y, cand.w)
        f_w0 = jnp.take(f_cur, cand.w)
        slot_ok = cand.ok & candidate_live_mask(a_w0, y_w, c)
        # No gap gate on `limit` here: cond() already guarantees the
        # carried gap is open on every body entry (the plain engine
        # gates because ITS extrema come from a fresh mid-body
        # selection; this body's extrema ARE the carry).
        limit = jnp.minimum(jnp.int32(inner_iters), max_iter - st.pairs)
        a_w, coef, t = dispatch_subproblem(
            cand.kb, cand.kd, slot_ok, a_w0, y_w, f_w0, c, eps, tau,
            limit, inner_impl, interpret, selection,
            pair_batch=pair_batch)
        # ---- next round's prefetch, from the PRE-fold carry: depends
        # only on (f_cur, st.alpha), never on the subproblem above —
        # the overlap the whole engine exists for.
        nxt = prefetch(f_cur, st.alpha)
        # ---- fold + scatter: the only stages that consume the chain.
        k_rows = kernel_rows(x, x_sq, cand.qx, cand.qsq, kp)
        f, f_err = maybe_kahan(st.f, st.f_err, coef @ k_rows)
        safe_w = jnp.where(slot_ok, cand.w, jnp.int32(n))
        alpha = st.alpha.at[safe_w].set(
            jnp.where(slot_ok, a_w, 0.0), mode="drop")
        new_st = BlockState(alpha, f, nxt.b_hi, nxt.b_lo, st.pairs + t,
                            st.rounds + 1, f_err)
        return new_st, nxt

    final, _ = lax.while_loop(cond, body, (st0, cand0))
    return final


_PIPE_STATICS = _CHUNK_STATICS + ("pallas_select",)
run_chunk_block_pipelined = partial(
    jax.jit, static_argnames=_PIPE_STATICS)(_run_chunk_block_pipelined)
run_chunk_block_pipelined_donated = partial(
    jax.jit, donate_argnums=(5,),
    static_argnames=_PIPE_STATICS)(_run_chunk_block_pipelined)


def _run_chunk_block_active(x, y, x_sq, k_diag, valid, state: BlockState,
                            max_iter,
                            kp: KernelParams, c, eps: float, tau: float,
                            q: int, inner_iters: int, rounds_per_chunk: int,
                            m: int, k_rounds: int,
                            inner_impl: str = "xla",
                            interpret: bool = False,
                            selection: str = "mvp",
                            pair_batch: int = 1) -> BlockState:
    """Active-set ("shrinking") variant of run_chunk_block.

    LibSVM shrinks by dropping bound-saturated rows from its scans and
    reconstructing the gradient when the shrunken problem converges
    (svm.cpp Solver::do_shrinking) — a dynamic-size strategy XLA can't
    compile. This is the same idea re-derived for static shapes. One
    CYCLE:

      1. active selection: A = the m most-violating rows (select_block
         with q=m — top m/2 of I_up and of I_low), which also yields the
         EXACT global stopping extrema of the current f;
      2. up to `k_rounds` ordinary block rounds whose selection, Gram
         gathers and fold all run on (m,)-sized state only — the per
         round full-n fold becomes a (q, d) x (d, m) pass;
      3. one batched reconciliation fold applies every round's
         accumulated (W, coef) deltas to the full gradient with a single
         (k_rounds*q, d) x (d, n) matmul chain, then the active slots are
         scattered back.

    Exactness: f updates are linear in the per-round coefs, so deferring
    the non-active rows' fold to step 3 changes floating-point grouping
    only, never the math; convergence is only ever declared from step 1's
    full-f extrema (the A-restricted gap merely ends a cycle early).
    Why it's faster: the full-X HBM stream — the block engine's dominant
    cost — happens once per cycle instead of once per round (same FLOPs,
    ~k_rounds x less X traffic, and a (k_rounds*q)-row matmul tiles the
    MXU better than q-row passes).

    Requires q <= m <= n. `rounds_per_chunk` is checked at cycle
    granularity, so a chunk can overshoot by up to k_rounds-1 rounds.
    """
    n = y.shape[0]
    end = state.rounds + rounds_per_chunk

    def cond(st: BlockState):
        return ((st.rounds < end) & (st.pairs < max_iter)
                & (st.b_lo > st.b_hi + 2.0 * eps))

    def cycle(st: BlockState):
        f_cur = eff_f(st)
        act_ids, act_ok, b_hi, b_lo = select_block(
            f_cur, st.alpha, y, c, m, valid=valid, rule=selection)
        gap_open = b_lo > b_hi + 2.0 * eps
        x_act = jnp.take(x, act_ids, axis=0)  # (m, d)
        sq_act = jnp.take(x_sq, act_ids)
        kd_act = jnp.take(k_diag, act_ids)
        y_act = jnp.take(y, act_ids)
        a_act0 = jnp.take(st.alpha, act_ids)
        f_act0 = jnp.take(f_cur, act_ids)
        pend_w0 = jnp.zeros((k_rounds, q), jnp.int32)
        pend_c0 = jnp.zeros((k_rounds, q), jnp.float32)

        def inner_cond(carry):
            _, _, _, _, k, t_tot, open_a = carry
            return ((k < k_rounds) & open_a
                    & (st.pairs + t_tot < max_iter))

        def inner_body(carry):
            a_act, f_act, pend_w, pend_c, k, t_tot, _ = carry
            # The shared round step, restricted to the active views
            # (valid=act_ok keeps dead filler slots out of every mask).
            w, slot_ok, bh_a, bl_a, a_w, coef, t, qx, qsq = _round_core(
                x_act, y_act, sq_act, kd_act, f_act, a_act, act_ok,
                max_iter - st.pairs - t_tot,
                kp, c, eps, tau, q, inner_iters, inner_impl, interpret,
                selection, pair_batch=pair_batch)
            open_a = bl_a > bh_a + 2.0 * eps
            k_rows_act = kernel_rows(x_act, sq_act, qx, qsq, kp)  # (q, m)
            f_act = f_act + coef @ k_rows_act
            safe_w = jnp.where(slot_ok, w, jnp.int32(m))
            a_act = a_act.at[safe_w].set(
                jnp.where(slot_ok, a_w, 0.0), mode="drop")
            # Record this round's deltas for the reconciliation fold
            # (dead slots carry coef 0 and contribute nothing).
            pend_w = pend_w.at[k].set(jnp.take(act_ids, w))
            pend_c = pend_c.at[k].set(coef)
            return a_act, f_act, pend_w, pend_c, k + 1, t_tot + t, open_a

        a_act, f_act, pend_w, pend_c, k_done, t_tot, _ = lax.while_loop(
            inner_cond, inner_body,
            (a_act0, f_act0, pend_w0, pend_c0, jnp.int32(0), jnp.int32(0),
             gap_open))

        # Reconciliation: one batched fold applies the cycle's deltas to
        # the FULL gradient (skipped entirely on the terminal all-zero
        # cycle). XLA fuses the kernel evaluation into the contraction
        # exactly as in run_chunk_block's per-round fold.
        def do_fold(carry):
            f, err = carry
            wf = pend_w.reshape(-1)
            cf = pend_c.reshape(-1)
            xw = jnp.take(x, wf, axis=0)  # (k_rounds*q, d)
            sqw = jnp.take(x_sq, wf)
            delta = cf @ kernel_rows(x, x_sq, xw, sqw, kp)
            return maybe_kahan(f, err, delta)

        f, f_err = lax.cond(t_tot > 0, do_fold, lambda c: c,
                            (st.f, st.f_err))
        # Active slots hold the incrementally-maintained values the inner
        # selections actually saw — scatter them over the fold's
        # (numerically regrouped) results so the two views agree exactly.
        # Only LIVE slots scatter (a dead duplicate slot holds stale
        # copies of a live row's state).
        safe_ids = jnp.where(act_ok, act_ids, jnp.int32(n))
        f = f.at[safe_ids].set(jnp.where(act_ok, f_act, 0.0), mode="drop")
        if f_err is not None:
            # The scattered entries are reset to the incrementally-
            # maintained values directly; their Kahan residual no longer
            # describes them.
            f_err = f_err.at[safe_ids].set(0.0, mode="drop")
        alpha = st.alpha.at[safe_ids].set(
            jnp.where(act_ok, a_act, 0.0), mode="drop")
        return BlockState(alpha, f, b_hi, b_lo,
                          st.pairs + t_tot, st.rounds + k_done, f_err)

    return lax.while_loop(cond, cycle, state)


_ACTIVE_STATICS = _CHUNK_STATICS + ("m", "k_rounds")
run_chunk_block_active = partial(
    jax.jit, static_argnames=_ACTIVE_STATICS)(_run_chunk_block_active)
run_chunk_block_active_donated = partial(
    jax.jit, donate_argnums=(5,),
    static_argnames=_ACTIVE_STATICS)(_run_chunk_block_active)


def _run_chunk_block_fusedround(x, y, x_sq, k_diag, valid,
                                state: BlockState, max_iter,
                                kp: KernelParams, c, eps: float,
                                tau: float, q: int, inner_iters: int,
                                rounds_per_chunk: int,
                                inner_impl: str = "pallas",
                                interpret: bool = False,
                                selection: str = "mvp",
                                pair_batch: int = 1) -> BlockState:
    """ONE-HBM-PASS fused round engine (config.fused_round;
    ops/pallas_round.py — ISSUE 12): run_chunk_block_fused with the
    remaining stock-XLA round stages fused into two Pallas passes, so
    one round touches X exactly once (the gather rides the kernel-row
    pass as in-kernel row DMAs, the Gram block rides grid step 0) and
    the O(n) vectors exactly once (the fold contraction runs
    in-register inside the fold+select pass).

    Loop structure, candidate carry, seeding, budget gating and
    stopping are run_chunk_block_fused's VERBATIM — each replaced stage
    is bitwise-exact (ops/pallas_round.py module docstring), so the
    trajectory is pinned bitwise equal to the stock fused engine
    (tests/test_fused_round.py). Same padding contract: n padded to a
    multiple of 1024 with `valid` marking real rows, selection in
    {"mvp", "second_order"}, q/2 <= n_pad/128, feature kernels only.
    """
    from dpsvm_tpu.ops.pallas_round import fused_round

    n_pad = y.shape[0]
    shp = (n_pad // 128, 128)
    y2d = y.reshape(shp)
    valid2d = valid.astype(jnp.float32).reshape(shp)
    end = state.rounds + rounds_per_chunk

    w0, ok0, bhi0, blo0 = select_block(eff_f(state), state.alpha, y, c, q,
                                       valid=valid, rule=selection)
    st0 = state._replace(b_hi=bhi0, b_lo=blo0)

    def cond(carry):
        st, w, ok = carry
        return ((st.rounds < end) & (st.pairs < max_iter)
                & (st.b_lo > st.b_hi + 2.0 * eps))

    def body(carry):
        st, w, slot_ok = carry
        alpha, f, f_err, b_hi_n, b_lo_n, w_n, ok_n, t = fused_round(
            x, y, x_sq, k_diag, y2d, valid2d, st.alpha, st.f, st.f_err,
            w, slot_ok, st.b_hi, st.b_lo, max_iter - st.pairs, kp, c,
            eps, tau, q, inner_iters, inner_impl, interpret, selection,
            pair_batch=pair_batch)
        new_st = BlockState(alpha, f, b_hi_n, b_lo_n, st.pairs + t,
                            st.rounds + 1, f_err)
        return new_st, w_n, ok_n

    final, _, _ = lax.while_loop(cond, body, (st0, w0, ok0))
    return final


run_chunk_block_fusedround = partial(
    jax.jit, static_argnames=_CHUNK_STATICS)(_run_chunk_block_fusedround)
run_chunk_block_fusedround_donated = partial(
    jax.jit, donate_argnums=(5,),
    static_argnames=_CHUNK_STATICS)(_run_chunk_block_fusedround)
