"""Pure-NumPy sequential modified-SMO — the correctness oracle.

Plays the role seq.cpp plays in the reference: a transparent, host-only
implementation of the exact algorithm (Keerthi et al. "modification 2",
global most-violating pair), used by the tests as ground truth for the
jitted engines. Algebra matches seq.cpp:195-260 step for step; the known
reference bugs are fixed (eta clamp — B2; float index transport — B4 is
moot here).

Also provides ``duality_gap`` — the reference ships an unused
``get_duality_gap`` (seq.cpp:352-376); here it is revived as a test
invariant (SURVEY.md section 4).
"""

from __future__ import annotations

import time

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.solver.result import SolveResult


def _kernel_row_np(x: np.ndarray, x_sq: np.ndarray, i: int, p: KernelParams) -> np.ndarray:
    dots = x @ x[i]
    if p.kind == "linear":
        return dots.astype(np.float32)
    if p.kind == "rbf":
        sq = np.maximum(x_sq + x_sq[i] - 2.0 * dots, 0.0)
        return np.exp(-p.gamma * sq).astype(np.float32)
    if p.kind == "poly":
        return ((p.gamma * dots + p.coef0) ** p.degree).astype(np.float32)
    if p.kind == "sigmoid":
        return np.tanh(p.gamma * dots + p.coef0).astype(np.float32)
    raise ValueError(p.kind)


def smo_reference(
    x: np.ndarray,
    y: np.ndarray,
    config: SVMConfig,
    full_gram_limit: int = 6000,
) -> SolveResult:
    """Train binary C-SVC by sequential modified SMO (NumPy, CPU).

    For n <= full_gram_limit the Gram matrix is precomputed (fast oracle
    path for tests); above that, kernel rows are evaluated on demand like
    seq.cpp's update_f (seq.cpp:378-386).
    """
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n = x.shape[0]
    gamma = config.resolve_gamma(x.shape[1])
    p = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    eps = np.float32(config.epsilon)
    c_pos, c_neg = config.c_bounds()
    cp = np.float32(c_pos)
    cn = np.float32(c_neg)
    c_arr = np.where(y > 0, cp, cn).astype(np.float32)

    x_sq = np.einsum("nd,nd->n", x, x).astype(np.float32)
    gram = None
    if n <= full_gram_limit:
        dots = (x @ x.T).astype(np.float32)
        if p.kind == "linear":
            gram = dots
        elif p.kind == "rbf":
            sq = np.maximum(x_sq[:, None] + x_sq[None, :] - 2.0 * dots, 0.0)
            gram = np.exp(-p.gamma * sq).astype(np.float32)
        elif p.kind == "poly":
            gram = ((p.gamma * dots + p.coef0) ** p.degree).astype(np.float32)
        elif p.kind == "sigmoid":
            gram = np.tanh(p.gamma * dots + p.coef0).astype(np.float32)

    def row(i: int) -> np.ndarray:
        if gram is not None:
            return gram[i]
        return _kernel_row_np(x, x_sq, i, p)

    alpha = np.zeros(n, np.float32)
    f = (-y).astype(np.float32)  # f_i = -y_i at alpha = 0 (seq.cpp:463-467)

    yp = y > 0
    t0 = time.perf_counter()
    it = 0
    b_hi = np.float32(0.0)
    b_lo = np.float32(0.0)
    empty_iset = False
    while it < config.max_iter:
        up = np.where(yp, alpha < c_arr, alpha > 0)
        low = np.where(yp, alpha > 0, alpha < c_arr)
        if not up.any() or not low.any():
            # Degenerate I-set (single-class data, extreme class-weight/C
            # corners): no feasible ascent pair exists, so the current
            # iterate is optimal. Without this guard the argmin below
            # reads a finite junk f value through the all-inf mask and
            # can mis-decide convergence. Mirrors the native twin's
            # `if (i_hi < 0 || i_lo < 0) break` (native/seqsmo.cpp).
            empty_iset = True
            break
        f_up = np.where(up, f, np.inf)
        f_low = np.where(low, f, -np.inf)
        i_hi = int(np.argmin(f_up))
        i_lo = int(np.argmax(f_low))
        b_hi = f[i_hi]
        b_lo = f[i_lo]

        k_hi = row(i_hi)
        k_lo = row(i_lo)
        eta = k_hi[i_hi] + k_lo[i_lo] - 2.0 * k_hi[i_lo]
        eta = max(float(eta), config.tau)  # B2 fix (LibSVM-style clamp)

        y_hi = np.float32(y[i_hi])
        y_lo = np.float32(y[i_lo])
        a_hi_old = alpha[i_hi]
        a_lo_old = alpha[i_lo]
        # Pair update with the joint [L, H] clip (the reference's sequential
        # double clip at seq.cpp:237-250 can violate sum alpha_i y_i — see
        # solver/smo.py pair_alpha_update). c_hi/c_lo are the per-variable
        # box bounds (class-weighted C).
        c_hi = c_arr[i_hi]
        c_lo = c_arr[i_lo]
        s = y_hi * y_lo
        w = a_hi_old + s * a_lo_old
        if s > 0:
            lo_b, hi_b = max(np.float32(0.0), w - c_hi), min(c_lo, w)
        else:
            lo_b, hi_b = max(np.float32(0.0), -w), min(c_lo, c_hi - w)
        a_lo_new = np.float32(np.clip(a_lo_old + y_lo * (b_hi - b_lo) / eta, lo_b, hi_b))
        # Bound snap (see solver/smo.py pair_alpha_update: avoids the
        # c - 1ulp livelock); a_lo snaps BEFORE a_hi is derived from it so
        # conservation survives the snap.
        snap_lo = np.float32(1e-6) * c_lo
        snap_hi = np.float32(1e-6) * c_hi
        if a_lo_new < snap_lo:
            a_lo_new = np.float32(0.0)
        elif a_lo_new > c_lo - snap_lo:
            a_lo_new = c_lo
        a_hi_new = np.float32(np.clip(a_hi_old + s * (a_lo_old - a_lo_new), 0.0, c_hi))
        if a_hi_new < snap_hi:
            a_hi_new = np.float32(0.0)
        elif a_hi_new > c_hi - snap_hi:
            a_hi_new = c_hi
        alpha[i_lo] = a_lo_new
        alpha[i_hi] = a_hi_new

        f += (a_hi_new - a_hi_old) * y_hi * k_hi + (a_lo_new - a_lo_old) * y_lo * k_lo
        it += 1
        # do-while: test AFTER the update, like seq.cpp:260.
        if not (b_lo > b_hi + 2.0 * eps):
            break

    # On the empty-I-set break b_hi/b_lo are the PREVIOUS iteration's
    # (pre-update) envelope, whose gap may still read open — but the break
    # itself certifies optimality (the true gap is -inf).
    converged = empty_iset or not (b_lo > b_hi + 2.0 * eps)
    return SolveResult(
        alpha=alpha,
        b=float((b_lo + b_hi) / 2.0),
        b_hi=float(b_hi),
        b_lo=float(b_lo),
        iterations=it,
        converged=converged,
        train_seconds=time.perf_counter() - t0,
        stats={"f": f},
    )


def smo_native(x: np.ndarray, y: np.ndarray, config: SVMConfig) -> SolveResult:
    """Train with the native C++ sequential engine (native/seqsmo.cpp) —
    the compiled counterpart of ``smo_reference`` (the reference's seq.cpp
    role as an actual native binary). Raises RuntimeError if the native
    toolchain is unavailable; callers wanting a guaranteed path should use
    ``smo_reference``."""
    from dpsvm_tpu.utils.native import get_seqsmo

    eng = get_seqsmo()
    if eng is None:
        raise RuntimeError(
            "native seqsmo engine unavailable (g++ missing or build failed); "
            "use backend='reference' for the NumPy oracle")
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    gamma = config.resolve_gamma(x.shape[1])
    t0 = time.perf_counter()
    c_pos, c_neg = config.c_bounds()
    alpha, f, b, b_hi, b_lo, it, converged = eng.train(
        x, y, c=c_pos, c_neg=c_neg, gamma=gamma, epsilon=config.epsilon,
        tau=max(config.tau, 1e-20), max_iter=config.max_iter,
        kernel=config.kernel, degree=config.degree, coef0=config.coef0)
    return SolveResult(
        alpha=alpha, b=b, b_hi=b_hi, b_lo=b_lo, iterations=it,
        converged=converged, train_seconds=time.perf_counter() - t0,
        stats={"f": f, "engine": "native-seqsmo"},
    )


def duality_gap(alpha, y, f, c, b) -> float:
    """Duality gap invariant (revived from dead code at seq.cpp:352-376).

    gap = sum_i alpha_i y_i f_i + sum_i C * max(0, y_i (b - f_i y_i) ...)
    following the reference's formulation; approaches ~0 at convergence.
    """
    alpha = np.asarray(alpha, np.float64)
    y = np.asarray(y, np.float64)
    f = np.asarray(f, np.float64)
    slack = np.where(y > 0, np.maximum(0.0, b - f), np.maximum(0.0, f - b))
    return float(np.sum(alpha * y * f) + c * np.sum(slack))
