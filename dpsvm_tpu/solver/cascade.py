"""Cascade warm-start training: block solves -> SV merge -> seeded global.

The continuous-learning increment shape is "previous generation's support
vectors + fresh rows".  Solving that from scratch re-pays every pair the
previous generation already converged; solving it as ONE warm-started
global problem helps, but the first-order structure of the cascade SVM
(Graf et al.) buys more: partition the increment into blocks, solve each
block warm-started from the rows of the seed that landed in it, keep only
the survivors (alpha > 0), and run the final global solve seeded from the
merged survivor set.  Non-SV rows are filtered by cheap small solves
before the expensive global pass ever sees them.

Partitioning is a deterministic stride (``idx[i::k]``): the seed rows and
both classes spread evenly across blocks, block sizes differ by at most
one row (at most two compiled shapes), and the layout is reproducible
without an RNG.

Feasibility across the merge is structural: each block solve satisfies
its own equality constraint sum(alpha_i * y_i) = 0, so the union of block
solutions satisfies the global constraint up to f64 summation — the
repair stage in :mod:`dpsvm_tpu.solver.warmstart` (which every warm solve
runs anyway) absorbs the rounding dust.

``cascade_solve`` returns ``(SolveResult, stats)`` where the result is a
plain global SolveResult over the full (x, y) — indistinguishable
downstream from a cold ``solve()`` — and stats carries the per-block and
total pair counts the bench harness A/Bs against cold training.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpsvm_tpu.solver.warmstart import WarmStart

__all__ = ["cascade_partition", "cascade_solve"]


def cascade_partition(n: int, block_rows: int) -> list:
    """Deterministic strided partition of ``range(n)`` into
    ``ceil(n / block_rows)`` blocks whose sizes differ by at most one."""
    n = int(n)
    block_rows = int(block_rows)
    if n <= 0:
        raise ValueError("n must be positive")
    if block_rows <= 0:
        raise ValueError("block_rows must be positive")
    k = max(1, -(-n // block_rows))
    idx = np.arange(n)
    return [idx[i::k] for i in range(k)]


def cascade_solve(x, y, config, seed: Optional[WarmStart] = None,
                  block_rows: int = 4096, device=None, callback=None):
    """Two-level cascade solve of (x, y): warm block solves, SV merge,
    warm-started final global solve.

    seed        optional WarmStart over the FULL row set (e.g. from
                ``seed_from_model`` on the previous generation laid out at
                the head of x); each block receives the slice of the seed
                that its rows carry.
    block_rows  target block size; n <= block_rows degenerates to a
                single warm-started global solve (no partition pass).

    Returns ``(SolveResult, stats)``.  stats keys: ``blocks`` (list of
    per-block dicts: rows / seed_nnz / iterations / sv), ``merged_sv``,
    ``final_iterations``, ``total_iterations`` (blocks + final — the
    pair count a cold solve's ``iterations`` is compared against),
    ``seed_rows``.
    """
    from dpsvm_tpu.solver.smo import solve

    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    n = int(x.shape[0])
    if y.shape[0] != n:
        raise ValueError(f"y has {y.shape[0]} rows, x has {n}")
    seed_dense = seed.dense(n) if seed is not None else None

    stats = {"blocks": [], "seed_rows": 0 if seed_dense is None
             else int(np.count_nonzero(seed_dense))}

    if n <= int(block_rows):
        res = solve(x, y, config, callback=callback, device=device,
                    warm_start=seed)
        stats["merged_sv"] = int(np.count_nonzero(np.asarray(res.alpha)))
        stats["final_iterations"] = int(res.iterations)
        stats["total_iterations"] = int(res.iterations)
        res.stats["cascade"] = stats
        return res, stats

    blocks = cascade_partition(n, block_rows)
    merged = np.zeros(n, np.float64)
    total = 0
    for bidx in blocks:
        seed_b = None
        if seed_dense is not None and np.any(seed_dense[bidx] > 0):
            seed_b = WarmStart(alpha=seed_dense[bidx])
        res_b = solve(x[bidx], y[bidx], config, device=device,
                      warm_start=seed_b)
        a_b = np.asarray(res_b.alpha, np.float64)
        merged[bidx] = a_b
        total += int(res_b.iterations)
        stats["blocks"].append({
            "rows": int(bidx.size),
            "seed_nnz": 0 if seed_dense is None
            else int(np.count_nonzero(seed_dense[bidx])),
            "iterations": int(res_b.iterations),
            "sv": int(np.count_nonzero(a_b)),
        })

    stats["merged_sv"] = int(np.count_nonzero(merged))
    final_seed = (WarmStart(alpha=merged)
                  if stats["merged_sv"] else None)
    res = solve(x, y, config, callback=callback, device=device,
                warm_start=final_seed)
    stats["final_iterations"] = int(res.iterations)
    stats["total_iterations"] = total + int(res.iterations)
    res.stats["cascade"] = stats
    return res, stats
