"""Exact float64 gradient reconstruction legs (config.reconstruct_every).

The extreme-C productization of the round-3 external harness: at the
reference's covtype stress hyperparameters (c=2048, gamma=0.03125 —
reference Makefile:77) the solver's fp32 incremental gradient drifts
(measured: carried gap 0.005 vs true 1.1 after one 8M-pair leg), so the
carried stopping rule b_lo <= b_hi + 2*eps (svmTrainMain.cpp:310) cannot
be trusted. This module runs the device solve in LEGS of at most
``config.reconstruct_every`` pair updates and, between legs,

  1. recomputes the gradient EXACTLY in float64 on the host from alpha
     (the LibSVM move — its solver reconstructs its gradient too),
  2. REJECTS a leg whose true gap regressed (its drift did more harm
     than its optimization did good), reverting and halving the next
     leg's budget — the reachable drift floor halves with it,
  3. judges convergence ONLY on the reconstructed gap, and reports the
     reconstructed extrema as the model's (b_hi, b_lo).

With ``config.compensated`` (Kahan gradient carry, solver/smo.py
kahan_add) the within-leg drift is second-order, so legs rarely reject
and one or two reconstructions certify convergence; without it the
adaptive halving alone reproduces the round-3 harness behavior.

TPU split of labor: the solve legs are entirely on-device (XLA/Pallas);
only the O(n * n_sv) float64 certification pass runs on the host, where
f64 exists natively (TPUs have no f64 datapath).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams
from dpsvm_tpu.ops.select import extrema_np
from dpsvm_tpu.solver.result import SolveResult

# Smallest leg budget the halving scheme will run before giving up: below
# this the per-leg overhead (dispatch + reconstruction) dwarfs progress.
_LEG_FLOOR = 2048
_MAX_LEGS = 1000  # runaway guard; real runs end on gap/budget/floor

# Hybrid tail engine (engine='block' runs only): a full block leg that
# fails to cut the TRUE gap below this fraction of the previous one — or
# regresses it outright — is declared stalled, and every remaining leg
# runs the per-pair engine instead. The block engine's restricted working
# sets are measured to cycle at extreme-C tails (gap ~3 after 460M
# subproblem pairs at the covtype stress config, BENCH_COVTYPE.md
# engine-semantics note) while per-pair global selection closes them; the
# per-pair legs ride the resident-Gram path (solver/smo.py _resolve_gram)
# where the (n, n) kernel matrix fits HBM, so the tail costs gathers, not
# matvecs. The ratio is deliberately permissive (block legs halving the
# gap keep the throughput engine); per-pair tail legs near convergence
# legitimately progress slower than this and are never re-judged.
_BLOCK_STALL_RATIO = 0.5

# Upfront regime gate (VERDICT round-5 item 6, heuristic half): the
# reactive stall detector above only fires AFTER a full block leg has
# been burned — at the covtype-stress shape that wasted leg is minutes
# of device time the trajectory shows is predictable from (C, n, d) up
# front. C·n/d is the discriminator: the block engine's restricted
# working sets cycle when the box is so loose (huge C) relative to the
# problem's effective dimension that the dual face is wide and the
# per-round q-subset keeps re-optimizing interchangeable coordinates.
# Validated against every measured regime on file:
#
#   | regime (measured verdict)                      | C·n/d  | gate |
#   |------------------------------------------------|--------|------|
#   | covtype stress n=50k d=54 C=2048 (block CYCLES,|        |      |
#   |   PARITY.md/BENCH_COVTYPE.md)                  | 1.9e6  | per-pair |
#   | covtype-shaped n=500k d=54 C=10 (block healthy,|        |      |
#   |   BENCH_COVTYPE_SWEEP.md round-5)              | 9.3e4  | block |
#   | blobs n=500k d=24 C=10 (block healthy, ditto)  | 2.1e5  | block |
#   | adult-shaped n=32.5k d=123 C=100 (healthy,     |        |      |
#   |   PARITY.md)                                   | 2.6e4  | block |
#
# The threshold sits an order of magnitude above the largest healthy
# point and ~2x below the measured-doomed one. The gate ALSO requires
# the resident (n, n) Gram to fit the device budget: the per-pair tail
# only beats block legs when its rows are gathers (22 vs 49.7 us/pair,
# PROFILE.md round-5) — at full-covtype n=500k the Gram cannot fit, so
# block legs + the reactive detector remain the best available start
# even though C·n/d is far past the threshold.
_UPFRONT_CND = 1e6


def block_tail_doomed(config: SVMConfig, n: int, d: int, device=None,
                      gram_budget_bytes: int = None) -> bool:
    """True when a hybrid (engine='block' + reconstruction legs) run
    should START on the per-pair engine (+ auto resident Gram) instead
    of burning a block leg the C·n/d heuristic predicts will stall.
    `gram_budget_bytes` overrides the device-derived budget (tests)."""
    if config.c * n / max(d, 1) < _UPFRONT_CND:
        return False
    from dpsvm_tpu.solver.smo import _GRAM_MIN_N, _gram_budget_bytes

    if gram_budget_bytes is None:
        import jax

        gram_budget_bytes = _gram_budget_bytes(
            device if device is not None else jax.devices()[0])
    return n >= _GRAM_MIN_N and 4 * n * n <= gram_budget_bytes


def _stored_x64(x, dtype: str) -> np.ndarray:
    """The float64 view of X as the SOLVER sees it: under bfloat16
    storage the device kernel rows see the bf16-rounded features, so the
    reconstruction must evaluate on the same rounded values or it would
    certify a different problem than the one being solved (same rule as
    ops/kernels.py blocked_kernel_matvec)."""
    x = np.asarray(x, np.float32)
    if dtype == "bfloat16":
        import ml_dtypes

        x = x.astype(ml_dtypes.bfloat16).astype(np.float32)
    return x.astype(np.float64)


def gram_matvec_f64(x, coef, kp: KernelParams, dtype: str = "float32",
                    block: int = 4096, queries=None) -> np.ndarray:
    """K(x, x_active) @ coef_active in float64 on the host, blocked so at
    most a (block, n_active) kernel tile is live. Only the nonzero-coef
    columns are evaluated (n_sv << n near convergence). Returns
    (len(queries) or n,) f64.

    `queries=None` evaluates at x's own rows (gradient reconstruction);
    a (m, d) query matrix evaluates at arbitrary points (the float64
    prediction path, predict.decision_function precision='float64' —
    ONE definition of the host f64 kernel algebra serves both). The
    float64 counterpart of ops/kernels.py blocked_kernel_matvec; mirrors
    kernel_from_dots exactly (including the RBF distance clamp at 0).
    """
    coef = np.asarray(coef, np.float64)
    n = x.shape[0]
    active = np.nonzero(coef != 0.0)[0]
    if kp.kind == "precomputed":
        if queries is not None:
            raise ValueError(
                "precomputed kernels carry no feature vectors; gather "
                "K(query, train) columns instead "
                "(models/precomputed.py decision_function)")
        # x IS the (n, n) Gram matrix (cast blockwise THROUGH the stored
        # dtype — the device gathers bf16-rounded rows under
        # dtype='bfloat16', and certifying unrounded values would judge a
        # different problem; same rule as _stored_x64 — and only the
        # active columns: n_sv << n near convergence).
        if active.size == 0:
            return np.zeros(n, np.float64)
        ca = coef[active]
        out = np.empty(n, np.float64)
        if dtype == "bfloat16":
            import ml_dtypes
        for s in range(0, n, block):
            blk = np.asarray(x[s:s + block][:, active], np.float32)
            if dtype == "bfloat16":
                blk = blk.astype(ml_dtypes.bfloat16).astype(np.float32)
            out[s:s + block] = blk.astype(np.float64) @ ca
        return out
    xq = (_stored_x64(x, dtype) if queries is None
          else np.asarray(queries, np.float64))
    m = xq.shape[0]
    if active.size == 0:
        return np.zeros(m, np.float64)
    x64 = xq if queries is None else _stored_x64(x, dtype)
    xa = x64[active]
    ca = coef[active]
    out = np.empty(m, np.float64)
    if kp.kind == "rbf":
        sq = np.einsum("nd,nd->n", xq, xq)
        sqa = np.einsum("nd,nd->n", xa, xa)
    for s in range(0, m, block):
        t = xq[s:s + block]
        dots = t @ xa.T
        if kp.kind == "linear":
            k = dots
        elif kp.kind == "rbf":
            d2 = np.maximum(sq[s:s + block, None] + sqa[None, :]
                            - 2.0 * dots, 0.0)
            k = np.exp(-kp.gamma * d2)
        elif kp.kind == "poly":
            k = (kp.gamma * dots + kp.coef0) ** kp.degree
        elif kp.kind == "sigmoid":
            k = np.tanh(kp.gamma * dots + kp.coef0)
        else:
            raise ValueError(f"unknown kernel kind {kp.kind!r}")
        out[s:s + block] = k @ ca
    return out


def _linear_term(x, y64, alpha_init, f_init, kp: KernelParams,
                 dtype: str) -> np.ndarray:
    """The y-scaled linear term of the dual, recovered from the caller's
    start point: f_i = sum_j a_j y_j K_ij + y_i p_i, so
    y*p = f_init - K @ (alpha_init * y). For the plain C-SVC start
    (f_init is None) this is exactly -y; the SVR / one-class / nu
    reductions (models/*.py) supply their transformed f_init, which makes
    the reconstruction valid for every problem the solvers express."""
    if f_init is None:
        return -y64
    yp = np.asarray(f_init, np.float64).copy()
    if alpha_init is not None and np.any(np.asarray(alpha_init) != 0):
        yp -= gram_matvec_f64(
            x, np.asarray(alpha_init, np.float64) * y64, kp, dtype)
    return yp


def solve_in_legs(base_solve, x, y, config: SVMConfig, callback=None,
                  checkpoint_path: Optional[str] = None, resume: bool = False,
                  alpha_init=None, f_init=None, **solve_kw) -> SolveResult:
    """Run ``base_solve`` (solver.smo.solve or a mesh binding) in
    reconstruction legs. See the module docstring for the scheme.

    Contract notes:
      * ``iterations`` counts ALL pair updates executed, including those
        of rejected legs (the budget was genuinely spent);
      * ``converged``/``b_hi``/``b_lo`` come from the float64
        reconstruction, never the carried state;
      * checkpoints (``checkpoint_path``) are written once per leg with
        the reconstructed state, so a resume restarts from certified
        ground truth rather than drifted carry.
    """
    from dpsvm_tpu.utils.checkpoint import (PeriodicCheckpointer,
                                            resume_solver_state)

    x = np.asarray(x, np.float32)
    y_i32 = np.asarray(y, np.int32)
    y64 = y_i32.astype(np.float64)
    n, d = x.shape
    kp = KernelParams(config.kernel, config.resolve_gamma(d),
                      config.degree, config.coef0)
    target = 2.0 * config.epsilon
    # Legs aim BELOW the outer target (measured 0.35x, round-3 harness):
    # carried-converging at exactly the target stalls the true gap just
    # above it once residual drift is added back. The outer config's
    # RESOLVED matmul precision is pinned explicitly: the inner legs have
    # reconstruct_every=0, so leaving precision on auto would silently
    # drop the accuracy-mode escalation to "highest" — and bf16 dot
    # products are the dominant drift term the legs exist to beat.
    inner = config.replace(reconstruct_every=0,
                           epsilon=0.35 * config.epsilon,
                           checkpoint_every=0,
                           matmul_precision=config.resolve_precision()
                           or "default")
    yp = _linear_term(x, y64, alpha_init, f_init, kp, config.dtype)

    alpha_cur = (None if alpha_init is None
                 else np.asarray(alpha_init, np.float32))
    f_cur = None if f_init is None else np.asarray(f_init, np.float32)
    pairs_done = 0
    if resume:
        restored = resume_solver_state(checkpoint_path, config, n)
        if restored is not None:
            alpha_cur = restored[0]
            f_cur = restored[1]
            pairs_done = int(restored[2])
    ckpt = PeriodicCheckpointer(checkpoint_path, config, pairs_done)

    aborted = [False]
    if callback is not None and hasattr(callback, "on_start"):
        # Fired ONCE with the cumulative (possibly resumed) pair count.
        # The per-leg wrappers deliberately carry no on_start: the inner
        # solves must not re-baseline a resume-aware metrics callback at
        # every leg.
        callback.on_start(pairs_done)

    def wrap_cb(offset):
        # Leg-local iteration counts are re-based onto the cumulative
        # pair count; a truthy return aborts the leg AND the leg loop.
        if callback is None:
            return None

        def cb(it, bh, bl, st):
            r = callback(offset + it, bh, bl, st)
            if r:
                aborted[0] = True
            return r

        return cb

    gap = np.inf
    b_hi = b_lo = None
    leg_budget = int(config.reconstruct_every)
    floor = min(_LEG_FLOOR, leg_budget)
    device_s = recon_s = 0.0
    recons = legs = 0
    converged = False
    hybrid = config.engine == "block"
    switch_pairs = None  # cumulative pair count at the block->xla switch
    upfront = False

    def switch_to_per_pair():
        # The per-pair engine takes over for the remaining legs: same
        # selection rule, block-only knobs reset (they would fail
        # validation on engine='xla').
        nonlocal inner, switch_pairs
        inner = inner.replace(engine="xla", pair_batch=1,
                              active_set_size=0, fused_fold=None,
                              fused_round=None, pipeline_rounds=None,
                              local_working_sets=None, sync_rounds=1)
        switch_pairs = pairs_done
        if config.verbose and not upfront:
            print(f"[reconstruct] block legs stalled at true gap "
                  f"{gap:.6f} after {pairs_done} pairs; switching "
                  f"remaining legs to the per-pair engine", flush=True)

    if hybrid and block_tail_doomed(config, n, d,
                                    device=solve_kw.get("device")):
        # Upfront regime gate: start the per-pair (+ auto resident Gram)
        # tail DIRECTLY — at this (C, n, d) the block legs are measured
        # to cycle and the reactive stall detector below would burn a
        # full leg re-learning it (VERDICT round-5 item 6, heuristic
        # half; see _UPFRONT_CND's validation table).
        upfront = True
        switch_to_per_pair()
        if config.verbose:
            print(f"[reconstruct] upfront regime gate: C*n/d = "
                  f"{config.c * n / max(d, 1):.3g} >= {_UPFRONT_CND:.0e} "
                  f"and the resident Gram fits — starting legs on the "
                  f"per-pair engine", flush=True)

    def reconstruct(alpha):
        f64 = gram_matvec_f64(
            x, np.asarray(alpha, np.float64) * y64, kp, config.dtype) + yp
        bh, bl = extrema_np(f64, alpha, y_i32, config.c_bounds(),
                            rule=config.selection)
        return f64, float(bh), float(bl)

    if alpha_cur is not None and np.any(alpha_cur != 0):
        # Warm start / resume: establish the rejection baseline from the
        # CURRENT state, or the first leg would be accepted even if it
        # regressed below the (possibly already good) starting point.
        t0 = time.perf_counter()
        f64_new, b_hi, b_lo = reconstruct(alpha_cur)
        recon_s += time.perf_counter() - t0
        recons += 1
        f_cur = f64_new.astype(np.float32)
        gap = b_lo - b_hi
        converged = gap <= target

    while (not converged and legs < _MAX_LEGS
           and pairs_done < config.max_iter):
        legs += 1
        cfg = inner.replace(
            max_iter=min(leg_budget, config.max_iter - pairs_done))
        res = base_solve(x, y_i32, cfg, callback=wrap_cb(pairs_done),
                         alpha_init=alpha_cur, f_init=f_cur, **solve_kw)
        pairs_done += int(res.iterations)
        device_s += res.train_seconds
        t0 = time.perf_counter()
        f64_new, bh, bl = reconstruct(res.alpha)
        recon_s += time.perf_counter() - t0
        recons += 1
        new_gap = bl - bh
        if config.verbose:
            print(f"[reconstruct] leg={legs} budget={cfg.max_iter} "
                  f"pairs={pairs_done} "
                  f"carried_gap={float(res.b_lo - res.b_hi):.6f} "
                  f"true_gap={new_gap:.6f}", flush=True)
        if np.isfinite(gap) and new_gap > gap:
            # REJECT: revert to the kept state. A regressed BLOCK leg in
            # hybrid mode is the cycling signature — switch engines at
            # the full budget; otherwise halve (drift floor semantics:
            # the true gap descends monotonically by construction).
            if hybrid and inner.engine == "block":
                switch_to_per_pair()
                if aborted[0]:
                    break
                continue
            leg_budget //= 2
            if leg_budget < floor or aborted[0]:
                break
            continue
        prev_gap = gap
        alpha_cur = res.alpha
        f_cur = f64_new.astype(np.float32)
        gap, b_hi, b_lo = float(new_gap), bh, bl
        if ckpt.active:
            ckpt.save(pairs_done, alpha_cur, f_cur, b_hi, b_lo, force=True)
        if gap <= target:
            converged = True
            break
        if aborted[0]:
            break
        if (hybrid and inner.engine == "block" and np.isfinite(prev_gap)
                and gap > _BLOCK_STALL_RATIO * prev_gap):
            # Accepted but stalled block leg: hand the tail to the
            # per-pair engine (supersedes the drift-floor halving — the
            # slow progress is the engine, not the leg length).
            switch_to_per_pair()
            continue
        if np.isfinite(prev_gap) and gap > 0.85 * prev_gap:
            # Near the per-leg drift floor: finer legs resolve further.
            leg_budget //= 2
            if leg_budget < floor:
                break

    if b_hi is None:
        # No leg ran (resumed at budget) or none was accepted: certify
        # whatever state we hold so the result is still reconstructed.
        if alpha_cur is None:
            alpha_cur = np.zeros(n, np.float32)
        t0 = time.perf_counter()
        f64_new, b_hi, b_lo = reconstruct(alpha_cur)
        recon_s += time.perf_counter() - t0
        recons += 1
        f_cur = f64_new.astype(np.float32)
        gap = b_lo - b_hi
        converged = gap <= target

    return SolveResult(
        alpha=alpha_cur,
        b=float((b_lo + b_hi) / 2.0),  # svmTrainMain.cpp:329
        b_hi=float(b_hi),
        b_lo=float(b_lo),
        iterations=pairs_done,
        converged=converged,
        train_seconds=device_s,
        stats={
            "f": f_cur,
            "true_gap": float(gap),
            "legs": legs,
            "reconstructions": recons,
            "reconstruct_seconds": recon_s,
            "final_leg_budget": leg_budget,
            # Cumulative pair count at which hybrid mode handed the tail
            # to the per-pair engine (None: never switched / not block;
            # 0 with hybrid_upfront: the C·n/d regime gate fired before
            # any leg ran).
            "hybrid_switch_pairs": switch_pairs,
            "hybrid_upfront": upfront,
        },
    )
