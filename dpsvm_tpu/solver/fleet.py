"""Batched multi-problem SMO: train a FLEET of binary subproblems that
share one X inside a single compiled ``lax.while_loop``.

Why it exists: the reference (and our ``solve()``) trains ONE binary
problem per dispatch sequence, and the multiclass layer inherits that
shape — 60k OvO is 45 sequential solves whose warm end-to-end time is
dominated by per-solve dispatch/transfer glue, not device work
(BENCH_MULTICLASS.md: 4.95 s of device time inside 112 s of warm e2e on
a tunneled runtime, ~360 round-trips). LIBSVM-class CPU/GPU tools cannot
batch across problems at all; on TPU the idiomatic answer is to stack
the independent subproblems along a leading ``k`` axis and let ONE
jitted program train them all:

* per-problem carries ``(alpha, f, b_hi, b_lo, it)`` are stacked
  ``(k, n)`` / ``(k,)`` arrays; X (or the resident Gram) is SHARED and
  device-resident once;
* selection is one batched masked argmin/argmax pass
  (``ops/select.py select_working_set_batched``);
* the 2k kernel rows of a trip ride ONE ``(2k, d) x (d, n)`` MXU matmul
  (or 2k row gathers of the shared resident Gram);
* the pair algebra is the SAME ``pair_alpha_update`` the per-pair engine
  compiles, evaluated on ``(k,)`` vectors;
* per-problem convergence MASKING freezes finished problems exactly
  (their gated deltas are 0.0, so ``f`` and ``alpha`` are bit-frozen)
  while stragglers keep iterating — the loop exits when every problem
  has converged or exhausted ``max_iter``.

OvO's per-pair class subsets become ROW MASKS over the shared X: no
per-subset host copies, no per-shape recompiles — one executor shape
per (fleet bucket, n). The per-problem box bounds ``C`` are TRACED
``(k, 2)`` values, so a C/gamma-free hyperparameter sweep (same kernel,
different C per problem) batches without recompiling
(``estimators.svc_c_sweep``).

Trade-off, stated honestly: each trip's row pass covers the FULL shared
row set even for problems whose mask selects a fraction of it, and a
fleet with one straggler still pays a full (2k, n) trip per iteration.
The win is dispatch count and latency amortization — ceil(K /
fleet_size) dispatch sequences instead of K — which is exactly what
dominates multiclass training on dispatch-latency-bound runtimes.

Parity contract: problem j's trajectory is the per-pair MVP engine's
trajectory (same selection rule, same pair algebra, same f-update
association); results match sequential ``solve()`` on the explicit
subset within the existing parity tolerances (tests/test_fleet.py pins
this per problem).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_rows
from dpsvm_tpu.ops.select import refresh_extrema_host, select_working_set_batched
from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.solver.smo import (_BUDGET_EPS, _UNOBSERVED_CHUNK,
                                  _device_x_cached, _precision_ctx,
                                  _resident_gram_cached, _resolve_gram,
                                  pair_alpha_update)


@dataclasses.dataclass
class FleetProblem:
    """One binary subproblem over the fleet's SHARED row set.

    y        -- (n,) labels in {-1, +1} over ALL shared rows; values at
                rows outside `row_mask` are ignored (pinned to +1 in the
                stacked carry).
    row_mask -- (n,) bool marking this problem's rows (None = all rows).
                This is how OvO subsets ride the shared X without
                per-subset copies.
    c        -- per-problem box bound override: a scalar C (the config's
                class weights still apply) or an explicit (c_pos, c_neg)
                pair; None = the config's c_bounds(). Traced, so a C
                sweep shares one compiled executor.
    tag      -- caller bookkeeping, returned in stats["tag"].
    alpha_init / f_init -- per-problem warm-start carry (ISSUE 18):
                (n,) float arrays over the shared row set, BOTH given
                or both None. Callers must pass a seed that is already
                feasibility-repaired against THIS problem's box with a
                matching rebuilt gradient
                (solver/warmstart.prepare_warm_start — values outside
                `row_mask` must be zero / cold). When every problem in
                a chunk is seedless the stacked carry is constructed
                exactly as before, so cold fleets stay bit-identical.
    """

    y: np.ndarray
    row_mask: Optional[np.ndarray] = None
    c: object = None
    tag: object = None
    alpha_init: Optional[np.ndarray] = None
    f_init: Optional[np.ndarray] = None


class FleetState(NamedTuple):
    """while_loop carry: SMOState stacked along the problem axis, plus a
    global trip counter for chunk bookkeeping (per-problem `it` counts
    diverge once problems freeze)."""

    alpha: jax.Array  # (k, n) float32
    f: jax.Array  # (k, n) float32
    b_hi: jax.Array  # (k,) float32
    b_lo: jax.Array  # (k,) float32
    it: jax.Array  # (k,) int32
    t: jax.Array  # () int32 trips


@partial(jax.jit, donate_argnums=(5,),
         static_argnames=("kp", "eps", "tau", "chunk"))
def _run_fleet_chunk(x, y, x_sq, valid, cb, state: FleetState, max_iter,
                     kp: KernelParams, eps: float, tau: float,
                     chunk: int) -> FleetState:
    """Run up to `chunk` fleet trips fully on device. One trip advances
    every still-active problem by exactly one reference-parity MVP
    iteration; frozen problems ride along with gated (exact no-op)
    updates."""
    k, n_pad = y.shape
    t_end = state.t + chunk
    cp = cb[:, 0:1]  # (k, 1) for row broadcasting
    cn = cb[:, 1:2]

    def active_mask(st):
        return (st.it < max_iter) & (st.b_lo > st.b_hi + 2.0 * eps)

    def cond(st: FleetState):
        return (st.t < t_end) & jnp.any(active_mask(st))

    def body(st: FleetState):
        active = active_mask(st)
        i_hi, b_hi, i_lo, b_lo = select_working_set_batched(
            st.f, st.alpha, y, cp, cn, valid)
        idx = jnp.concatenate([i_hi, i_lo])  # (2k,)
        # Row extraction via UNROLLED dynamic slices, never jnp.take:
        # XLA lowers a general row gather from a large operand (X, or
        # the (n, n) resident Gram) to a one-hot MATMUL on TPU; 2k
        # dynamic slices are plain DMAs (_run_chunk_micro precedent).
        qx = jnp.stack([lax.dynamic_index_in_dim(x, idx[s], 0,
                                                 keepdims=False)
                        for s in range(2 * k)])
        # ONE batched pass produces every problem's hi AND lo kernel row
        # (a (2k, d) x (d, n) MXU matmul — or, in resident-Gram /
        # precomputed mode, the gathered rows verbatim).
        rows = kernel_rows(x, x_sq, qx, jnp.take(x_sq, idx), kp)
        rows_hi = rows[:k]  # (k, n)
        rows_lo = rows[k:]
        hi_col = i_hi[:, None]
        lo_col = i_lo[:, None]
        k_hh = jnp.take_along_axis(rows_hi, hi_col, axis=1)[:, 0]
        k_ll = jnp.take_along_axis(rows_lo, lo_col, axis=1)[:, 0]
        k_hl = jnp.take_along_axis(rows_hi, lo_col, axis=1)[:, 0]
        eta = jnp.maximum(k_hh + k_ll - 2.0 * k_hl, tau)

        y_hi = jnp.take_along_axis(y, hi_col, axis=1)[:, 0]
        y_lo = jnp.take_along_axis(y, lo_col, axis=1)[:, 0]
        a_hi_old = jnp.take_along_axis(st.alpha, hi_col, axis=1)[:, 0]
        a_lo_old = jnp.take_along_axis(st.alpha, lo_col, axis=1)[:, 0]
        c_hi = jnp.where(y_hi > 0, cb[:, 0], cb[:, 1])
        c_lo = jnp.where(y_lo > 0, cb[:, 0], cb[:, 1])
        # THE shared pair algebra, on (k,) vectors. `gate=active` is the
        # convergence mask: a frozen problem's deltas are exactly 0, so
        # its alpha/f stay bit-identical while stragglers run.
        a_hi_new, a_lo_new = pair_alpha_update(
            a_hi_old, a_lo_old, y_hi, y_lo, b_hi, b_lo, eta, c_hi, c_lo,
            gate=active)
        rowid = jnp.arange(k, dtype=jnp.int32)
        # lo first, hi second — the per-pair engine's degenerate-pair
        # override order (solver/smo.py _apply_pair_update).
        alpha = st.alpha.at[rowid, i_lo].set(a_lo_new)
        alpha = alpha.at[rowid, i_hi].set(a_hi_new)
        d_hi = (a_hi_new - a_hi_old) * y_hi
        d_lo = (a_lo_new - a_lo_old) * y_lo
        # Rank-2 f update per problem, one (k, n) VPU pass for the fleet;
        # association matches the sequential engine's left-to-right sum.
        f = st.f + d_hi[:, None] * rows_hi + d_lo[:, None] * rows_lo
        b_hi_new = jnp.where(active, b_hi, st.b_hi)
        b_lo_new = jnp.where(active, b_lo, st.b_lo)
        it = st.it + active.astype(jnp.int32)
        return FleetState(alpha, f, b_hi_new, b_lo_new, it, st.t + 1)

    return lax.while_loop(cond, body, state)


def fleet_routing_reasons(config: SVMConfig) -> list:
    """Why a config cannot ROUTE through the fleet executor (empty list
    = eligible). The single source of truth for the engine-compatibility
    gate shared by models/multiclass.py _fleet_eligible and
    estimators.svc_c_sweep — a hand-maintained copy in each caller would
    drift. (solve_fleet itself is slightly more permissive — it accepts
    kernel='precomputed' directly — these are the ROUTER's rules, where
    a silent engine swap would make results incomparable with what the
    user configured.)"""
    reasons = []
    if config.engine != "xla" or config.selection != "mvp" \
            or config.pair_batch != 1:
        reasons.append(
            "the fleet executor is the per-pair MVP engine "
            "(engine='xla', selection='mvp', pair_batch=1)")
    if config.kernel == "precomputed":
        reasons.append("kernel='precomputed' (per-split Gram sub-matrices)")
    if config.compensated or config.reconstruct_every:
        reasons.append("accuracy-mode (compensated/reconstruction) solves")
    return reasons


def _fleet_bucket(k_real: int) -> int:
    """Power-of-two fleet bucket: OvO routes 45 problems in fleet_size
    chunks whose last chunk is short — padding it to the bucket keeps
    ONE compiled executor shape per (bucket, n)."""
    return 1 << max(0, k_real - 1).bit_length()


def _problem_bounds(p: FleetProblem, config: SVMConfig) -> tuple:
    """(c_pos, c_neg) of one problem: config bounds, a scalar C override
    (config class weights still apply), or an explicit pair."""
    if p.c is None:
        return config.c_bounds()
    if isinstance(p.c, tuple):
        cp, cn = p.c
        return float(cp), float(cn)
    c = float(p.c)
    if c <= 0:
        raise ValueError("FleetProblem.c must be > 0")
    return c * config.weight_pos, c * config.weight_neg


def solve_fleet(
    x,
    problems: list,
    config: SVMConfig,
    device: Optional[jax.Device] = None,
    pad_to: Optional[int] = None,
) -> list:
    """Train every FleetProblem in `problems` (all sharing `x`) in a
    handful of device dispatches. Returns one SolveResult per problem,
    in order; each result's alpha/f cover ONLY that problem's masked
    rows (aligned with ``x[row_mask]``), so it drops into the same
    model-assembly code a sequential per-subset ``solve()`` feeds.

    Semantics: every problem runs the reference-parity per-pair MVP
    iteration (engine='xla', selection='mvp', pair_batch=1 equivalent);
    `config.engine` is NOT consulted for the iteration structure — the
    fleet IS its own executor. Honored config knobs: kernel family,
    epsilon/max_iter/tau, class weights (per-problem C overrides
    compose with them), dtype, budget_mode, gram_resident (the shared
    resident Gram serves all problems), matmul_precision, chunk_iters +
    verbose (per-chunk observation). Not supported here: callbacks,
    checkpoint/resume, compensated/reconstruction accuracy mode, the
    LRU row cache, nu/second_order selection.

    `train_seconds` is the fleet's total device time divided evenly
    across the real problems (per-problem attribution inside one fused
    dispatch is not separable); stats["fleet"] carries the whole-fleet
    numbers.
    """
    if not problems:
        return []
    if config.selection != "mvp":
        raise ValueError(
            "solve_fleet implements the reference MVP rule only "
            f"(selection={config.selection!r}); run those problems "
            "through sequential solve()")
    if config.compensated or config.reconstruct_every:
        raise ValueError(
            "solve_fleet does not implement the compensated/"
            "reconstruction accuracy stack; use sequential solve() for "
            "extreme-C problems")

    x = np.asarray(x, np.float32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    if config.dtype == "bfloat16":
        from dpsvm_tpu.ops.kernels import warn_if_bf16_degrades
        warn_if_bf16_degrades(x, config)
    # bf16 Gram path (config.bf16_gram): one gate decides for the WHOLE
    # fleet (shared X, one storage dtype), against the largest box
    # bound any problem runs under — per-problem C overrides included,
    # so a single extreme-C problem in the fleet refuses bf16 for all
    # (the conservative reading of the shared-storage contract). Same
    # loud-refusal stats/warning as solve() (ops/kernels.py).
    bf16_gram_stats = {}
    if config.bf16_gram:
        from dpsvm_tpu.ops.kernels import resolve_bf16_gram

        c_max = max(config.c_bounds())
        for p in problems:
            if p.c is not None:
                cs = np.asarray(p.c, np.float64).reshape(-1)
                c_max = max(c_max, float(cs.max()))
        _bfg_on, _, _entry = resolve_bf16_gram(
            x, config, gamma, c_max=c_max,
            scope="for the fleet (largest per-problem C)")
        bf16_gram_stats = {"bf16_gram": _entry}
        if _bfg_on:
            dtype = jnp.bfloat16
        else:
            import warnings

            warnings.warn(_entry["note"], stacklevel=3)
    if device is None:
        device = jax.devices()[0]

    if kp.kind == "precomputed" and x.shape[0] != x.shape[1]:
        raise ValueError(
            f"kernel='precomputed' needs the square (n, n) Gram matrix "
            f"as x; got {x.shape}")
    n_pad = max(n, min(pad_to, 2 ** 31) if pad_to else n)
    if kp.kind == "precomputed" and n_pad != n:
        raise ValueError(
            "pad_to does not compose with kernel='precomputed' (the "
            "padded Gram rows/columns would need kernel values)")

    k_real = len(problems)
    k_pad = _fleet_bucket(k_real)

    def build_x_p():
        if n_pad == n:
            return x
        xp = np.zeros((n_pad, d), np.float32)
        xp[:n] = x
        return xp

    with _precision_ctx(config):
        use_gram = _resolve_gram(config, kp, n_pad, device)
        if use_gram:
            x_dev, _ = _resident_gram_cached(x, build_x_p, n_pad, dtype,
                                             kp, config, device)
            kp_run = KernelParams("precomputed")
            x_sq = jnp.zeros((n_pad,), jnp.float32)
        elif kp.kind == "precomputed":
            x_dev = jax.device_put(jnp.asarray(build_x_p(), dtype), device)
            kp_run = kp
            x_sq = jnp.zeros((n_pad,), jnp.float32)
        else:
            x_dev, x_sq = _device_x_cached(x, build_x_p, n_pad, dtype,
                                           device)
            kp_run = kp

        # Stacked per-problem carries. Dummy bucket-padding problems have
        # an all-False mask: their selection sets are empty, the gap
        # reads closed after the first (sentinel) trip, and they freeze.
        y_stack = np.ones((k_pad, n_pad), np.float32)
        valid_stack = np.zeros((k_pad, n_pad), bool)
        cb = np.ones((k_pad, 2), np.float32)
        masks: list = []
        for j, p in enumerate(problems):
            yj = np.asarray(p.y)
            if yj.shape != (n,):
                raise ValueError(
                    f"problem {j}: y has shape {yj.shape}, expected "
                    f"({n},) over the shared row set")
            if p.row_mask is None:
                mask = np.ones((n,), bool)
            else:
                mask = np.asarray(p.row_mask, bool)
                if mask.shape != (n,):
                    raise ValueError(
                        f"problem {j}: row_mask has shape {mask.shape}, "
                        f"expected ({n},)")
            lab = set(np.unique(yj[mask]).tolist())
            if not lab <= {-1, 1, -1.0, 1.0}:
                raise ValueError(
                    f"problem {j}: masked labels must be in {{-1, +1}}, "
                    f"got {sorted(lab)[:6]}")
            y_stack[j, :n] = np.where(mask, yj, 1.0).astype(np.float32)
            valid_stack[j, :n] = mask
            cb[j] = _problem_bounds(p, config)
            masks.append(mask)
            if (p.alpha_init is None) != (p.f_init is None):
                raise ValueError(
                    f"problem {j}: alpha_init and f_init come together "
                    "(solver/warmstart.prepare_warm_start builds the "
                    "pair)")

        y_dev = jax.device_put(jnp.asarray(y_stack), device)
        valid_dev = jax.device_put(jnp.asarray(valid_stack), device)
        cb_dev = jax.device_put(jnp.asarray(cb), device)
        if any(p.alpha_init is not None for p in problems):
            # Warm-start carry (ISSUE 18): seeded problems write their
            # repaired alpha / rebuilt f rows into the stacked numpy
            # carries before upload; seedless problems keep the exact
            # cold rows (alpha = 0, f = -y).
            alpha_stack = np.zeros((k_pad, n_pad), np.float32)
            f_stack = (-y_stack).astype(np.float32)
            for j, p in enumerate(problems):
                if p.alpha_init is None:
                    continue
                a_j = np.asarray(p.alpha_init, np.float32)
                f_j = np.asarray(p.f_init, np.float32)
                if a_j.shape != (n,) or f_j.shape != (n,):
                    raise ValueError(
                        f"problem {j}: alpha_init/f_init must be ({n},) "
                        f"over the shared row set, got {a_j.shape} / "
                        f"{f_j.shape}")
                mask = masks[j]
                alpha_stack[j, :n] = np.where(mask, a_j, 0.0)
                f_stack[j, :n] = np.where(mask, f_j, f_stack[j, :n])
            alpha0 = jnp.asarray(alpha_stack)
            f0 = jnp.asarray(f_stack)
        else:
            alpha0 = jnp.zeros((k_pad, n_pad), jnp.float32)
            f0 = jnp.asarray(-y_stack)  # f = -y at alpha = 0
        state = FleetState(
            alpha=alpha0,
            f=f0,
            b_hi=jnp.full((k_pad,), -jnp.inf, jnp.float32),
            b_lo=jnp.full((k_pad,), jnp.inf, jnp.float32),
            it=jnp.zeros((k_pad,), jnp.int32),
            t=jnp.int32(0),
        )
        state = jax.device_put(state, device)

        eps_run = _BUDGET_EPS if config.budget_mode else float(config.epsilon)
        observe = bool(config.verbose)
        chunk = int(config.chunk_iters) if observe else _UNOBSERVED_CHUNK
        max_iter = jnp.int32(config.max_iter)

        # Observability (dpsvm_tpu/obs; NULL_OBS when disabled): one
        # run log for the whole fleet, chunk records from the per-chunk
        # host pulls the loop already makes (zero new transfers). Not
        # part of `observe` — chunk cadence is unchanged.
        from dpsvm_tpu.obs import run_obs

        obs = run_obs("fleet", config,
                      meta={"n": n, "d": d, "n_pad": n_pad,
                            "k": k_real, "bucket": k_pad,
                            "kernel": config.kernel,
                            "gram_resident": bool(use_gram)})

        train_seconds = 0.0
        dispatches = 0
        while True:
            with obs.span("fleet/chunk"):
                t0 = time.perf_counter()
                dispatches += 1
                state = _run_fleet_chunk(
                    x_dev, y_dev, x_sq, valid_dev, cb_dev, state,
                    max_iter, kp=kp_run, eps=eps_run,
                    tau=float(config.tau), chunk=chunk)
                jax.block_until_ready(state)
            chunk_dt = time.perf_counter() - t0
            train_seconds += chunk_dt
            b_hi = np.asarray(state.b_hi)
            b_lo = np.asarray(state.b_lo)
            it = np.asarray(state.it)
            active = (it < config.max_iter) & (b_lo > b_hi + 2.0 * eps_run)
            # Fleet-wide scalars derived from the arrays the loop just
            # pulled anyway (the convergence test needs them).
            obs.chunk(pairs=int(it[:k_real].sum()),
                      b_hi=float(np.min(b_hi[:k_real])),
                      b_lo=float(np.max(b_lo[:k_real])),
                      device_seconds=chunk_dt, dispatch=dispatches,
                      active=int(active[:k_real].sum()))
            if config.verbose:
                gaps = (b_lo - b_hi)[:k_real]
                print(f"[fleet] trips={int(state.t)} "
                      f"active={int(active[:k_real].sum())}/{k_real} "
                      f"max_gap={float(np.max(gaps)):.6f}")
            if not active.any():
                break
        # Only host-held values in the final record (NULL_OBS still
        # evaluates the arguments — a device pull here would tax the
        # disabled path).
        obs.finish(dispatches=dispatches,
                   pairs=int(it[:k_real].sum()),
                   train_seconds=round(train_seconds, 6),
                   converged=int((~active[:k_real]).sum()))

    alpha_all = np.asarray(state.alpha)
    f_all = np.asarray(state.f)
    results = []
    for j, p in enumerate(problems):
        mask = masks[j]
        rows_idx = np.nonzero(mask)[0]
        full = rows_idx.shape[0] == n
        a_sub = alpha_all[j, :n] if full else alpha_all[j, :n][rows_idx]
        f_sub = f_all[j, :n] if full else f_all[j, :n][rows_idx]
        y_sub = (y_stack[j, :n] if full
                 else y_stack[j, :n][rows_idx]).astype(np.int32)
        bh, bl = float(b_hi[j]), float(b_lo[j])
        conv = not (bl > bh + 2.0 * eps_run)
        if config.budget_mode:
            # Same discipline as solve(): budget exits report the honest
            # stopping rule at the REAL epsilon on the final state.
            bh, bl, conv = refresh_extrema_host(
                f_sub, a_sub, y_sub, (float(cb[j, 0]), float(cb[j, 1])),
                config.epsilon)
        results.append(SolveResult(
            alpha=a_sub,
            b=float((bl + bh) / 2.0),
            b_hi=bh,
            b_lo=bl,
            iterations=int(it[j]),
            converged=bool(conv),
            train_seconds=train_seconds / k_real,
            dispatches=dispatches,
            stats={
                "f": f_sub,
                "tag": p.tag,
                "fleet": {
                    "size": k_real,
                    "bucket": k_pad,
                    "index": j,
                    "dispatches": dispatches,
                    "device_seconds": train_seconds,
                    "gram_resident": bool(use_gram),
                },
                **bf16_gram_stats,
            },
        ))
    return results


def fleet_chunks(items: list, fleet_size: int) -> list:
    """Split a work list into fleet-sized chunks (the multiclass router's
    bucketing helper; the short tail chunk is padded to its power-of-two
    bucket inside solve_fleet)."""
    size = max(1, int(fleet_size))
    return [items[s:s + size] for s in range(0, len(items), size)]
