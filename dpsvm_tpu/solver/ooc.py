"""Out-of-core block-engine driver: train with X resident on the HOST.

Every other engine in this repo assumes the full (n, d) training matrix
fits in HBM, which caps trainable n at a few million rows per chip.
The reference scaled past device memory with its cache.cu LRU of kernel
dot rows (SVMlight's decomposition + kernel caching, Joachims 1999;
ThunderSVM's batched working-set rounds are the modern proof the same
storage hierarchy amortizes). This driver is that regime re-derived for
the TPU memory model:

* X stays in host memory — a NumPy array or an np.memmap — and is never
  fully materialized on device. Device-resident state is the O(n)
  solver vectors (f, alpha, y, x_sq, k_diag), a static-shape pool of
  (tile_rows, d) X tiles, and optionally the (L, n) block cache.
* Each outer round runs the SAME algebra as the in-core block engine
  (solver/block.py): selection over the device-resident gradient, a
  (q, q) Gram block, the shared subproblem (block.dispatch_subproblem),
  and the fold f += coef @ K(W, :). Only the fold's geometry changes:
  it streams over tiles with DOUBLE BUFFERING — tile t+1's async
  host->HBM ``device_put`` is issued before tile t's partial-fold
  matmul dispatch, so the H2D DMA overlaps the MXU work instead of
  serializing with it (ops/ooc.ooc_fold_tile).
* On top of the tile pool, ``ooc_cache_lines`` extends the
  solver/cache.py discipline (static-shape data/keys/ticks arrays,
  scatter-refresh LRU — cache.refresh_rows) to whole working sets: an
  (L, n) HBM cache of hot kernel DOT rows keyed by training-row index.
  A round whose entire live working set hits reads its Gram block AND
  its fold rows straight from the cache — no host gather, no tile
  stream, no recompute. Near convergence the selection concentrates on
  a stable set of support vectors, so all-hit rounds dominate exactly
  when rounds are cheapest to skip.

The host drives one round per iteration (the stream must be fed from
host memory, so a fully on-device while_loop is impossible by
construction — same reason the reference's loop was host-driven). The
trajectory is bit-identical to the in-core block engine's on shapes
where both fit: selection, subproblem and fold all reduce over the
same axes in the same order (tests/test_ooc.py pins exact equality,
including a memmap-backed X leg).
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                   kernel_from_dots, squared_norms)
from dpsvm_tpu.ops.ooc import ooc_fold_tile
from dpsvm_tpu.ops.select import refresh_extrema_host
from dpsvm_tpu.solver.block import dispatch_subproblem, select_block
from dpsvm_tpu.solver.cache import (CacheState, init_cache, probe_rows,
                                    refresh_rows)
from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.solver.smo import (_BUDGET_EPS, check_obs_finite,
                                  drain_pending_obs_events, maybe_kahan,
                                  run_with_fault_retry)
from dpsvm_tpu.testing import faults


class OocState(NamedTuple):
    """Host-visible round state handed to callbacks (the chunk-callback
    contract of solve(); MetricsLogger reads .hits on every backend)."""

    alpha: jax.Array
    f: jax.Array
    b_hi: float
    b_lo: float
    pairs: int
    rounds: int
    hits: int


_tile_sq = jax.jit(squared_norms)


@partial(jax.jit, static_argnames=("c", "q", "selection"))
def _ooc_select(f, f_err, alpha, y, valid, keys, c, q: int,
                selection: str):
    """One selection pass + (when the cache is live) the batched cache
    probe, fused into a single dispatch so the host learns everything
    it needs to route the round — all-hit vs stream — from one pull."""
    f_cur = f if f_err is None else f - f_err
    w, slot_ok, b_hi, b_lo = select_block(f_cur, alpha, y, c, q,
                                          valid=valid, rule=selection)
    if keys is None:
        hit = jnp.zeros((q,), bool)
        hit_slot = jnp.zeros((q,), jnp.int32)
    else:
        hit, hit_slot = probe_rows(keys, w, slot_ok)
    return w, slot_ok, b_hi, b_lo, hit, hit_slot


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau",
                                   "inner_iters", "inner_impl",
                                   "interpret", "selection",
                                   "pair_batch"))
def _ooc_subproblem(qx, w, slot_ok, f, f_err, alpha, y, x_sq, k_diag,
                    b_hi, b_lo, budget_left, kp: KernelParams, c,
                    eps: float, tau: float, inner_iters: int,
                    inner_impl: str, interpret: bool, selection: str,
                    pair_batch: int):
    """Gram block + subproblem for a STREAM round (rows freshly
    gathered host-side). Identical algebra to block._round_core's
    gather/gram/subproblem stages; returns (a_w, coef, t, qsq)."""
    f_cur = f if f_err is None else f - f_err
    gap_open = b_lo > b_hi + 2.0 * eps
    qsq = jnp.take(x_sq, w)
    kd_w = jnp.take(k_diag, w)
    a_w0 = jnp.take(alpha, w)
    y_w = jnp.take(y, w)
    f_w0 = jnp.take(f_cur, w)
    dots_w = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
    kb_w = kernel_from_dots(dots_w, qsq, qsq, kp)
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    a_w, coef, t = dispatch_subproblem(
        kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
        inner_impl, interpret, selection, pair_batch)
    return a_w, coef, t, qsq


def _apply_core(f_tiles, err_tiles, alpha, w, slot_ok, a_w):
    """Shared round tail: reassemble the full gradient from the folded
    tiles (pure data movement — the accumulate itself happened inside
    ooc_fold_tile, fused with the matmul exactly as the in-core round
    fuses it) and scatter alpha."""
    f = jnp.concatenate(f_tiles) if len(f_tiles) > 1 else f_tiles[0]
    f_err = None
    if err_tiles is not None:
        f_err = (jnp.concatenate(err_tiles)
                 if len(err_tiles) > 1 else err_tiles[0])
    n_pad = alpha.shape[0]
    safe_w = jnp.where(slot_ok, w, jnp.int32(n_pad))
    alpha = alpha.at[safe_w].set(jnp.where(slot_ok, a_w, 0.0),
                                 mode="drop")
    return f, f_err, alpha


@partial(jax.jit, donate_argnames=("alpha",))
def _ooc_apply(f_tiles, err_tiles, alpha, w, slot_ok, a_w):
    """Cache-off round tail. The alpha carry is donated (the
    run_chunk_block_donated discipline); the old f buffer died when
    its last tile slice was read."""
    return _apply_core(f_tiles, err_tiles, alpha, w, slot_ok, a_w)


@partial(jax.jit,
         donate_argnames=("alpha", "data", "keys", "ticks"))
def _ooc_apply_cached(f_tiles, err_tiles, alpha, data, keys, ticks, w,
                      slot_ok, a_w, dots, stamp):
    """Stream-round tail with the block cache live: reassemble +
    scatter + scatter-refresh of the freshly streamed dot rows into
    the LRU (solver/cache.refresh_rows). Returns the counters as one
    packed (2,) int32 pull: (n_hits, n_evictions)."""
    f, f_err, alpha = _apply_core(f_tiles, err_tiles, alpha, w,
                                  slot_ok, a_w)
    dots_full = (jnp.concatenate(dots, axis=1)
                 if len(dots) > 1 else dots[0])  # (q, n_pad)
    cache, n_hits, n_evict = refresh_rows(
        CacheState(data, keys, ticks), w, slot_ok, dots_full, stamp)
    return (f, f_err, alpha, cache.data, cache.keys, cache.ticks,
            jnp.stack([n_hits, n_evict]))


@partial(jax.jit,
         donate_argnames=("f", "f_err", "alpha", "ticks"),
         static_argnames=("kp", "c", "eps", "tau", "inner_iters",
                          "inner_impl", "interpret", "selection",
                          "pair_batch"))
def _ooc_round_cached(f, f_err, alpha, y, x_sq, k_diag, data, ticks,
                      w, slot_ok, hit_slot, b_hi, b_lo, budget_left,
                      stamp, kp: KernelParams, c, eps: float, tau: float,
                      inner_iters: int, inner_impl: str, interpret: bool,
                      selection: str, pair_batch: int):
    """ONE complete all-hit round in a single dispatch: Gram block and
    fold rows both read from the cache — the stream and the recompute
    are both skipped, which is the whole point of the block cache."""
    f_cur = f if f_err is None else f - f_err
    gap_open = b_lo > b_hi + 2.0 * eps
    qsq = jnp.take(x_sq, w)
    kd_w = jnp.take(k_diag, w)
    dots_w = jnp.take(data, hit_slot, axis=0)  # (q, n_pad) dot rows
    kb_w = kernel_from_dots(jnp.take(dots_w, w, axis=1), qsq, qsq, kp)
    a_w0 = jnp.take(alpha, w)
    y_w = jnp.take(y, w)
    f_w0 = jnp.take(f_cur, w)
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    a_w, coef, t = dispatch_subproblem(
        kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
        inner_impl, interpret, selection, pair_batch)
    k_rows = kernel_from_dots(dots_w, x_sq, qsq, kp)  # (q, n_pad)
    f, f_err = maybe_kahan(f, f_err, coef @ k_rows)
    n_pad = alpha.shape[0]
    safe_w = jnp.where(slot_ok, w, jnp.int32(n_pad))
    alpha = alpha.at[safe_w].set(jnp.where(slot_ok, a_w, 0.0),
                                 mode="drop")
    lines = ticks.shape[0]
    safe_slot = jnp.where(slot_ok, hit_slot, jnp.int32(lines))
    ticks = ticks.at[safe_slot].set(stamp, mode="drop")
    return f, f_err, alpha, ticks, t


def solve_ooc(
    x,
    y,
    config: SVMConfig,
    callback=None,
    device: Optional[jax.Device] = None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    alpha_init=None,
    f_init=None,
    pad_to: Optional[int] = None,
    warm_start=None,
) -> SolveResult:
    """Train binary C-SVC with host-resident X (config.ooc). Same
    result contract as solver/smo.solve; `x` may be any array-like the
    host can slice row-blocks from — np.ndarray or np.memmap.

    Checkpoint/resume (ISSUE 13): with ``checkpoint_path`` and
    ``config.checkpoint_every > 0``, the FULL driver carry — alpha,
    raw f AND the compensated f_err lanes, pair/round counters,
    extrema — is written atomically at round boundaries as a
    FORMAT_VERSION 2 checkpoint (utils/checkpoint.py). ``resume=True``
    restores it; because raw f and f_err are both restored, a cache-off
    resume reproduces the uninterrupted trajectory BITWISE from the
    restore point (tests/test_ooc.py pins it, memmap and padded tails
    included). The block kernel-row cache is deliberately NOT
    checkpointed — an (L, n) HBM cache would dwarf the O(n) state it
    rides on — so a resumed run restarts it cold (exact, just
    re-streamed; ``stats['cache_cold_restart']`` records it), which
    also means cache-ON resumes are exact-but-not-bitwise (a cold
    cache changes which rounds take the all-hit path).

    Fault retries ride the shared run_with_fault_retry machinery and
    resume from the last checkpoint this run wrote (else restart from
    scratch) — host-scale ooc runs are exactly the multi-hour jobs
    that get preempted.

    `warm_start` (solver/warmstart.py, ISSUE 18): the seed is repaired
    and its gradient rebuilt by the SAME streamed tile fold this
    driver's rounds dispatch (one extra pass over host X, double-
    buffered), then delegated to alpha_init/f_init. An all-zero
    repaired seed routes bit-identically through the cold path; a
    checkpoint resume, when present, still takes precedence."""
    from dpsvm_tpu.solver.smo import _precision_ctx

    if warm_start is not None:
        if alpha_init is not None or f_init is not None:
            raise ValueError(
                "pass either warm_start or alpha_init/f_init, not both")
        from dpsvm_tpu.solver.warmstart import prepare_warm_start

        a0, f0, wstats = prepare_warm_start(x, y, config, warm_start,
                                            device=device)
        res = solve_ooc(x, y, config, callback=callback, device=device,
                        checkpoint_path=checkpoint_path, resume=resume,
                        alpha_init=a0, f_init=f0, pad_to=pad_to)
        res.stats["warm_start"] = wstats
        return res

    def attempt(cfg_k, res_k, _k):
        return _solve_ooc_impl(x, y, cfg_k, callback, device,
                               checkpoint_path, res_k,
                               alpha_init, f_init, pad_to)

    with _precision_ctx(config):
        return run_with_fault_retry(config, checkpoint_path, resume,
                                    attempt)


def _tile_host(x, s: int, t: int, n: int, d: int):
    """Rows [s, s+t) of host X as a float32 (t, d) block, zero-padded
    past n. Slicing + np.asarray keeps memmaps lazy until here — this
    is the ONLY place training reads X's bulk."""
    blk = np.asarray(x[s:min(s + t, n)], np.float32)
    if blk.shape[0] < t:
        pad = np.zeros((t, d), np.float32)
        pad[:blk.shape[0]] = blk
        return pad
    return np.ascontiguousarray(blk)


def _put_tile(x, s: int, t: int, n: int, d: int, dtype, device):
    """One round-stream tile's host->HBM upload, with the
    ``ooc_tile_put`` fault seam in front: an injected transient here
    models the H2D DMA faulting mid-stream (the tunneled-runtime
    preemption shape), which the retry wrapper recovers from the last
    checkpoint."""
    faults.device_fault("ooc_tile_put", f"tile rows [{s}, {s + t})")
    return jax.device_put(jnp.asarray(_tile_host(x, s, t, n, d), dtype),
                          device)


def _solve_ooc_impl(x, y, config: SVMConfig, callback, device,
                    checkpoint_path, resume, alpha_init, f_init,
                    pad_to) -> SolveResult:
    t_entry = time.perf_counter()
    y_np = np.asarray(y, np.int32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    if config.dtype == "bfloat16":
        from dpsvm_tpu.ops.kernels import warn_if_bf16_degrades
        warn_if_bf16_degrades(np.asarray(x[:min(n, 4096)]), config)
    if device is None:
        device = jax.devices()[0]
    interpret = device.platform != "tpu"
    inner_impl = "xla" if interpret else "pallas"

    tile = min(int(config.ooc_tile_rows), max(n, int(pad_to or 0)))
    n_min = max(n, min(pad_to, 2 ** 31) if pad_to else n)
    n_pad = -(-n_min // tile) * tile
    tiles = n_pad // tile
    tile_bytes = tile * d * (2 if config.dtype == "bfloat16" else 4)

    gran = 2  # mvp / second_order only (config validates)
    q = max(gran, min(config.working_set_size, n_pad))
    q -= q % gran
    inner = config.inner_iters or 2 * q
    lines = int(config.ooc_cache_lines)
    use_cache = lines > 0

    # ---- device-side O(n) state. y/valid pad exactly as the in-core
    # driver does (solver/smo.py _solve_impl) so selections see the
    # identical masked problem.
    if n_pad == n:
        y_p = y_np.astype(np.float32)
        valid_dev = None
    else:
        y_p = np.ones((n_pad,), np.float32)
        y_p[:n] = y_np
        valid_np = np.zeros((n_pad,), bool)
        valid_np[:n] = True
        valid_dev = jax.device_put(jnp.asarray(valid_np), device)
    y_dev = jax.device_put(jnp.asarray(y_p, jnp.float32), device)

    # ---- setup stream: ONE pass over host X computes the squared
    # norms tile-by-tile on device (each row's reduction is identical
    # to the in-core full-matrix einsum, so x_sq is bit-identical).
    # The per-tile norm arrays are kept — the round stream feeds them
    # back to ooc_fold_tile so the per-tile program never touches an
    # (n,)-sized operand.
    from dpsvm_tpu.obs import run_obs

    obs = run_obs("solve", config,
                  meta={"n": n, "d": d, "n_pad": n_pad,
                        "engine": config.engine, "kernel": config.kernel,
                        "selection": config.selection, "ooc": True,
                        "ooc_tile_rows": tile, "ooc_tiles": tiles,
                        "ooc_cache_lines": lines})
    drain_pending_obs_events(obs)

    with obs.span("solver/ooc_setup_stream"):
        xsq_tiles = []
        for i in range(tiles):
            xt = jax.device_put(
                jnp.asarray(_tile_host(x, i * tile, tile, n, d), dtype),
                device)
            xsq_tiles.append(_tile_sq(xt))
        x_sq = jnp.concatenate(xsq_tiles) if tiles > 1 else xsq_tiles[0]
        k_diag = jax.jit(kernel_diag,
                         static_argnames="params")(x_sq, params=kp)

    f = jnp.asarray(-y_p, jnp.float32)
    alpha = jnp.zeros((n_pad,), jnp.float32)
    if alpha_init is not None:
        a_p = np.zeros((n_pad,), np.float32)
        a_p[:n] = np.asarray(alpha_init, np.float32)
        alpha = jnp.asarray(a_p)
    if f_init is not None:
        f_p = np.asarray(-y_p, np.float32)
        f_p[:n] = np.asarray(f_init, np.float32)
        f = jnp.asarray(f_p)
    f = jax.device_put(f, device)
    alpha = jax.device_put(alpha, device)
    f_err = jnp.zeros_like(f) if config.compensated else None

    # ---- checkpoint resume (ISSUE 13): restore the FULL v2 carry —
    # alpha, raw f and the compensated f_err lanes, pair/round
    # counters. Padded lanes re-initialize exactly as a fresh start
    # does (-y_p / 0): they are masked out of every selection, and the
    # padded-tail bit-identity pin proves they never steer the
    # real-row trajectory. A checkpoint resume takes precedence over
    # alpha_init/f_init (the solve() contract).
    start_pairs = 0
    start_rounds = 0
    resumed_from = None
    if resume:
        from dpsvm_tpu.utils.checkpoint import resume_state

        st = resume_state(checkpoint_path, config, n)
        if st is not None:
            a_pad = np.zeros((n_pad,), np.float32)
            a_pad[:n] = st.alpha
            f_pad = np.asarray(-y_p, np.float32)
            f_pad[:n] = st.f
            alpha = jax.device_put(jnp.asarray(a_pad), device)
            f = jax.device_put(jnp.asarray(f_pad), device)
            if f_err is not None:
                e_pad = np.zeros((n_pad,), np.float32)
                if st.f_err is not None:
                    # v2 ooc checkpoints carry the raw Kahan residual;
                    # restoring it is what makes the resumed
                    # compensated trajectory BITWISE equal to the
                    # uninterrupted one (v1 files restart it at zero —
                    # exact, but a different rounding path).
                    e_pad[:n] = st.f_err
                f_err = jax.device_put(jnp.asarray(e_pad), device)
            start_pairs = st.iteration
            start_rounds = st.rounds
            resumed_from = st.iteration
            obs.event("resume", iteration=start_pairs,
                      rounds=start_rounds,
                      format_version=st.format_version,
                      cache_cold_restart=bool(use_cache))

    # The block kernel-row cache restarts COLD on resume (an (L, n)
    # HBM cache is not worth persisting next to the O(n) carry); the
    # first post-resume rounds re-stream what it held.
    cache = init_cache(lines, n_pad) if use_cache else None
    cache = jax.device_put(cache, device) if use_cache else None

    c = config.c_bounds()
    eps_run = _BUDGET_EPS if config.budget_mode else float(config.epsilon)
    max_iter = int(config.max_iter)
    sub_kw = dict(kp=kp, c=c, eps=eps_run, tau=float(config.tau),
                  inner_iters=inner, inner_impl=inner_impl,
                  interpret=interpret, selection=config.selection,
                  pair_batch=int(config.pair_batch))

    jax.block_until_ready((x_sq, k_diag, f, alpha))
    phase_seconds = {"setup": time.perf_counter() - t_entry,
                     "solve": 0.0, "observe": 0.0, "finalize": 0.0}

    from dpsvm_tpu.utils.checkpoint import PeriodicCheckpointer

    ckpt = PeriodicCheckpointer(checkpoint_path, config, start_pairs)
    pairs = start_pairs
    rounds = start_rounds
    dispatches = 0
    tiles_streamed = 0
    bytes_h2d = 0
    cache_hits = 0
    cache_lookups = 0
    cache_evictions = 0
    cached_rounds = 0
    b_hi = float("-inf")
    b_lo = float("inf")
    converged = False
    train_seconds = 0.0
    keys_arg = cache.keys if use_cache else None

    if obs.live:
        c_tiles = obs.registry.counter("solve.ooc_tiles_total")
        c_bytes = obs.registry.counter("solve.ooc_tile_bytes_total")
        c_hits = obs.registry.counter("solve.cache_hits_total")
        c_looks = obs.registry.counter("solve.cache_lookups_total")
        c_evict = obs.registry.counter("solve.cache_evictions_total")
        c_saved = obs.registry.counter("solve.ooc_cached_rounds_total")

    while True:
        _sp = obs.span("solver/ooc_round")
        _sp.__enter__()
        try:
            t0 = time.perf_counter()
            dispatches += 1
            faults.device_fault("dispatch", f"ooc round {rounds + 1}")
            w_d, ok_d, bh_d, bl_d, hit_d, slot_d = _ooc_select(
                f, f_err, alpha, y_dev, valid_dev, keys_arg,
                c=c, q=q, selection=config.selection)
            b_hi = float(np.asarray(bh_d))
            b_lo = float(np.asarray(bl_d))
            # Non-finite sentinel (free: the extrema are already
            # materialized). A NaN gap would otherwise read as
            # "converged" (NaN comparisons are False) and return a
            # silently corrupt model — the one outcome no fault may
            # produce.
            b_hi, b_lo = faults.poison_obs(b_hi, b_lo)
            check_obs_finite(b_hi, b_lo, pairs, "ooc")
            converged = not (b_lo > b_hi + 2.0 * eps_run)
            if converged or pairs >= max_iter:
                round_dt = time.perf_counter() - t0
                train_seconds += round_dt
                break

            round_hits = 0
            round_evicts = 0
            round_tiles = 0
            ok_np = np.asarray(ok_d)
            live = int(ok_np.sum())
            hit_np = np.asarray(hit_d)
            all_hit = use_cache and live > 0 \
                and bool(np.all(hit_np[ok_np]))
            budget_left = jnp.int32(max_iter - pairs)
            stamp = jnp.int32(rounds + 1)
            if all_hit:
                # All live slots cached: one dispatch, zero stream.
                dispatches += 1
                f, f_err, alpha, ticks, t_d = _ooc_round_cached(
                    f, f_err, alpha, y_dev, x_sq, k_diag, cache.data,
                    cache.ticks, w_d, ok_d, slot_d, bh_d, bl_d,
                    budget_left, stamp, **sub_kw)
                cache = CacheState(cache.data, cache.keys, ticks)
                round_hits = live
                cached_rounds += 1
                t = int(np.asarray(t_d))
            else:
                # Stream round: host-gather the working-set rows, run
                # the subproblem, then fold over double-buffered tiles.
                w_np = np.clip(np.asarray(w_d), 0, n - 1)
                # Fancy row indexing reads exactly q rows from host X
                # (ndarray and memmap alike — this plus _tile_host are
                # the only reads of X's bulk).
                qx = jax.device_put(
                    jnp.asarray(np.ascontiguousarray(
                        np.asarray(x[w_np], np.float32)), dtype),
                    device)
                dispatches += 1
                a_w, coef, t_d, qsq = _ooc_subproblem(
                    qx, w_d, ok_d, f, f_err, alpha, y_dev, x_sq, k_diag,
                    bh_d, bl_d, budget_left, **sub_kw)
                # Double-buffered tile stream: issue tile i+1's async
                # H2D put BEFORE dispatching tile i's fold so the DMA
                # overlaps the matmul (the two-slot tile pool — all
                # tiles share one shape, so the allocator recycles the
                # freed slots). Each fold consumes its slice of the
                # carried gradient and returns the folded slice — the
                # accumulate stays fused with the matmul, which is
                # what keeps the trajectory bit-identical to the
                # in-core engine.
                f_tiles = []
                err_tiles = [] if f_err is not None else None
                dots = []
                nxt = _put_tile(x, 0, tile, n, d, dtype, device)
                for i in range(tiles):
                    cur, nxt = nxt, (
                        _put_tile(x, (i + 1) * tile, tile, n, d,
                                  dtype, device)
                        if i + 1 < tiles else None)
                    dispatches += 1
                    s = i * tile
                    ft, et, dots_i = ooc_fold_tile(
                        cur, xsq_tiles[i], f[s:s + tile],
                        f_err[s:s + tile] if f_err is not None else None,
                        qx, qsq, coef, kp=kp, want_dots=use_cache,
                        compensated=f_err is not None)
                    f_tiles.append(ft)
                    if err_tiles is not None:
                        err_tiles.append(et)
                    if use_cache:
                        dots.append(dots_i)
                # Tile-stream bytes only (the q*d working-set gather is
                # separate, small, and not part of the stream) — keeps
                # this stat and the solve.ooc_tile_bytes_total registry
                # counter the same sum.
                round_tiles = tiles
                tiles_streamed += tiles
                bytes_h2d += tiles * tile_bytes
                dispatches += 1
                if use_cache:
                    (f, f_err, alpha, data, keys, ticks,
                     stats_d) = _ooc_apply_cached(
                        tuple(f_tiles),
                        tuple(err_tiles) if err_tiles is not None
                        else None,
                        alpha, cache.data, cache.keys, cache.ticks,
                        w_d, ok_d, a_w, tuple(dots), stamp)
                    cache = CacheState(data, keys, ticks)
                    keys_arg = keys
                    stats_np = np.asarray(stats_d)
                    round_hits = int(stats_np[0])
                    round_evicts = int(stats_np[1])
                else:
                    f, f_err, alpha = _ooc_apply(
                        tuple(f_tiles),
                        tuple(err_tiles) if err_tiles is not None
                        else None,
                        alpha, w_d, ok_d, a_w)
                t = int(np.asarray(t_d))
            pairs += t
            rounds += 1
            if use_cache:
                cache_lookups += live
                cache_hits += round_hits
                cache_evictions += round_evicts
            round_dt = time.perf_counter() - t0
            train_seconds += round_dt
        finally:
            _sp.__exit__(None, None, None)

        t_obs0 = time.perf_counter()
        # The chunk record's device_seconds is EXACTLY the round time
        # train_seconds accumulated — the bench runlog reconciliation
        # (<= 1%) depends on the two being the same sum.
        obs.chunk(pairs=pairs, b_hi=b_hi, b_lo=b_lo,
                  device_seconds=round_dt,
                  dispatch=dispatches, tiles=round_tiles,
                  cached_round=bool(all_hit), cache_hits=round_hits)
        if obs.live:
            c_tiles.add(round_tiles)
            c_bytes.add(tile_bytes * round_tiles)
            if use_cache:
                c_hits.add(round_hits)
                c_looks.add(live)
                c_evict.add(round_evicts)
                if all_hit:
                    c_saved.add(1)
        abort = False
        if callback is not None:
            state = OocState(alpha, f, b_hi, b_lo, pairs, rounds,
                             cache_hits)
            abort = bool(callback(pairs, b_hi, b_lo, state))
        if config.check_numerics:
            from dpsvm_tpu.solver.smo import assert_finite_state
            assert_finite_state(OocState(alpha, f, b_hi, b_lo, pairs,
                                         rounds, cache_hits),
                                pairs, "ooc")
        if ckpt.due(pairs) or (abort and ckpt.active):
            # Round-boundary checkpoint, gated BEFORE any np.asarray
            # materialization (the smo.py discipline). The v2 payload
            # carries the RAW f plus the f_err lanes — not the
            # effective f - f_err the in-core v1 writers save —
            # because the compensated resume must continue the exact
            # Kahan accumulation bits, not restart the residual.
            ckpt.save(pairs, np.asarray(alpha)[:n], np.asarray(f)[:n],
                      b_hi, b_lo, force=True,
                      f_err=(np.asarray(f_err)[:n]
                             if f_err is not None else None),
                      rounds=rounds)
        if config.verbose:
            print(f"[ooc] round={rounds} pairs={pairs} "
                  f"gap={b_lo - b_hi:.6f} tiles={round_tiles} "
                  f"hits={round_hits}")
        phase_seconds["observe"] += time.perf_counter() - t_obs0
        if abort:
            break

    t_fin0 = time.perf_counter()
    alpha_np = np.asarray(alpha)[:n]
    f_eff = f if f_err is None else f - f_err
    f_final = np.asarray(f_eff)[:n]
    if not converged:
        b_hi, b_lo, converged = refresh_extrema_host(
            f_final, alpha_np, y_np, c, config.epsilon,
            rule=config.selection)
    phase_seconds["solve"] = train_seconds
    phase_seconds["finalize"] = time.perf_counter() - t_fin0
    phase_seconds = {k: round(v, 6) for k, v in phase_seconds.items()}
    hit_rate = (cache_hits / cache_lookups) if cache_lookups else 0.0
    stats = {
        "f": f_final,
        "outer_rounds": rounds,
        "ooc": True,
        "ooc_tile_rows": tile,
        "tiles_streamed": tiles_streamed,
        "tile_bytes_h2d": bytes_h2d,
        "cached_rounds": cached_rounds,
        "cache_hits": cache_hits,
        "cache_lookups": cache_lookups,
        "cache_hit_rate": hit_rate,
        "cache_evictions": cache_evictions,
        "phase_seconds": phase_seconds,
    }
    if resumed_from is not None:
        stats["resumed_from"] = resumed_from
        # The block cache is never checkpointed: a resumed cache-on
        # run restarted it cold (exact, but the first post-resume
        # rounds re-stream what it held — and all-hit round placement
        # differs from the uninterrupted run's).
        stats["cache_cold_restart"] = bool(use_cache)
    if obs.live:
        stats["obs_run_id"] = obs.run_id
        stats["obs_runlog"] = obs.path
    obs.finish(iterations=pairs, converged=bool(converged),
               train_seconds=round(train_seconds, 6),
               dispatches=dispatches, b_hi=b_hi, b_lo=b_lo,
               n_sv=int(np.count_nonzero(alpha_np > 0)),
               tiles_streamed=tiles_streamed,
               tile_bytes_h2d=bytes_h2d,
               cached_rounds=cached_rounds,
               cache_hits=cache_hits, cache_lookups=cache_lookups,
               cache_hit_rate=round(hit_rate, 6),
               cache_evictions=cache_evictions,
               phase_seconds=phase_seconds)
    return SolveResult(
        alpha=alpha_np,
        b=float((b_lo + b_hi) / 2.0),
        b_hi=b_hi,
        b_lo=b_lo,
        iterations=pairs,
        converged=converged,
        train_seconds=train_seconds,
        dispatches=dispatches,
        stats=stats,
    )
