"""Out-of-core block-engine driver: train with X resident on the HOST.

Every other engine in this repo assumes the full (n, d) training matrix
fits in HBM, which caps trainable n at a few million rows per chip.
The reference scaled past device memory with its cache.cu LRU of kernel
dot rows (SVMlight's decomposition + kernel caching, Joachims 1999;
ThunderSVM's batched working-set rounds are the modern proof the same
storage hierarchy amortizes). This driver is that regime re-derived for
the TPU memory model:

* X stays in host memory — a NumPy array or an np.memmap — and is never
  fully materialized on device. Device-resident state is the O(n)
  solver vectors (f, alpha, y, x_sq, k_diag), a static-shape pool of
  (tile_rows, d) X tiles, and optionally the (L, n) block cache.
* Each outer round runs the SAME algebra as the in-core block engine
  (solver/block.py): selection over the device-resident gradient, a
  (q, q) Gram block, the shared subproblem (block.dispatch_subproblem),
  and the fold f += coef @ K(W, :). Only the fold's geometry changes:
  it streams over tiles with DOUBLE BUFFERING — tile t+1's async
  host->HBM ``device_put`` is issued before tile t's partial-fold
  matmul dispatch, so the H2D DMA overlaps the MXU work instead of
  serializing with it (ops/ooc.ooc_fold_tile).
* On top of the tile pool, ``ooc_cache_lines`` extends the
  solver/cache.py discipline (static-shape data/keys/ticks arrays,
  scatter-refresh LRU — cache.refresh_rows) to whole working sets: an
  (L, n) HBM cache of hot kernel DOT rows keyed by training-row index.
  A round whose entire live working set hits reads its Gram block AND
  its fold rows straight from the cache — no host gather, no tile
  stream, no recompute. Near convergence the selection concentrates on
  a stable set of support vectors, so all-hit rounds dominate exactly
  when rounds are cheapest to skip.

The host drives one round per iteration (the stream must be fed from
host memory, so a fully on-device while_loop is impossible by
construction — same reason the reference's loop was host-driven). The
trajectory is bit-identical to the in-core block engine's on shapes
where both fit: selection, subproblem and fold all reduce over the
same axes in the same order (tests/test_ooc.py pins exact equality,
including a memmap-backed X leg).

Two stream geometries ride on top of the base round (ISSUE 19):

* SHRUNKEN stream (config.ooc_shrink / active_set_size with ooc) —
  Joachims' SVMlight shrinking re-derived for a streamed fold. A
  shrink CYCLE opens with one m-select over the full problem (m =
  active_set_size, or auto-sized): its extrema are the exact global
  KKT gap (the only place convergence is ever decided), and its m
  most-violating rows become a static-shape active view. In-cycle
  rounds select from the view and stream ONLY the tiles it intersects
  — a skipped tile's H2D put and fold dispatch never happen, so its
  gradient slice goes stale by exactly the skipped deltas. Exactness
  via the shardlocal-engine precedent: a periodic full reconstruction
  rebuilds f over all n from alpha (the warmstart one-streamed-pass
  fold — it IS this program), and the endgame demotes permanently to
  the exact full stream when the gap stalls or nears eps, so the
  FINAL model meets the identical convergence criterion.
* MESH stream (solve_ooc_mesh, backend='mesh' + ooc) — each device
  owns a padded row shard's tiles; one host-driven double-buffered
  ``device_put`` per step feeds every device its (tile, d) block, each
  folds its shard locally (zero collectives), and the round joins on
  ONE psum of the (q, 5) working-set scalars inside selection
  (parallel/dist_block.py make_ooc_mesh_programs). The trajectory is
  BITWISE equal to the single-chip ooc one (tests/test_ooc.py pins it
  at 2 devices): each lane's fold is the same fold_tile_body op
  sequence at the same shapes, and the psum gathers exactly one
  nonzero term per slot — exact, not just close.
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import (KernelParams, kernel_diag,
                                   kernel_from_dots, squared_norms)
from dpsvm_tpu.ops.ooc import ooc_fold_tile
from dpsvm_tpu.ops.select import refresh_extrema_host, shrink_view
from dpsvm_tpu.solver.block import (autotune_gate_resolver,
                                    dispatch_subproblem, ooc_shrink_pays,
                                    select_block)
from dpsvm_tpu.solver.cache import (CacheState, init_cache, probe_rows,
                                    refresh_rows)
from dpsvm_tpu.solver.result import SolveResult
from dpsvm_tpu.solver.smo import (_BUDGET_EPS, check_obs_finite,
                                  drain_pending_obs_events, maybe_kahan,
                                  run_with_fault_retry)
from dpsvm_tpu.testing import faults


class OocState(NamedTuple):
    """Host-visible round state handed to callbacks (the chunk-callback
    contract of solve(); MetricsLogger reads .hits on every backend)."""

    alpha: jax.Array
    f: jax.Array
    b_hi: float
    b_lo: float
    pairs: int
    rounds: int
    hits: int


_tile_sq = jax.jit(squared_norms)

# ---- shrunken-stream cycle tuning (ISSUE 19). A cycle's reconstruction
# costs one full streamed pass (ceil(n/tile) tiles), so the cycle must
# run long enough that the per-round tile savings amortize it; 32 rounds
# against the view keeps the amortized overhead a few percent while
# re-deriving the view often enough that it tracks the working set
# (SVMlight re-checks shrinking every ~100 cheap per-pair iterations; an
# ooc ROUND is a q-sized batch, so 32 rounds is the same order of
# progress between re-shrinks).
_SHRINK_CYCLE_ROUNDS = 32
# Endgame demotion: the final model must meet the IDENTICAL convergence
# criterion as the full stream, so shrinking hands over to the exact
# path once the global gap is within 10x of 2*eps (the view would churn
# on near-satisfied rows) or the gap stalls — fails to shrink by >= 5%
# over a cycle — for TWO cycles in a row (the active set stopped
# capturing the true violators — stalling on a stale view burns
# reconstruction passes for nothing). One stalled cycle is not enough
# to demote: hard regions legitimately plateau for a cycle and then
# resume progress, and a premature permanent demotion forfeits the
# whole stream saving; the streak resets on any cycle that makes the
# cut.
_SHRINK_DEMOTE_EPS_MULT = 10.0
_SHRINK_STALL_FACTOR = 0.95
_SHRINK_STALL_CYCLES = 2


@partial(jax.jit, static_argnames=("c", "q", "selection"))
def _ooc_select(f, f_err, alpha, y, valid, keys, c, q: int,
                selection: str):
    """One selection pass + (when the cache is live) the batched cache
    probe, fused into a single dispatch so the host learns everything
    it needs to route the round — all-hit vs stream — from one pull."""
    f_cur = f if f_err is None else f - f_err
    w, slot_ok, b_hi, b_lo = select_block(f_cur, alpha, y, c, q,
                                          valid=valid, rule=selection)
    if keys is None:
        hit = jnp.zeros((q,), bool)
        hit_slot = jnp.zeros((q,), jnp.int32)
    else:
        hit, hit_slot = probe_rows(keys, w, slot_ok)
    return w, slot_ok, b_hi, b_lo, hit, hit_slot


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau",
                                   "inner_iters", "inner_impl",
                                   "interpret", "selection",
                                   "pair_batch"))
def _ooc_subproblem(qx, w, slot_ok, f, f_err, alpha, y, x_sq, k_diag,
                    b_hi, b_lo, budget_left, kp: KernelParams, c,
                    eps: float, tau: float, inner_iters: int,
                    inner_impl: str, interpret: bool, selection: str,
                    pair_batch: int):
    """Gram block + subproblem for a STREAM round (rows freshly
    gathered host-side). Identical algebra to block._round_core's
    gather/gram/subproblem stages; returns (a_w, coef, t, qsq)."""
    f_cur = f if f_err is None else f - f_err
    gap_open = b_lo > b_hi + 2.0 * eps
    qsq = jnp.take(x_sq, w)
    kd_w = jnp.take(k_diag, w)
    a_w0 = jnp.take(alpha, w)
    y_w = jnp.take(y, w)
    f_w0 = jnp.take(f_cur, w)
    dots_w = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
    kb_w = kernel_from_dots(dots_w, qsq, qsq, kp)
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    a_w, coef, t = dispatch_subproblem(
        kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
        inner_impl, interpret, selection, pair_batch)
    return a_w, coef, t, qsq


def _apply_core(f_tiles, err_tiles, alpha, w, slot_ok, a_w):
    """Shared round tail: reassemble the full gradient from the folded
    tiles (pure data movement — the accumulate itself happened inside
    ooc_fold_tile, fused with the matmul exactly as the in-core round
    fuses it) and scatter alpha."""
    f = jnp.concatenate(f_tiles) if len(f_tiles) > 1 else f_tiles[0]
    f_err = None
    if err_tiles is not None:
        f_err = (jnp.concatenate(err_tiles)
                 if len(err_tiles) > 1 else err_tiles[0])
    n_pad = alpha.shape[0]
    safe_w = jnp.where(slot_ok, w, jnp.int32(n_pad))
    alpha = alpha.at[safe_w].set(jnp.where(slot_ok, a_w, 0.0),
                                 mode="drop")
    return f, f_err, alpha


@partial(jax.jit, donate_argnames=("alpha",))
def _ooc_apply(f_tiles, err_tiles, alpha, w, slot_ok, a_w):
    """Cache-off round tail. The alpha carry is donated (the
    run_chunk_block_donated discipline); the old f buffer died when
    its last tile slice was read."""
    return _apply_core(f_tiles, err_tiles, alpha, w, slot_ok, a_w)


@partial(jax.jit,
         donate_argnames=("alpha", "data", "keys", "ticks"))
def _ooc_apply_cached(f_tiles, err_tiles, alpha, data, keys, ticks, w,
                      slot_ok, a_w, dots, stamp):
    """Stream-round tail with the block cache live: reassemble +
    scatter + scatter-refresh of the freshly streamed dot rows into
    the LRU (solver/cache.refresh_rows). Returns the counters as one
    packed (2,) int32 pull: (n_hits, n_evictions)."""
    f, f_err, alpha = _apply_core(f_tiles, err_tiles, alpha, w,
                                  slot_ok, a_w)
    dots_full = (jnp.concatenate(dots, axis=1)
                 if len(dots) > 1 else dots[0])  # (q, n_pad)
    cache, n_hits, n_evict = refresh_rows(
        CacheState(data, keys, ticks), w, slot_ok, dots_full, stamp)
    return (f, f_err, alpha, cache.data, cache.keys, cache.ticks,
            jnp.stack([n_hits, n_evict]))


@partial(jax.jit,
         donate_argnames=("f", "f_err", "alpha", "ticks"),
         static_argnames=("kp", "c", "eps", "tau", "inner_iters",
                          "inner_impl", "interpret", "selection",
                          "pair_batch"))
def _ooc_round_cached(f, f_err, alpha, y, x_sq, k_diag, data, ticks,
                      w, slot_ok, hit_slot, b_hi, b_lo, budget_left,
                      stamp, kp: KernelParams, c, eps: float, tau: float,
                      inner_iters: int, inner_impl: str, interpret: bool,
                      selection: str, pair_batch: int):
    """ONE complete all-hit round in a single dispatch: Gram block and
    fold rows both read from the cache — the stream and the recompute
    are both skipped, which is the whole point of the block cache."""
    f_cur = f if f_err is None else f - f_err
    gap_open = b_lo > b_hi + 2.0 * eps
    qsq = jnp.take(x_sq, w)
    kd_w = jnp.take(k_diag, w)
    dots_w = jnp.take(data, hit_slot, axis=0)  # (q, n_pad) dot rows
    kb_w = kernel_from_dots(jnp.take(dots_w, w, axis=1), qsq, qsq, kp)
    a_w0 = jnp.take(alpha, w)
    y_w = jnp.take(y, w)
    f_w0 = jnp.take(f_cur, w)
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    a_w, coef, t = dispatch_subproblem(
        kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
        inner_impl, interpret, selection, pair_batch)
    k_rows = kernel_from_dots(dots_w, x_sq, qsq, kp)  # (q, n_pad)
    f, f_err = maybe_kahan(f, f_err, coef @ k_rows)
    n_pad = alpha.shape[0]
    safe_w = jnp.where(slot_ok, w, jnp.int32(n_pad))
    alpha = alpha.at[safe_w].set(jnp.where(slot_ok, a_w, 0.0),
                                 mode="drop")
    lines = ticks.shape[0]
    safe_slot = jnp.where(slot_ok, hit_slot, jnp.int32(lines))
    ticks = ticks.at[safe_slot].set(stamp, mode="drop")
    return f, f_err, alpha, ticks, t


def solve_ooc(
    x,
    y,
    config: SVMConfig,
    callback=None,
    device: Optional[jax.Device] = None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    alpha_init=None,
    f_init=None,
    pad_to: Optional[int] = None,
    warm_start=None,
) -> SolveResult:
    """Train binary C-SVC with host-resident X (config.ooc). Same
    result contract as solver/smo.solve; `x` may be any array-like the
    host can slice row-blocks from — np.ndarray or np.memmap.

    Checkpoint/resume (ISSUE 13): with ``checkpoint_path`` and
    ``config.checkpoint_every > 0``, the FULL driver carry — alpha,
    raw f AND the compensated f_err lanes, pair/round counters,
    extrema — is written atomically at round boundaries as a
    FORMAT_VERSION 2 checkpoint (utils/checkpoint.py). ``resume=True``
    restores it; because raw f and f_err are both restored, a cache-off
    resume reproduces the uninterrupted trajectory BITWISE from the
    restore point (tests/test_ooc.py pins it, memmap and padded tails
    included). The block kernel-row cache is deliberately NOT
    checkpointed — an (L, n) HBM cache would dwarf the O(n) state it
    rides on — so a resumed run restarts it cold (exact, just
    re-streamed; ``stats['cache_cold_restart']`` records it), which
    also means cache-ON resumes are exact-but-not-bitwise (a cold
    cache changes which rounds take the all-hit path).

    Fault retries ride the shared run_with_fault_retry machinery and
    resume from the last checkpoint this run wrote (else restart from
    scratch) — host-scale ooc runs are exactly the multi-hour jobs
    that get preempted.

    `warm_start` (solver/warmstart.py, ISSUE 18): the seed is repaired
    and its gradient rebuilt by the SAME streamed tile fold this
    driver's rounds dispatch (one extra pass over host X, double-
    buffered), then delegated to alpha_init/f_init. An all-zero
    repaired seed routes bit-identically through the cold path; a
    checkpoint resume, when present, still takes precedence."""
    from dpsvm_tpu.solver.smo import _precision_ctx

    if warm_start is not None:
        if alpha_init is not None or f_init is not None:
            raise ValueError(
                "pass either warm_start or alpha_init/f_init, not both")
        from dpsvm_tpu.solver.warmstart import prepare_warm_start

        a0, f0, wstats = prepare_warm_start(x, y, config, warm_start,
                                            device=device)
        res = solve_ooc(x, y, config, callback=callback, device=device,
                        checkpoint_path=checkpoint_path, resume=resume,
                        alpha_init=a0, f_init=f0, pad_to=pad_to)
        res.stats["warm_start"] = wstats
        return res

    def attempt(cfg_k, res_k, _k):
        return _solve_ooc_impl(x, y, cfg_k, callback, device,
                               checkpoint_path, res_k,
                               alpha_init, f_init, pad_to)

    with _precision_ctx(config):
        return run_with_fault_retry(config, checkpoint_path, resume,
                                    attempt)


def _tile_host(x, s: int, t: int, n: int, d: int):
    """Rows [s, s+t) of host X as a float32 (t, d) block, zero-padded
    past n. Slicing + np.asarray keeps memmaps lazy until here — this
    is the ONLY place training reads X's bulk."""
    blk = np.asarray(x[s:min(s + t, n)], np.float32)
    if blk.shape[0] < t:
        pad = np.zeros((t, d), np.float32)
        pad[:blk.shape[0]] = blk
        return pad
    return np.ascontiguousarray(blk)


def _put_tile(x, s: int, t: int, n: int, d: int, dtype, device):
    """One round-stream tile's host->HBM upload, with the
    ``ooc_tile_put`` fault seam in front: an injected transient here
    models the H2D DMA faulting mid-stream (the tunneled-runtime
    preemption shape), which the retry wrapper recovers from the last
    checkpoint."""
    faults.device_fault("ooc_tile_put", f"tile rows [{s}, {s + t})")
    return jax.device_put(jnp.asarray(_tile_host(x, s, t, n, d), dtype),
                          device)


def _solve_ooc_impl(x, y, config: SVMConfig, callback, device,
                    checkpoint_path, resume, alpha_init, f_init,
                    pad_to) -> SolveResult:
    t_entry = time.perf_counter()
    y_np = np.asarray(y, np.int32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    if config.dtype == "bfloat16":
        from dpsvm_tpu.ops.kernels import warn_if_bf16_degrades
        warn_if_bf16_degrades(np.asarray(x[:min(n, 4096)]), config)
    if device is None:
        device = jax.devices()[0]
    interpret = device.platform != "tpu"
    inner_impl = "xla" if interpret else "pallas"

    tile = min(int(config.ooc_tile_rows), max(n, int(pad_to or 0)))
    n_min = max(n, min(pad_to, 2 ** 31) if pad_to else n)
    n_pad = -(-n_min // tile) * tile
    tiles = n_pad // tile
    tile_bytes = tile * d * (2 if config.dtype == "bfloat16" else 4)

    gran = 2  # mvp / second_order only (config validates)
    q = max(gran, min(config.working_set_size, n_pad))
    q -= q % gran
    inner = config.inner_iters or 2 * q
    lines = int(config.ooc_cache_lines)
    use_cache = lines > 0

    # ---- shrunken-stream resolution (ISSUE 19). active_set_size is an
    # explicit request (config validates it against ooc_shrink=False)
    # and also sizes the view; ooc_shrink=True asks for the auto-sized
    # view; ooc_shrink=None consults the autotune gate with the
    # hand-measured default (solver/block.py ooc_shrink_pays — the CPU
    # seed profile resolves OFF; only an authoritative real-TPU probe
    # verdict turns it on, the ISSUE 14 honesty rule).
    _auto_gate, _autotune_embed = autotune_gate_resolver(device)
    if config.active_set_size:
        use_shrink = True
        shrink_m = int(config.active_set_size)
    elif config.ooc_shrink is not None:
        use_shrink = bool(config.ooc_shrink)
        shrink_m = 0
    else:
        use_shrink = bool(_auto_gate("ooc_shrink",
                                     ooc_shrink_pays(n, d)))
        shrink_m = 0
    if use_shrink:
        if shrink_m <= 0:
            # Auto view: big enough that several rounds' working sets
            # fit inside one view, small enough to actually skip tiles.
            shrink_m = max(4 * q, n_pad // 8)
        shrink_m = max(q, min(shrink_m, n_pad))
        shrink_m -= shrink_m % gran

    # ---- device-side O(n) state. y/valid pad exactly as the in-core
    # driver does (solver/smo.py _solve_impl) so selections see the
    # identical masked problem.
    if n_pad == n:
        y_p = y_np.astype(np.float32)
        valid_dev = None
    else:
        y_p = np.ones((n_pad,), np.float32)
        y_p[:n] = y_np
        valid_np = np.zeros((n_pad,), bool)
        valid_np[:n] = True
        valid_dev = jax.device_put(jnp.asarray(valid_np), device)
    y_dev = jax.device_put(jnp.asarray(y_p, jnp.float32), device)

    # ---- setup stream: ONE pass over host X computes the squared
    # norms tile-by-tile on device (each row's reduction is identical
    # to the in-core full-matrix einsum, so x_sq is bit-identical).
    # The per-tile norm arrays are kept — the round stream feeds them
    # back to ooc_fold_tile so the per-tile program never touches an
    # (n,)-sized operand.
    from dpsvm_tpu.obs import run_obs

    obs = run_obs("solve", config,
                  meta={"n": n, "d": d, "n_pad": n_pad,
                        "engine": config.engine, "kernel": config.kernel,
                        "selection": config.selection, "ooc": True,
                        "ooc_tile_rows": tile, "ooc_tiles": tiles,
                        "ooc_cache_lines": lines,
                        "ooc_shrink": use_shrink,
                        "shrink_m": shrink_m})
    drain_pending_obs_events(obs)

    with obs.span("solver/ooc_setup_stream"):
        xsq_tiles = []
        for i in range(tiles):
            xt = jax.device_put(
                jnp.asarray(_tile_host(x, i * tile, tile, n, d), dtype),
                device)
            xsq_tiles.append(_tile_sq(xt))
        x_sq = jnp.concatenate(xsq_tiles) if tiles > 1 else xsq_tiles[0]
        k_diag = jax.jit(kernel_diag,
                         static_argnames="params")(x_sq, params=kp)

    f = jnp.asarray(-y_p, jnp.float32)
    alpha = jnp.zeros((n_pad,), jnp.float32)
    if alpha_init is not None:
        a_p = np.zeros((n_pad,), np.float32)
        a_p[:n] = np.asarray(alpha_init, np.float32)
        alpha = jnp.asarray(a_p)
    if f_init is not None:
        f_p = np.asarray(-y_p, np.float32)
        f_p[:n] = np.asarray(f_init, np.float32)
        f = jnp.asarray(f_p)
    f = jax.device_put(f, device)
    alpha = jax.device_put(alpha, device)
    f_err = jnp.zeros_like(f) if config.compensated else None

    # ---- checkpoint resume (ISSUE 13): restore the FULL v2 carry —
    # alpha, raw f and the compensated f_err lanes, pair/round
    # counters. Padded lanes re-initialize exactly as a fresh start
    # does (-y_p / 0): they are masked out of every selection, and the
    # padded-tail bit-identity pin proves they never steer the
    # real-row trajectory. A checkpoint resume takes precedence over
    # alpha_init/f_init (the solve() contract).
    start_pairs = 0
    start_rounds = 0
    resumed_from = None
    resume_demoted = False
    resume_gap = None
    resume_stall = 0
    if resume:
        from dpsvm_tpu.utils.checkpoint import resume_state

        st = resume_state(checkpoint_path, config, n)
        if st is not None:
            a_pad = np.zeros((n_pad,), np.float32)
            a_pad[:n] = st.alpha
            f_pad = np.asarray(-y_p, np.float32)
            f_pad[:n] = st.f
            alpha = jax.device_put(jnp.asarray(a_pad), device)
            f = jax.device_put(jnp.asarray(f_pad), device)
            if f_err is not None:
                e_pad = np.zeros((n_pad,), np.float32)
                if st.f_err is not None:
                    # v2 ooc checkpoints carry the raw Kahan residual;
                    # restoring it is what makes the resumed
                    # compensated trajectory BITWISE equal to the
                    # uninterrupted one (v1 files restart it at zero —
                    # exact, but a different rounding path).
                    e_pad[:n] = st.f_err
                f_err = jax.device_put(jnp.asarray(e_pad), device)
            start_pairs = st.iteration
            start_rounds = st.rounds
            resumed_from = st.iteration
            # Shrink carry (ISSUE 19): ooc checkpoints are written at
            # shrink-cycle boundaries only, so the view itself never
            # needs persisting — but the demotion latch (permanent) and
            # the previous cycle-start gap (the stall test's baseline)
            # both steer the next cycle, and restoring them is what
            # keeps a shrinking resume BITWISE on the uninterrupted
            # trajectory (tests/test_ooc.py pins it).
            resume_demoted = bool(st.shrink_demoted)
            resume_gap = st.shrink_gap
            resume_stall = int(st.shrink_stall)
            obs.event("resume", iteration=start_pairs,
                      rounds=start_rounds,
                      format_version=st.format_version,
                      cache_cold_restart=bool(use_cache),
                      shrink_demoted=resume_demoted)

    # The block kernel-row cache restarts COLD on resume (an (L, n)
    # HBM cache is not worth persisting next to the O(n) carry); the
    # first post-resume rounds re-stream what it held.
    cache = init_cache(lines, n_pad) if use_cache else None
    cache = jax.device_put(cache, device) if use_cache else None

    c = config.c_bounds()
    eps_run = _BUDGET_EPS if config.budget_mode else float(config.epsilon)
    max_iter = int(config.max_iter)
    sub_kw = dict(kp=kp, c=c, eps=eps_run, tau=float(config.tau),
                  inner_iters=inner, inner_impl=inner_impl,
                  interpret=interpret, selection=config.selection,
                  pair_batch=int(config.pair_batch))

    jax.block_until_ready((x_sq, k_diag, f, alpha))
    phase_seconds = {"setup": time.perf_counter() - t_entry,
                     "solve": 0.0, "observe": 0.0, "finalize": 0.0}

    from dpsvm_tpu.utils.checkpoint import PeriodicCheckpointer

    ckpt = PeriodicCheckpointer(checkpoint_path, config, start_pairs)
    pairs = start_pairs
    rounds = start_rounds
    dispatches = 0
    tiles_streamed = 0
    bytes_h2d = 0
    cache_hits = 0
    cache_lookups = 0
    cache_evictions = 0
    cached_rounds = 0
    b_hi = float("-inf")
    b_lo = float("inf")
    converged = False
    train_seconds = 0.0
    keys_arg = cache.keys if use_cache else None

    # ---- shrunken-stream cycle state (ISSUE 19). `active_dev` is the
    # device-side view mask while a cycle is open (None between
    # cycles); `stale` flips the first time a round skips a tile, and
    # only a full reconstruction clears it — every exit path
    # reconstructs while stale, so finalize (and any checkpoint) only
    # ever sees an exact gradient.
    shrink_live = use_shrink and not resume_demoted
    shrink_demoted = use_shrink and resume_demoted
    last_cycle_gap = resume_gap
    stall_streak = resume_stall
    active_dev = None
    live_list = []
    cycle_rounds = 0
    stale = False
    shrink_cycles = 0
    reconstructions = 0
    tiles_skipped = 0
    bytes_skipped = 0
    # Tiles actually streamed during shrink-active rounds (cadence
    # reconstructions included — they are the price of the cycle).
    # With tiles_skipped this gives the late-phase byte cut the bench
    # records: (in_cycle + skipped) / in_cycle.
    tiles_in_cycle = 0

    if obs.live:
        c_tiles = obs.registry.counter("solve.ooc_tiles_total")
        c_bytes = obs.registry.counter("solve.ooc_tile_bytes_total")
        c_hits = obs.registry.counter("solve.cache_hits_total")
        c_looks = obs.registry.counter("solve.cache_lookups_total")
        c_evict = obs.registry.counter("solve.cache_evictions_total")
        c_saved = obs.registry.counter("solve.ooc_cached_rounds_total")
        c_skip = obs.registry.counter("solve.ooc_tiles_skipped_total")
        c_recon = obs.registry.counter(
            "solve.shrink_reconstructions_total")

    def _reconstruct(reason: str) -> int:
        """Full-stream rebuild of f from alpha — the warmstart fold
        (solver/warmstart.py warm_f_rebuild IS this program: one
        double-buffered streamed pass over host X), clearing whatever
        staleness the skipped tiles accumulated. The Kahan residual
        restarts at zero (the rebuilt f is exact; there is nothing to
        compensate). Counts its ceil(n/tile) tiles into the stream
        totals and returns the count for the round's chunk record."""
        nonlocal f, f_err, stale, reconstructions, tiles_streamed, \
            bytes_h2d
        from dpsvm_tpu.solver.warmstart import warm_f_rebuild

        alpha_h = np.asarray(alpha)[:n]
        f_np = warm_f_rebuild(x, y_np, alpha_h, kp, device=device,
                              tile_rows=tile)
        f_pad = (-y_p).astype(np.float32)
        f_pad[:n] = f_np
        f = jax.device_put(jnp.asarray(f_pad), device)
        if f_err is not None:
            f_err = jax.device_put(jnp.zeros((n_pad,), jnp.float32),
                                   device)
        stale = False
        reconstructions += 1
        # warm_f_rebuild short-circuits (no stream) on an all-zero
        # alpha; only count tiles the pass actually streamed.
        tr = -(-n // tile) if np.any(alpha_h != 0.0) else 0
        tiles_streamed += tr
        bytes_h2d += tr * tile_bytes
        obs.event("shrink_reconstruct", reason=reason, rounds=rounds,
                  pairs=pairs, tiles=tr)
        if obs.live:
            c_recon.add(1)
            c_tiles.add(tr)
            c_bytes.add(tr * tile_bytes)
        return tr

    while True:
        _sp = obs.span("solver/ooc_round")
        _sp.__enter__()
        try:
            t0 = time.perf_counter()
            round_hits = 0
            round_evicts = 0
            round_tiles = 0
            round_skipped = 0
            recon_tiles = 0
            all_hit = False
            t = 0
            recon_only = False

            # ---- shrink cycle start (between cycles): ONE m-select
            # over the FULL problem plays three roles — the exact
            # global stopping test (the only place convergence is ever
            # decided while shrinking; f is never stale here), the
            # endgame demotion decision, and the next active view.
            if shrink_live and active_dev is None:
                dispatches += 1
                faults.device_fault(
                    "dispatch", f"ooc shrink cycle {shrink_cycles + 1}")
                w_m, ok_m, bh_d, bl_d, _, _ = _ooc_select(
                    f, f_err, alpha, y_dev, valid_dev, None,
                    c=c, q=shrink_m, selection=config.selection)
                b_hi = float(np.asarray(bh_d))
                b_lo = float(np.asarray(bl_d))
                b_hi, b_lo = faults.poison_obs(b_hi, b_lo)
                check_obs_finite(b_hi, b_lo, pairs, "ooc")
                converged = not (b_lo > b_hi + 2.0 * eps_run)
                if converged or pairs >= max_iter:
                    round_dt = time.perf_counter() - t0
                    train_seconds += round_dt
                    break
                gap_now = b_lo - b_hi
                demote = None
                if gap_now <= _SHRINK_DEMOTE_EPS_MULT * eps_run:
                    demote = "near_eps"
                else:
                    if (last_cycle_gap is not None and
                            gap_now > _SHRINK_STALL_FACTOR
                            * last_cycle_gap):
                        stall_streak += 1
                        if stall_streak >= _SHRINK_STALL_CYCLES:
                            demote = "stalled"
                    else:
                        stall_streak = 0
                if demote is None:
                    active_np, live_tiles = shrink_view(
                        np.asarray(w_m), np.asarray(ok_m), n, n_pad,
                        tile)
                    if live_tiles.size >= tiles:
                        # The view spans every tile: a cycle would
                        # stream everything anyway and still pay the
                        # reconstruction — pure overhead.
                        demote = "full_view"
                if demote is not None:
                    # Permanent handoff to the exact full-stream path
                    # (resume restores it via the checkpoint's
                    # shrink_demoted latch).
                    shrink_live = False
                    shrink_demoted = True
                    obs.event("shrink_demote", reason=demote,
                              rounds=rounds, pairs=pairs, gap=gap_now)
                else:
                    last_cycle_gap = gap_now
                    active_dev = jax.device_put(jnp.asarray(active_np),
                                                device)
                    live_list = [int(i) for i in live_tiles]
                    cycle_rounds = 0
                    shrink_cycles += 1

            in_cycle = shrink_live and active_dev is not None

            dispatches += 1
            faults.device_fault("dispatch", f"ooc round {rounds + 1}")
            w_d, ok_d, bh_d, bl_d, hit_d, slot_d = _ooc_select(
                f, f_err, alpha, y_dev,
                active_dev if in_cycle else valid_dev, keys_arg,
                c=c, q=q, selection=config.selection)
            b_hi = float(np.asarray(bh_d))
            b_lo = float(np.asarray(bl_d))
            # Non-finite sentinel (free: the extrema are already
            # materialized). A NaN gap would otherwise read as
            # "converged" (NaN comparisons are False) and return a
            # silently corrupt model — the one outcome no fault may
            # produce.
            b_hi, b_lo = faults.poison_obs(b_hi, b_lo)
            check_obs_finite(b_hi, b_lo, pairs, "ooc")
            gap_closed = not (b_lo > b_hi + 2.0 * eps_run)
            if not in_cycle:
                converged = gap_closed
                if converged or pairs >= max_iter:
                    round_dt = time.perf_counter() - t0
                    train_seconds += round_dt
                    break
            else:
                # In-cycle extrema are the ACTIVE VIEW's: they steer
                # the view, never the stopping test (that belongs to
                # the cycle-start full select above).
                converged = False
                if pairs >= max_iter:
                    if stale:
                        recon_tiles += _reconstruct("budget")
                    active_dev = None
                    round_dt = time.perf_counter() - t0
                    train_seconds += round_dt
                    break
                if gap_closed:
                    # The view is solved to tolerance: rebuild the
                    # exact gradient and open the next cycle from it.
                    if stale:
                        recon_tiles += _reconstruct("view_converged")
                    active_dev = None
                    recon_only = True

            in_cycle = in_cycle and not recon_only
            if not recon_only:
                ok_np = np.asarray(ok_d)
                live = int(ok_np.sum())
                hit_np = np.asarray(hit_d)
                all_hit = use_cache and live > 0 \
                    and bool(np.all(hit_np[ok_np]))
                budget_left = jnp.int32(max_iter - pairs)
                stamp = jnp.int32(rounds + 1)
                if all_hit:
                    # All live slots cached: one dispatch, zero stream.
                    # Cached rows are full (q, n_pad) width, so this
                    # round is exact over EVERY lane even mid-cycle —
                    # stale lanes advance by the exact delta and stay
                    # consistently stale by only the skipped rounds.
                    dispatches += 1
                    f, f_err, alpha, ticks, t_d = _ooc_round_cached(
                        f, f_err, alpha, y_dev, x_sq, k_diag, cache.data,
                        cache.ticks, w_d, ok_d, slot_d, bh_d, bl_d,
                        budget_left, stamp, **sub_kw)
                    cache = CacheState(cache.data, cache.keys, ticks)
                    round_hits = live
                    cached_rounds += 1
                    t = int(np.asarray(t_d))
                else:
                    # Stream round: host-gather the working-set rows,
                    # run the subproblem, then fold over
                    # double-buffered tiles.
                    w_np = np.clip(np.asarray(w_d), 0, n - 1)
                    # Fancy row indexing reads exactly q rows from host
                    # X (ndarray and memmap alike — this plus
                    # _tile_host are the only reads of X's bulk).
                    qx = jax.device_put(
                        jnp.asarray(np.ascontiguousarray(
                            np.asarray(x[w_np], np.float32)), dtype),
                        device)
                    dispatches += 1
                    a_w, coef, t_d, qsq = _ooc_subproblem(
                        qx, w_d, ok_d, f, f_err, alpha, y_dev, x_sq,
                        k_diag, bh_d, bl_d, budget_left, **sub_kw)
                    # Double-buffered tile stream: issue the next live
                    # tile's async H2D put BEFORE dispatching this
                    # one's fold so the DMA overlaps the matmul (the
                    # two-slot tile pool — all tiles share one shape,
                    # so the allocator recycles the freed slots). Each
                    # fold consumes its slice of the carried gradient
                    # and returns the folded slice — the accumulate
                    # stays fused with the matmul, which is what keeps
                    # the trajectory bit-identical to the in-core
                    # engine. A SHRUNKEN round walks only the active
                    # view's tiles (the skip is a dispatch that never
                    # happens, not a masked kernel); a skipped tile's
                    # f slice passes through below untouched, and the
                    # cache refresh is skipped too — a partial dot row
                    # would poison the full-width LRU.
                    order = live_list if in_cycle else list(range(tiles))
                    want_dots = use_cache and not in_cycle
                    f_tiles = [None] * tiles
                    err_tiles = ([None] * tiles
                                 if f_err is not None else None)
                    dots = []
                    nxt = _put_tile(x, order[0] * tile, tile, n, d,
                                    dtype, device)
                    for oi, i in enumerate(order):
                        cur, nxt = nxt, (
                            _put_tile(x, order[oi + 1] * tile, tile, n,
                                      d, dtype, device)
                            if oi + 1 < len(order) else None)
                        dispatches += 1
                        s = i * tile
                        ft, et, dots_i = ooc_fold_tile(
                            cur, xsq_tiles[i], f[s:s + tile],
                            f_err[s:s + tile] if f_err is not None
                            else None,
                            qx, qsq, coef, kp=kp, want_dots=want_dots,
                            compensated=f_err is not None)
                        f_tiles[i] = ft
                        if err_tiles is not None:
                            err_tiles[i] = et
                        if want_dots:
                            dots.append(dots_i)
                    for i in range(tiles):
                        if f_tiles[i] is None:
                            s = i * tile
                            f_tiles[i] = f[s:s + tile]
                            if err_tiles is not None:
                                err_tiles[i] = f_err[s:s + tile]
                    # Tile-stream bytes only (the q*d working-set
                    # gather is separate, small, and not part of the
                    # stream) — keeps this stat and the
                    # solve.ooc_tile_bytes_total registry counter the
                    # same sum.
                    round_tiles = len(order)
                    round_skipped = tiles - len(order)
                    if round_skipped:
                        stale = True
                        tiles_skipped += round_skipped
                        bytes_skipped += round_skipped * tile_bytes
                    tiles_streamed += round_tiles
                    bytes_h2d += round_tiles * tile_bytes
                    dispatches += 1
                    if want_dots:
                        (f, f_err, alpha, data, keys, ticks,
                         stats_d) = _ooc_apply_cached(
                            tuple(f_tiles),
                            tuple(err_tiles) if err_tiles is not None
                            else None,
                            alpha, cache.data, cache.keys, cache.ticks,
                            w_d, ok_d, a_w, tuple(dots), stamp)
                        cache = CacheState(data, keys, ticks)
                        keys_arg = keys
                        stats_np = np.asarray(stats_d)
                        round_hits = int(stats_np[0])
                        round_evicts = int(stats_np[1])
                    else:
                        f, f_err, alpha = _ooc_apply(
                            tuple(f_tiles),
                            tuple(err_tiles) if err_tiles is not None
                            else None,
                            alpha, w_d, ok_d, a_w)
                    t = int(np.asarray(t_d))
                pairs += t
                rounds += 1
                if use_cache:
                    cache_lookups += live
                    cache_hits += round_hits
                    cache_evictions += round_evicts
                if in_cycle:
                    cycle_rounds += 1
                    if cycle_rounds >= _SHRINK_CYCLE_ROUNDS:
                        # Re-shrink cadence: close the cycle so the
                        # next round re-derives the view from an exact
                        # gradient (and so a checkpoint can land).
                        if stale:
                            recon_tiles += _reconstruct("cadence")
                        active_dev = None
            round_dt = time.perf_counter() - t0
            train_seconds += round_dt
        finally:
            _sp.__exit__(None, None, None)

        t_obs0 = time.perf_counter()
        # The chunk record's device_seconds is EXACTLY the round time
        # train_seconds accumulated — the bench runlog reconciliation
        # (<= 1%) depends on the two being the same sum.
        if in_cycle:
            tiles_in_cycle += round_tiles + recon_tiles
        obs.chunk(pairs=pairs, b_hi=b_hi, b_lo=b_lo,
                  device_seconds=round_dt,
                  dispatch=dispatches, tiles=round_tiles + recon_tiles,
                  cached_round=bool(all_hit), cache_hits=round_hits,
                  tiles_skipped=round_skipped,
                  shrink_active=bool(in_cycle))
        if obs.live:
            c_tiles.add(round_tiles)
            c_bytes.add(tile_bytes * round_tiles)
            if round_skipped:
                c_skip.add(round_skipped)
            if use_cache:
                c_hits.add(round_hits)
                c_looks.add(live)
                c_evict.add(round_evicts)
                if all_hit:
                    c_saved.add(1)
        abort = False
        if callback is not None:
            state = OocState(alpha, f, b_hi, b_lo, pairs, rounds,
                             cache_hits)
            abort = bool(callback(pairs, b_hi, b_lo, state))
        if config.check_numerics:
            from dpsvm_tpu.solver.smo import assert_finite_state
            assert_finite_state(OocState(alpha, f, b_hi, b_lo, pairs,
                                         rounds, cache_hits),
                                pairs, "ooc")
        if abort and shrink_live and active_dev is not None:
            # Abort mid-cycle: leave nothing stale behind — the
            # checkpoint below and finalize both need the exact f.
            if stale:
                _reconstruct("abort")
            active_dev = None
        if (ckpt.due(pairs) or (abort and ckpt.active)) \
                and (not shrink_live or active_dev is None):
            # Round-boundary checkpoint, gated BEFORE any np.asarray
            # materialization (the smo.py discipline). The v2 payload
            # carries the RAW f plus the f_err lanes — not the
            # effective f - f_err the in-core v1 writers save —
            # because the compensated resume must continue the exact
            # Kahan accumulation bits, not restart the residual.
            # While SHRINKING, saves land only at cycle boundaries
            # (mid-cycle f has stale lanes, and the view itself is
            # not persisted): a resume then re-opens the next cycle
            # from exactly the state — f, alpha, demotion latch,
            # previous cycle gap — the uninterrupted run would have,
            # which is what keeps the shrinking resume BITWISE.
            ckpt.save(pairs, np.asarray(alpha)[:n], np.asarray(f)[:n],
                      b_hi, b_lo, force=True,
                      f_err=(np.asarray(f_err)[:n]
                             if f_err is not None else None),
                      rounds=rounds,
                      shrink_demoted=(shrink_demoted if use_shrink
                                      else None),
                      shrink_gap=last_cycle_gap,
                      shrink_stall=(stall_streak if use_shrink
                                    else None))
        if config.verbose:
            print(f"[ooc] round={rounds} pairs={pairs} "
                  f"gap={b_lo - b_hi:.6f} tiles={round_tiles} "
                  f"skip={round_skipped} hits={round_hits}")
        phase_seconds["observe"] += time.perf_counter() - t_obs0
        if abort:
            break

    t_fin0 = time.perf_counter()
    alpha_np = np.asarray(alpha)[:n]
    f_eff = f if f_err is None else f - f_err
    f_final = np.asarray(f_eff)[:n]
    if not converged:
        b_hi, b_lo, converged = refresh_extrema_host(
            f_final, alpha_np, y_np, c, config.epsilon,
            rule=config.selection)
    phase_seconds["solve"] = train_seconds
    phase_seconds["finalize"] = time.perf_counter() - t_fin0
    phase_seconds = {k: round(v, 6) for k, v in phase_seconds.items()}
    hit_rate = (cache_hits / cache_lookups) if cache_lookups else 0.0
    stats = {
        "f": f_final,
        "outer_rounds": rounds,
        "ooc": True,
        "ooc_tile_rows": tile,
        "tiles_streamed": tiles_streamed,
        "tile_bytes_h2d": bytes_h2d,
        "cached_rounds": cached_rounds,
        "cache_hits": cache_hits,
        "cache_lookups": cache_lookups,
        "cache_hit_rate": hit_rate,
        "cache_evictions": cache_evictions,
        "phase_seconds": phase_seconds,
        "ooc_shrink": use_shrink,
    }
    if use_shrink:
        stats.update(
            shrink_m=shrink_m,
            shrink_cycles=shrink_cycles,
            shrink_reconstructions=reconstructions,
            shrink_demoted=shrink_demoted,
            tiles_skipped=tiles_skipped,
            tile_bytes_skipped=bytes_skipped,
            shrink_tiles_in_cycle=tiles_in_cycle,
            shrink_active_fraction=round(min(1.0, shrink_m / max(n, 1)),
                                         6),
        )
    _at = _autotune_embed()
    if _at:
        stats.update(_at)
    if resumed_from is not None:
        stats["resumed_from"] = resumed_from
        # The block cache is never checkpointed: a resumed cache-on
        # run restarted it cold (exact, but the first post-resume
        # rounds re-stream what it held — and all-hit round placement
        # differs from the uninterrupted run's).
        stats["cache_cold_restart"] = bool(use_cache)
    if obs.live:
        stats["obs_run_id"] = obs.run_id
        stats["obs_runlog"] = obs.path
    obs.finish(iterations=pairs, converged=bool(converged),
               train_seconds=round(train_seconds, 6),
               dispatches=dispatches, b_hi=b_hi, b_lo=b_lo,
               n_sv=int(np.count_nonzero(alpha_np > 0)),
               tiles_streamed=tiles_streamed,
               tile_bytes_h2d=bytes_h2d,
               cached_rounds=cached_rounds,
               cache_hits=cache_hits, cache_lookups=cache_lookups,
               cache_hit_rate=round(hit_rate, 6),
               cache_evictions=cache_evictions,
               ooc_shrink=use_shrink,
               shrink_cycles=shrink_cycles,
               shrink_reconstructions=reconstructions,
               shrink_demoted=shrink_demoted,
               shrink_active_fraction=(
                   round(min(1.0, shrink_m / max(n, 1)), 6)
                   if use_shrink else 0.0),
               tiles_skipped=tiles_skipped,
               tile_bytes_skipped=bytes_skipped,
               phase_seconds=phase_seconds)
    return SolveResult(
        alpha=alpha_np,
        b=float((b_lo + b_hi) / 2.0),
        b_hi=b_hi,
        b_lo=b_lo,
        iterations=pairs,
        converged=converged,
        train_seconds=train_seconds,
        dispatches=dispatches,
        stats=stats,
    )


# ---------------------------------------------------------------------------
# Mesh out-of-core stream (ISSUE 19): solve_mesh + config.ooc routes here.
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau",
                                   "inner_iters", "inner_impl",
                                   "interpret", "selection",
                                   "pair_batch"))
def _ooc_mesh_subproblem(qx, slot_ok, scal, b_hi, b_lo, budget_left,
                         kp: KernelParams, c, eps: float, tau: float,
                         inner_iters: int, inner_impl: str,
                         interpret: bool, selection: str,
                         pair_batch: int):
    """Gram block + subproblem for a MESH stream round, replicated.

    The working set's per-row scalars arrive as the select program's
    ONE-psum (q, 5) stack — columns [x_sq, k_diag, alpha, y, f_eff] —
    instead of the single-chip driver's device-side takes, and the rows
    themselves as the replicated host gather qx. Same algebra as
    _ooc_subproblem from there on, so a_w/coef/t are bitwise the
    single-chip round's (dead slots carry psum zeros rather than
    whatever take() read — dispatch_subproblem masks them either way,
    the dist_block bitwise precedent). Returns (a_w, coef, t, qsq)."""
    qsq = scal[:, 0]
    kd_w = scal[:, 1]
    a_w0 = scal[:, 2]
    y_w = scal[:, 3]
    f_w0 = scal[:, 4]
    gap_open = b_lo > b_hi + 2.0 * eps
    dots_w = jnp.dot(qx, qx.T, preferred_element_type=jnp.float32)
    kb_w = kernel_from_dots(dots_w, qsq, qsq, kp)
    limit = jnp.minimum(jnp.int32(inner_iters), budget_left)
    limit = jnp.where(gap_open, limit, 0)
    a_w, coef, t = dispatch_subproblem(
        kb_w, kd_w, slot_ok, a_w0, y_w, f_w0, c, eps, tau, limit,
        inner_impl, interpret, selection, pair_batch)
    return a_w, coef, t, qsq


def _mesh_block_host(x, j: int, tile: int, n: int, d: int, n_loc: int,
                     n_dev: int):
    """Stream step j's (P*tile, d) host block: device k's slice is its
    shard's tile j — global rows [k*n_loc + j*tile, +tile), clipped and
    zero-padded past n (pad rows are inert: zero coef contributions and
    masked out of selection). One assembly feeds ONE sharded device_put
    that lands each device exactly its own tile."""
    blk = np.zeros((n_dev * tile, d), np.float32)
    for k in range(n_dev):
        s = k * n_loc + j * tile
        e = min(s + tile, n)
        if s < e:
            blk[k * tile:k * tile + (e - s)] = np.asarray(
                x[s:e], np.float32)
    return blk


def _put_block(x, j: int, tile: int, n: int, d: int, n_loc: int,
               n_dev: int, dtype, mesh):
    """One mesh stream step's host->HBM upload — the SAME
    ``ooc_tile_put`` fault seam as the single-chip stream sits in
    front, so injected H2D faults exercise the mesh path's
    checkpoint-resume recovery too (tools/faults_smoke.py)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from dpsvm_tpu.parallel.mesh import DATA_AXIS

    faults.device_fault("ooc_tile_put",
                        f"mesh stream step {j} (tile rows/device "
                        f"[{j * tile}, {(j + 1) * tile}))")
    blk = _mesh_block_host(x, j, tile, n, d, n_loc, n_dev)
    return jax.device_put(jnp.asarray(blk, dtype),
                          NamedSharding(mesh, PartitionSpec(DATA_AXIS)))


def solve_ooc_mesh(
    x,
    y,
    config: SVMConfig,
    num_devices: Optional[int] = None,
    mesh=None,
    callback=None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    alpha_init=None,
    f_init=None,
    warm_start=None,
) -> SolveResult:
    """Out-of-core training sharded over the mesh's `data` axis
    (backend='mesh' + config.ooc; solve_mesh routes here).

    Each device owns a padded row shard's tiles: the host drives the
    SAME double-buffered stream as solve_ooc, but every step's
    device_put carries one (P*tile, d) block row-sharded over the mesh
    — each device receives exactly its shard's tile j — and every
    device folds its own rows locally (ZERO collectives in the fold;
    the ``ooc_mesh_fold`` tpulint budget pins it). The round joins on
    ONE (q, 5) psum of the working-set scalars inside selection
    (parallel/dist_block.py make_ooc_mesh_programs), the (q, q)
    subproblem runs replicated, and alpha scatters back owner-local.

    The trajectory is BITWISE equal to the single-chip ooc stream
    (tests/test_ooc.py pins it at 2 devices): each lane's fold is the
    same fold_tile_body op sequence at the same (tile,) shapes, each
    lane updates exactly once per round (cross-tile order is
    irrelevant), and the scalar psum gathers exactly one nonzero f32
    term per slot — exact, not just close.

    Not composed here (loud errors, not silent drops): the block
    kernel-row cache (single-chip HBM structure) and the shrunken
    stream (host bookkeeping over one stream). Checkpoints are the
    same v2 files as the single-chip stream's — gathered to host,
    backend-portable, bitwise on resume — and the driver is
    host-driven single-controller, same as every ooc stream."""
    from dpsvm_tpu.solver.smo import _precision_ctx

    if config.ooc_cache_lines:
        raise ValueError(
            "ooc_cache_lines with backend='mesh' is not implemented: "
            "the (L, n) kernel-row cache is a single-chip HBM "
            "structure — drop ooc_cache_lines, or use backend='single'")
    if config.active_set_size or config.ooc_shrink:
        raise ValueError(
            "the shrunken tile stream (active_set_size / ooc_shrink) "
            "is single-chip: the live-tile skip is host bookkeeping "
            "over one stream — drop it, or use backend='single'")
    if warm_start is not None:
        if alpha_init is not None or f_init is not None:
            raise ValueError(
                "pass either warm_start or alpha_init/f_init, not both")
        from dpsvm_tpu.solver.warmstart import prepare_warm_start

        n_dev = (int(mesh.size) if mesh is not None
                 else int(num_devices or len(jax.devices())))
        a0, f0, wstats = prepare_warm_start(x, y, config, warm_start,
                                            mesh_devices=n_dev)
        res = solve_ooc_mesh(x, y, config, num_devices=num_devices,
                             mesh=mesh, callback=callback,
                             checkpoint_path=checkpoint_path,
                             resume=resume, alpha_init=a0, f_init=f0)
        res.stats["warm_start"] = wstats
        return res

    def attempt(cfg_k, res_k, _k):
        return _solve_ooc_mesh_impl(x, y, cfg_k, num_devices, mesh,
                                    callback, checkpoint_path, res_k,
                                    alpha_init, f_init)

    with _precision_ctx(config):
        return run_with_fault_retry(config, checkpoint_path, resume,
                                    attempt)


def _solve_ooc_mesh_impl(x, y, config: SVMConfig, num_devices, mesh,
                         callback, checkpoint_path, resume, alpha_init,
                         f_init) -> SolveResult:
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    from dpsvm_tpu.parallel.dist_block import make_ooc_mesh_programs
    from dpsvm_tpu.parallel.mesh import DATA_AXIS, make_data_mesh

    t_entry = time.perf_counter()
    y_np = np.asarray(y, np.int32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    if config.dtype == "bfloat16":
        from dpsvm_tpu.ops.kernels import warn_if_bf16_degrades
        warn_if_bf16_degrades(np.asarray(x[:min(n, 4096)]), config)
    if mesh is None:
        mesh = make_data_mesh(num_devices)
    n_dev = int(mesh.size)
    interpret = mesh.devices.flat[0].platform != "tpu"
    inner_impl = "xla" if interpret else "pallas"

    # ---- shard-and-tile geometry: every shard is a whole number of
    # stream tiles (n_loc = tile * ceil(n / (P*tile))), so stream step
    # j moves each device's tile j as one row-sharded block. At P*tile
    # dividing the single-chip n_pad the global pad is IDENTICAL to the
    # single-chip driver's — the bitwise-equality test shape.
    tile = min(int(config.ooc_tile_rows), n)
    n_loc = -(-n // (n_dev * tile)) * tile
    n_pad = n_dev * n_loc
    tiles_loc = n_loc // tile
    tile_bytes = tile * d * (2 if config.dtype == "bfloat16" else 4)

    gran = 2  # mvp / second_order only (config validates)
    # h = q/2 per-side candidates must fit a shard's rows.
    q = max(gran, min(config.working_set_size, 2 * n_loc))
    q -= q % gran
    inner = config.inner_iters or 2 * q

    c = config.c_bounds()
    programs = make_ooc_mesh_programs(mesh, kp, c, q, n_loc, tile,
                                      selection=config.selection,
                                      compensated=config.compensated)

    shard_s = NamedSharding(mesh, PSpec(DATA_AXIS))
    rep_s = NamedSharding(mesh, PSpec())

    y_p = np.ones((n_pad,), np.float32)
    y_p[:n] = y_np
    valid_np = np.zeros((n_pad,), bool)
    valid_np[:n] = True
    y_g = jax.device_put(jnp.asarray(y_p), shard_s)
    valid_g = jax.device_put(jnp.asarray(valid_np), shard_s)

    from dpsvm_tpu.obs import run_obs

    obs = run_obs("solve", config,
                  meta={"n": n, "d": d, "n_pad": n_pad,
                        "engine": config.engine,
                        "kernel": config.kernel,
                        "selection": config.selection, "ooc": True,
                        "ooc_mesh": True, "devices": n_dev,
                        "ooc_tile_rows": tile,
                        "ooc_tiles": tiles_loc * n_dev,
                        "ooc_cache_lines": 0, "ooc_shrink": False,
                        "shrink_m": 0})
    drain_pending_obs_events(obs)

    # ---- setup stream: squared norms computed ON DEVICE per (tile, d)
    # block — the identical jitted reduction shape as the single-chip
    # setup pass, which is what makes x_sq (and everything downstream
    # of it) bit-identical.
    with obs.span("solver/ooc_setup_stream"):
        x_sq = jax.device_put(jnp.zeros((n_pad,), jnp.float32), shard_s)
        nxt = _put_block(x, 0, tile, n, d, n_loc, n_dev, dtype, mesh)
        for j in range(tiles_loc):
            cur, nxt = nxt, (
                _put_block(x, j + 1, tile, n, d, n_loc, n_dev, dtype,
                           mesh)
                if j + 1 < tiles_loc else None)
            x_sq = programs["norms"](cur, x_sq, jnp.int32(j))
        k_diag = jax.jit(kernel_diag,
                         static_argnames="params")(x_sq, params=kp)

    f_np0 = (-y_p).astype(np.float32)
    a_np0 = np.zeros((n_pad,), np.float32)
    if alpha_init is not None:
        a_np0[:n] = np.asarray(alpha_init, np.float32)
    if f_init is not None:
        f_np0[:n] = np.asarray(f_init, np.float32)
    e_np0 = (np.zeros((n_pad,), np.float32)
             if config.compensated else None)

    start_pairs = 0
    start_rounds = 0
    resumed_from = None
    if resume:
        from dpsvm_tpu.utils.checkpoint import resume_state

        st = resume_state(checkpoint_path, config, n)
        if st is not None:
            a_np0 = np.zeros((n_pad,), np.float32)
            a_np0[:n] = st.alpha
            f_np0 = (-y_p).astype(np.float32)
            f_np0[:n] = st.f
            if e_np0 is not None and st.f_err is not None:
                # v2 carries the raw Kahan residual — restoring it is
                # what keeps the compensated mesh resume BITWISE.
                e_np0[:n] = st.f_err
            start_pairs = st.iteration
            start_rounds = st.rounds
            resumed_from = st.iteration
            obs.event("resume", iteration=start_pairs,
                      rounds=start_rounds,
                      format_version=st.format_version,
                      ooc_mesh=True)

    f_g = jax.device_put(jnp.asarray(f_np0), shard_s)
    alpha_g = jax.device_put(jnp.asarray(a_np0), shard_s)
    err_g = (jax.device_put(jnp.asarray(e_np0), shard_s)
             if e_np0 is not None else None)

    eps_run = _BUDGET_EPS if config.budget_mode else float(config.epsilon)
    max_iter = int(config.max_iter)
    sub_kw = dict(kp=kp, c=c, eps=eps_run, tau=float(config.tau),
                  inner_iters=inner, inner_impl=inner_impl,
                  interpret=interpret, selection=config.selection,
                  pair_batch=int(config.pair_batch))

    jax.block_until_ready((x_sq, k_diag, f_g, alpha_g))
    phase_seconds = {"setup": time.perf_counter() - t_entry,
                     "solve": 0.0, "observe": 0.0, "finalize": 0.0}

    from dpsvm_tpu.utils.checkpoint import PeriodicCheckpointer

    ckpt = PeriodicCheckpointer(checkpoint_path, config, start_pairs)
    pairs = start_pairs
    rounds = start_rounds
    dispatches = 0
    tiles_streamed = 0
    bytes_h2d = 0
    b_hi = float("-inf")
    b_lo = float("inf")
    converged = False
    train_seconds = 0.0

    if obs.live:
        c_tiles = obs.registry.counter("solve.ooc_tiles_total")
        c_bytes = obs.registry.counter("solve.ooc_tile_bytes_total")

    while True:
        _sp = obs.span("solver/ooc_round")
        _sp.__enter__()
        try:
            t0 = time.perf_counter()
            round_tiles = 0
            dispatches += 1
            faults.device_fault("dispatch",
                                f"ooc mesh round {rounds + 1}")
            if err_g is not None:
                w_d, ok_d, bh_d, bl_d, scal_d = programs["select"](
                    f_g, err_g, alpha_g, y_g, x_sq, k_diag, valid_g)
            else:
                w_d, ok_d, bh_d, bl_d, scal_d = programs["select"](
                    f_g, alpha_g, y_g, x_sq, k_diag, valid_g)
            b_hi = float(np.asarray(bh_d))
            b_lo = float(np.asarray(bl_d))
            b_hi, b_lo = faults.poison_obs(b_hi, b_lo)
            check_obs_finite(b_hi, b_lo, pairs, "ooc")
            converged = not (b_lo > b_hi + 2.0 * eps_run)
            if converged or pairs >= max_iter:
                round_dt = time.perf_counter() - t0
                train_seconds += round_dt
                break
            # Host-gather the working-set rows by GLOBAL id (exactly q
            # rows read from host X) and replicate them — the fold and
            # subproblem read them whole on every device.
            w_np = np.clip(np.asarray(w_d), 0, n - 1)
            qx = jax.device_put(
                jnp.asarray(np.ascontiguousarray(
                    np.asarray(x[w_np], np.float32)), dtype), rep_s)
            dispatches += 1
            a_w, coef, t_d, qsq = _ooc_mesh_subproblem(
                qx, ok_d, scal_d, bh_d, bl_d,
                jnp.int32(max_iter - pairs), **sub_kw)
            # Double-buffered mesh stream: step j+1's sharded put is
            # issued before step j's fold dispatch — one H2D DMA per
            # step feeding all P devices, each folding only its own
            # rows (zero collectives; the budget pins it).
            nxt = _put_block(x, 0, tile, n, d, n_loc, n_dev, dtype,
                             mesh)
            for j in range(tiles_loc):
                cur, nxt = nxt, (
                    _put_block(x, j + 1, tile, n, d, n_loc, n_dev,
                               dtype, mesh)
                    if j + 1 < tiles_loc else None)
                dispatches += 1
                if err_g is not None:
                    f_g, err_g = programs["fold"](
                        cur, x_sq, f_g, err_g, qx, qsq, coef,
                        jnp.int32(j))
                else:
                    f_g = programs["fold"](cur, x_sq, f_g, qx, qsq,
                                           coef, jnp.int32(j))
            dispatches += 1
            alpha_g = programs["scatter"](alpha_g, w_d, ok_d, a_w)
            pairs += int(np.asarray(t_d))
            rounds += 1
            round_tiles = tiles_loc * n_dev
            tiles_streamed += round_tiles
            bytes_h2d += round_tiles * tile_bytes
            round_dt = time.perf_counter() - t0
            train_seconds += round_dt
        finally:
            _sp.__exit__(None, None, None)

        t_obs0 = time.perf_counter()
        obs.chunk(pairs=pairs, b_hi=b_hi, b_lo=b_lo,
                  device_seconds=round_dt, dispatch=dispatches,
                  tiles=round_tiles)
        if obs.live:
            c_tiles.add(round_tiles)
            c_bytes.add(tile_bytes * round_tiles)
        abort = False
        if callback is not None:
            state = OocState(alpha_g, f_g, b_hi, b_lo, pairs, rounds, 0)
            abort = bool(callback(pairs, b_hi, b_lo, state))
        if config.check_numerics:
            from dpsvm_tpu.solver.smo import assert_finite_state
            assert_finite_state(OocState(alpha_g, f_g, b_hi, b_lo,
                                         pairs, rounds, 0),
                                pairs, "ooc")
        if ckpt.due(pairs) or (abort and ckpt.active):
            # Same v2 files as the single-chip stream: the sharded
            # carry gathers to host here, so checkpoints stay
            # backend-portable and the mesh resume is bitwise.
            ckpt.save(pairs, np.asarray(alpha_g)[:n],
                      np.asarray(f_g)[:n], b_hi, b_lo, force=True,
                      f_err=(np.asarray(err_g)[:n]
                             if err_g is not None else None),
                      rounds=rounds)
        if config.verbose:
            print(f"[ooc-mesh] round={rounds} pairs={pairs} "
                  f"gap={b_lo - b_hi:.6f} tiles={round_tiles} "
                  f"devices={n_dev}")
        phase_seconds["observe"] += time.perf_counter() - t_obs0
        if abort:
            break

    t_fin0 = time.perf_counter()
    alpha_np = np.asarray(alpha_g)[:n]
    f_eff = f_g if err_g is None else f_g - err_g
    f_final = np.asarray(f_eff)[:n]
    if not converged:
        b_hi, b_lo, converged = refresh_extrema_host(
            f_final, alpha_np, y_np, c, config.epsilon,
            rule=config.selection)
    phase_seconds["solve"] = train_seconds
    phase_seconds["finalize"] = time.perf_counter() - t_fin0
    phase_seconds = {k: round(v, 6) for k, v in phase_seconds.items()}
    stats = {
        "f": f_final,
        "outer_rounds": rounds,
        "ooc": True,
        "ooc_mesh": True,
        "ooc_devices": n_dev,
        "ooc_tile_rows": tile,
        "tiles_streamed": tiles_streamed,
        "tile_bytes_h2d": bytes_h2d,
        "cached_rounds": 0,
        "cache_hits": 0,
        "cache_lookups": 0,
        "cache_hit_rate": 0.0,
        "cache_evictions": 0,
        "phase_seconds": phase_seconds,
        "ooc_shrink": False,
    }
    if resumed_from is not None:
        stats["resumed_from"] = resumed_from
    if obs.live:
        stats["obs_run_id"] = obs.run_id
        stats["obs_runlog"] = obs.path
    obs.finish(iterations=pairs, converged=bool(converged),
               train_seconds=round(train_seconds, 6),
               dispatches=dispatches, b_hi=b_hi, b_lo=b_lo,
               n_sv=int(np.count_nonzero(alpha_np > 0)),
               tiles_streamed=tiles_streamed,
               tile_bytes_h2d=bytes_h2d,
               ooc_mesh=True, devices=n_dev,
               phase_seconds=phase_seconds)
    return SolveResult(
        alpha=alpha_np,
        b=float((b_lo + b_hi) / 2.0),
        b_hi=b_hi,
        b_lo=b_lo,
        iterations=pairs,
        converged=converged,
        train_seconds=train_seconds,
        dispatches=dispatches,
        stats=stats,
    )
