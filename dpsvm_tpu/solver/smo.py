"""Single-chip jitted SMO engine.

TPU-native re-design of class SvmTrain (svmTrain.h:48-140, svmTrain.cu):
the reference runs each SMO iteration as a host-driven sequence of GPU
launches (classify for_each, min/max reduce, cublas sgemv, f-update
for_each) with a device->host sync every iteration (svmTrain.cu:469-499,
svmTrainMain.cpp:235-310). Here the ENTIRE iteration — selection, kernel
rows (with HBM cache), alpha-pair algebra and f update — is one
``lax.while_loop`` body compiled once by XLA; the host only observes state
between chunks of ``config.chunk_iters`` iterations (for convergence
reporting, metrics and checkpointing; SURVEY.md section 7.3 item 6).
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from dpsvm_tpu.config import SVMConfig
from dpsvm_tpu.ops.kernels import KernelParams, kernel_from_dots, row_dots, squared_norms
from dpsvm_tpu.ops.select import select_working_set
from dpsvm_tpu.solver.cache import CacheState, init_cache, lookup_pair
from dpsvm_tpu.solver.result import SolveResult


class SMOState(NamedTuple):
    """while_loop carry. Mirrors SvmTrain's device-resident solver state
    (g_alpha/g_f, svmTrain.cu:349,380) plus convergence scalars and the
    kernel-row cache."""

    alpha: jax.Array  # (n,) float32
    f: jax.Array  # (n,) float32, f_i = sum_j a_j y_j K_ij - y_i
    b_hi: jax.Array  # float32
    b_lo: jax.Array  # float32
    it: jax.Array  # int32
    cache: CacheState
    hits: jax.Array  # int32 cache-hit count (observability, SURVEY 5.5)


def init_state(n: int, y: jax.Array, cache_lines: int) -> SMOState:
    return SMOState(
        alpha=jnp.zeros((n,), jnp.float32),
        f=(-y).astype(jnp.float32),  # f = -y at alpha = 0 (svmTrain.cu:380)
        b_hi=jnp.float32(-jnp.inf),
        b_lo=jnp.float32(jnp.inf),  # do-while: first chunk always enters
        it=jnp.int32(0),
        cache=init_cache(cache_lines, n),
        hits=jnp.int32(0),
    )


def _smo_iteration(x, y, x_sq, valid, state: SMOState, kp: KernelParams,
                   c: float, tau: float, use_cache: bool) -> SMOState:
    """One modified-SMO iteration (the body of the compiled loop)."""
    i_hi, b_hi, i_lo, b_lo = select_working_set(state.f, state.alpha, y, c, valid)

    q_hi = lax.dynamic_index_in_dim(x, i_hi, 0, keepdims=False)
    q_lo = lax.dynamic_index_in_dim(x, i_lo, 0, keepdims=False)
    if use_cache:
        d_hi, d_lo, cache, n_hits = lookup_pair(
            state.cache, x, i_hi, i_lo, q_hi, q_lo, state.it)
    else:
        d2 = row_dots(x, jnp.stack([q_hi, q_lo]))
        d_hi, d_lo, cache, n_hits = d2[0], d2[1], state.cache, jnp.int32(0)

    k_hi = kernel_from_dots(d_hi, x_sq, x_sq[i_hi], kp)
    k_lo = kernel_from_dots(d_lo, x_sq, x_sq[i_lo], kp)

    # eta = K(hi,hi) + K(lo,lo) - 2 K(hi,lo), clamped (fixes bug B2; the
    # reference divides unguarded at svmTrainMain.cpp:290).
    eta = jnp.maximum(k_hi[i_hi] + k_lo[i_lo] - 2.0 * k_hi[i_lo], tau)

    y_hi = y[i_hi].astype(jnp.float32)
    y_lo = y[i_lo].astype(jnp.float32)
    a_hi_old = state.alpha[i_hi]
    a_lo_old = state.alpha[i_lo]
    # Pair update + clip (svmTrainMain.cpp:285-299).
    a_lo_new = jnp.clip(a_lo_old + y_lo * (b_hi - b_lo) / eta, 0.0, c)
    a_hi_new = jnp.clip(a_hi_old + y_lo * y_hi * (a_lo_old - a_lo_new), 0.0, c)
    alpha = state.alpha.at[i_lo].set(a_lo_new).at[i_hi].set(a_hi_new)

    # Rank-2 gradient update (update_functor, svmTrain.cu:98-137).
    f = state.f + (a_hi_new - a_hi_old) * y_hi * k_hi \
                + (a_lo_new - a_lo_old) * y_lo * k_lo

    return SMOState(alpha, f, b_hi, b_lo, state.it + 1, cache, state.hits + n_hits)


@partial(jax.jit, static_argnames=("kp", "c", "eps", "tau", "chunk", "use_cache"))
def _run_chunk(x, y, x_sq, valid, state: SMOState, max_iter,
               kp: KernelParams, c: float, eps: float, tau: float,
               chunk: int, use_cache: bool) -> SMOState:
    """Run up to `chunk` SMO iterations fully on device."""
    end = jnp.minimum(state.it + chunk, max_iter)

    def cond(st: SMOState):
        return (st.it < end) & (st.b_lo > st.b_hi + 2.0 * eps)

    def body(st: SMOState):
        return _smo_iteration(x, y, x_sq, valid, st, kp, c, tau, use_cache)

    return lax.while_loop(cond, body, state)


def solve(
    x,
    y,
    config: SVMConfig,
    callback=None,
    device: Optional[jax.Device] = None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> SolveResult:
    """Train binary C-SVC on one chip. Returns SolveResult.

    `callback(iter, b_hi, b_lo, state)`, when given, fires once per chunk —
    the structured-progress hook the reference lacks (its per-iteration
    print is commented out, svmTrainMain.cpp:237-239).

    With `checkpoint_path` and config.checkpoint_every > 0, solver state
    (alpha, f, iteration) is persisted periodically; `resume=True` restarts
    from the file if present (a capability gap in the reference — SURVEY.md
    section 5.3: an MPI rank death loses the whole run).
    """
    import numpy as np

    x = np.asarray(x, np.float32)
    y_np = np.asarray(y, np.int32)
    n, d = x.shape
    gamma = config.resolve_gamma(d)
    kp = KernelParams(config.kernel, gamma, config.degree, config.coef0)
    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32

    if device is None:
        device = jax.devices()[0]
    x_dev = jax.device_put(jnp.asarray(x, dtype), device)
    y_dev = jax.device_put(jnp.asarray(y_np, jnp.float32), device)
    x_sq = jax.jit(squared_norms)(x_dev)

    from dpsvm_tpu.utils.checkpoint import PeriodicCheckpointer, resume_solver_state

    cache_lines = min(config.cache_lines, n)
    use_cache = cache_lines > 0
    state = init_state(n, y_dev, cache_lines if use_cache else 1)
    if resume:
        restored = resume_solver_state(checkpoint_path, config, n)
        if restored is not None:
            a0, f0, it0, bh0, bl0 = restored
            state = state._replace(
                alpha=jnp.asarray(a0), f=jnp.asarray(f0),
                b_hi=jnp.float32(bh0), b_lo=jnp.float32(bl0),
                it=jnp.int32(it0))
    state = jax.device_put(state, device)
    max_iter = jnp.int32(config.max_iter)
    start_iter = int(state.it)
    ckpt = PeriodicCheckpointer(checkpoint_path, config, start_iter)

    t0 = time.perf_counter()
    while True:
        state = _run_chunk(x_dev, y_dev, x_sq, None, state, max_iter,
                           kp, float(config.c), float(config.epsilon),
                           float(config.tau), int(config.chunk_iters), use_cache)
        it = int(state.it)
        b_hi = float(state.b_hi)
        b_lo = float(state.b_lo)
        converged = not (b_lo > b_hi + 2.0 * config.epsilon)
        if callback is not None:
            callback(it, b_hi, b_lo, state)
        ckpt.maybe_save(it, state.alpha, state.f, b_hi, b_lo)
        if config.verbose:
            gap = b_lo - b_hi
            print(f"[smo] iter={it} b_lo-b_hi={gap:.6f} "
                  f"hits={int(state.hits)}")
        if converged or it >= config.max_iter:
            break
    train_seconds = time.perf_counter() - t0

    alpha = np.asarray(state.alpha)
    # Hit-rate denominator covers only THIS run's lookups (post-resume).
    total_lookups = 2 * (it - start_iter) if use_cache else 0
    return SolveResult(
        alpha=alpha,
        b=float((b_lo + b_hi) / 2.0),  # svmTrainMain.cpp:329
        b_hi=b_hi,
        b_lo=b_lo,
        iterations=it,
        converged=converged,
        train_seconds=train_seconds,
        stats={
            "cache_hits": int(state.hits),
            "cache_lookups": total_lookups,
            "cache_hit_rate": (int(state.hits) / total_lookups) if total_lookups else 0.0,
            "f": np.asarray(state.f),
        },
    )
